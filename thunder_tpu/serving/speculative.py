"""Speculative continuous batching: a draft/verify lane over the paged arena.

``tt.serve(..., speculative=SpecConfig(draft_params, draft_cfg, K=4))`` adds
a second, cheaper proposal model to the serving engine.  Each decode-lane
turn then runs TWO bucket programs instead of one:

- ``draft_decode`` — K autoregressive single-token forwards of the draft
  model, chained on-device (a ``lax.scan``, exactly the solo
  ``models.speculative._spec_step`` draft loop), reading and writing a
  **draft KV block arena** that sits beside the target arena: its own
  ``PagedKVPool`` storage with the same dtype/quantization/mesh sharding,
  but *sharing the target pool's block tables* — block ids are allocated
  once per request and index both arenas, so the allocator, free list, and
  prefix index stay single;
- ``verify`` / ``verify_paged`` — ONE target forward over the K+1 query
  positions ``[cur, d_1..d_K]``, the shared rejection rule from
  :func:`thunder_tpu.models.speculative.accept_tokens` (one implementation
  for solo and served paths — pinned by tests), and a keep-masked commit
  that writes only the accepted prefix's K/V into the target arena
  (rejected offsets sink-route; static shapes throughout, so the program
  set stays bounded by the same bucket accounting as plain decode).

Reproducibility contract (the whole point): per-request PRNG keys split
exactly like solo ``speculative_generate()`` at B=1 — one split per round
in the draft program (greedy), plus one acceptance split in verify under
temperature — and keys only advance at harvest, so served tokens are
**bit-identical** to the solo path, the KV arenas stay soft state, and
re-prefill recovery (which replays prompt + emitted tokens through
``spec_prefill_chunk``) rebuilds both arenas bit-identically: every
attended draft-arena slot ``p`` holds the draft K/V of the emitted token
``x_p`` (rejected-draft slots above the accepted prefix are rewritten
before the next attend), so the replay reproduces them exactly, greedy or
sampled.

Emission is variable-rate: a round emits ``n_emit ∈ [1, K+1]`` tokens per
row (accepted drafts + the resampled/bonus token), harvested in order
through the engine's normal ``_emit_token`` path — EOS/length finishes can
land mid-round, in which case the surplus tokens are dropped exactly like
solo's buffer trim.  The decode-state device chain carries ``(y, pos +
n_emit)`` so steady-state rounds cost zero host->device transfers, same as
plain decode.

This module holds the five bucket-program builders plus the dispatch and
harvest halves of the speculative decode lane; the engine owns state
(pools, scheduler, program cache, counters) and calls in.  No engine
import — the engine imports lazily from here.
"""
from __future__ import annotations

import time
from dataclasses import dataclass
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from thunder_tpu.models.generate import build_rope_cache, forward_with_cache
from thunder_tpu.models.speculative import accept_tokens
from thunder_tpu.serving.faults import FP_DRAFT, FP_SCATTER, FP_VERIFY
from thunder_tpu.serving.kv_pool import (
    SINK_BLOCK,
    gather_dense,
    scatter_blocks,
    scatter_token,
)
from thunder_tpu.serving.quant import (
    gather_dense_q,
    scatter_blocks_q,
    scatter_token_q,
)

__all__ = ["SpecConfig", "multi_step_supported", "validate_spec"]


@dataclass
class SpecConfig:
    """Speculative-serving knob for ``tt.serve``.

    ``draft_params``/``draft_cfg``: the small proposal model (must share
    the target's padded vocab; LoRA and custom forwards stay target-only).
    ``K``: drafted tokens per round — each round costs one K-step draft
    scan plus one (K+1)-position target verify, and emits 1..K+1 tokens.
    ``draft_kv_dtype``: storage dtype of the DRAFT arena only (``"int8"``,
    ``"fp8"``, or ``None`` to inherit the engine's ``kv_dtype``) — the
    draft cache only feeds proposals that the acceptance rule corrects
    against the target, so it tolerates aggressive quantization even when
    the target arena stays full-precision (and vice versa).
    """

    draft_params: Any
    draft_cfg: Any
    K: int = 4
    draft_kv_dtype: Any = None


def validate_spec(spec: SpecConfig, cfg, *, custom_forward: bool,
                  sliding_window) -> None:
    """Engine-construction validation: everything the key-chain mirroring
    and the K-token arena math require, checked before any allocation."""
    if not isinstance(spec, SpecConfig):
        raise TypeError(f"speculative= expects SpecConfig, got {type(spec).__name__}")
    if spec.K < 1:
        raise ValueError(f"SpecConfig.K must be >= 1, got {spec.K}")
    if spec.draft_cfg.padded_vocab_size != cfg.padded_vocab_size:
        raise ValueError(
            "speculative serving needs a shared tokenizer: draft "
            f"padded_vocab_size={spec.draft_cfg.padded_vocab_size} != target "
            f"{cfg.padded_vocab_size}"
        )
    if custom_forward:
        raise ValueError(
            "speculative serving requires the in-tree forward "
            "(model_fn=None): the draft/verify programs mirror the solo "
            "speculative_generate() key chain, which a custom forward "
            "cannot guarantee"
        )
    if sliding_window is not None or getattr(cfg, "sliding_window", None) \
            or getattr(spec.draft_cfg, "sliding_window", None):
        raise ValueError(
            "speculative serving does not support sliding-window engines: "
            "window expiry would invalidate the K-token draft/verify arena "
            "math (solo speculative_generate has the same restriction)"
        )


def multi_step_supported(spec: SpecConfig) -> tuple[bool, str | None]:
    """Whether the speculative lane can chain draft+verify rounds behind
    ``decode_steps=N`` (the in-program multi-step scan).

    Currently always ``(False, reason)``: a spec round emits a
    *data-dependent* 1..K+1 tokens, so N rounds inside one program would
    need ragged (N, K+1) outputs plus an in-program replay of the
    harvest-side accounting (per-round acceptance histogram, key-chain
    mirroring against solo ``speculative_generate``, draft-arena trim on
    rejection) that today runs on the host between rounds.  The engine
    records this reason and rejects ``decode_steps>1`` with
    ``speculative=`` at construction rather than silently serving a
    different schedule — a spec round already amortizes the host visit
    over its accepted tokens, so the two knobs target the same overhead."""
    return False, (
        "a speculative round emits a data-dependent 1..K+1 tokens per host "
        "visit; chaining N rounds in-program needs ragged outputs and "
        "in-program acceptance accounting that currently lives on the host "
        "(the spec lane already amortizes host visits over accepted tokens)"
    )


#
# shared in-program pieces
#


def _gather(arenas, tables, qkv, cdtype):
    """Dense {k, v} cache view of ``tables``'s blocks (dequantizing when
    the pool is int8/fp8) — the same gather every plain bucket program
    opens with."""
    if qkv:
        kd, vd = gather_dense_q(
            arenas["k"], arenas["v"], arenas["k_scale"], arenas["v_scale"],
            tables, cdtype,
        )
    else:
        kd, vd = gather_dense(arenas["k"], arenas["v"], tables)
    return {"k": kd, "v": vd}


def _scatter_prefill(arenas, cache, dest, qkv):
    """Block-granular prefill writeback (quantize-on-scatter when the pool
    stores int8/fp8); returns (arenas, measured quantization error)."""
    if qkv:
        k_arena, k_scale, k_err = scatter_blocks_q(
            arenas["k"], arenas["k_scale"], cache["k"], dest)
        v_arena, v_scale, v_err = scatter_blocks_q(
            arenas["v"], arenas["v_scale"], cache["v"], dest)
        return ({"k": k_arena, "v": v_arena, "k_scale": k_scale, "v_scale": v_scale},
                0.5 * (k_err + v_err))
    return ({"k": scatter_blocks(arenas["k"], cache["k"], dest),
             "v": scatter_blocks(arenas["v"], cache["v"], dest)},
            jnp.float32(0.0))


def _scatter_at(arenas, kc, vc, p_k, db, ds, qkv):
    """Commits one offset's per-row K/V (picked from the dense cache at
    position ``p_k``) into the arena at (block ``db``, slot ``ds``)."""
    pick = jax.vmap(
        lambda c, p: jax.lax.dynamic_index_in_dim(c, p, axis=2, keepdims=False))
    if qkv:
        k_arena, k_scale = scatter_token_q(
            arenas["k"], arenas["k_scale"], pick(kc, p_k), db, ds)
        v_arena, v_scale = scatter_token_q(
            arenas["v"], arenas["v_scale"], pick(vc, p_k), db, ds)
        return {"k": k_arena, "v": v_arena, "k_scale": k_scale, "v_scale": v_scale}
    return {"k": scatter_token(arenas["k"], pick(kc, p_k), db, ds),
            "v": scatter_token(arenas["v"], pick(vc, p_k), db, ds)}


def _acceptance(tlogits, drafts, q_rows, keys, temp, K):
    """The shared rejection rule, vectorized per row with per-request key
    chains.  Greedy: accept drafts while they match the target's argmax
    (no key split — solo's greedy round splits once, in the draft half).
    Temperature: one more per-row split, then
    :func:`~thunder_tpu.models.speculative.accept_tokens` at B=1 — the
    ``split(k, 1)[0]`` inner split reproduces solo's
    ``vmap(accept_tokens)(split(ka, B), ...)`` draw exactly.

    Returns ``(emitted (B, K+1), n_emit (B,), y (B,), new_keys)`` —
    ``emitted[:, :n_emit]`` are the round's tokens, the tail is garbage
    masked by ``n_emit`` (solo's fixed-shape emission rule verbatim)."""
    B = drafts.shape[0]
    if temp == 0.0:
        tgt = jnp.argmax(tlogits, axis=-1).astype(jnp.int32)   # (B, K+1)
        match = drafts == tgt[:, :K]
        m = jnp.argmin(
            jnp.concatenate([match, jnp.zeros((B, 1), bool)], axis=1).astype(jnp.int32),
            axis=1,
        )
        y = jnp.take_along_axis(tgt, m[:, None], axis=1)[:, 0]
        new_keys = keys
    else:
        p_all = jax.nn.softmax(tlogits / temp, axis=-1)        # (B, K+1, V)
        sp = jax.vmap(jax.random.split)(keys)
        new_keys, kas = sp[:, 0], sp[:, 1]
        m, y = jax.vmap(
            lambda k, d, p, q: accept_tokens(jax.random.split(k, 1)[0], d, p, q)
        )(kas, drafts, p_all, q_rows)
    n_emit = m + 1
    iota = jnp.arange(K + 1)[None, :]
    emitted = jnp.where(
        iota < m[:, None],
        jnp.concatenate([drafts, jnp.zeros((B, 1), jnp.int32)], axis=1),
        y[:, None],
    )
    return emitted, n_emit, y, new_keys


#
# bucket-program builders (called from ServingEngine._program)
#


def build_spec_prefill(eng, Tb: int, nbb: int):
    """The speculative twin of ``_build_prefill``: one extra draft forward
    writes the prompt's draft K/V through the SAME chunk-granular dest
    table (shared block ids), and the first-token draw mirrors solo
    ``speculative_generate``'s ``decode_all`` entry — one key split always,
    then argmax (greedy) or a ``split(kf, 1)`` categorical (temperature) —
    NOT the plain engine's ``sample_token``, whose key use differs."""
    cfg, dcfg = eng.cfg, eng.spec.draft_cfg
    temp, quantized = eng.temperature, eng.quantized
    qkv = eng.pool.quantized_kv
    dqkv = eng.draft_pool.quantized_kv
    cdtype = jnp.dtype(eng.pool.dtype)
    ddtype = jnp.dtype(eng.draft_pool.dtype)
    cap = eng.pool.capacity_tokens(nbb)
    cos, sin = build_rope_cache(cfg, cap)
    cos_d, sin_d = build_rope_cache(dcfg, cap)

    @partial(jax.jit, donate_argnums=(5, 6), **eng._jit_kwargs("spec_prefill"))
    def spec_prefill(params, dparams, toks, pos, n_real, arenas, darenas,
                     table, dest, key, lora, slot):
        dense = _gather(arenas, table[None, :], qkv, cdtype)
        logits, cache = forward_with_cache(
            params, toks, pos, dense, cos, sin, cfg,
            **eng._fwd_kwargs(lora, slot),
        )
        # LoRA rides the target only (solo contract): the draft is a cheap
        # base proposal and the acceptance rule corrects any q/p mismatch
        ddense = _gather(darenas, table[None, :], dqkv, ddtype)
        _dlogits, dcache = forward_with_cache(
            dparams, toks, pos, ddense, cos_d, sin_d, dcfg, quantized=quantized)
        last = jax.lax.dynamic_index_in_dim(logits, n_real - 1, axis=1,
                                            keepdims=False)     # (1, V)
        key, kf = jax.random.split(key)
        if temp == 0.0:
            tok = jnp.argmax(last, axis=-1).astype(jnp.int32)
        else:
            tok = jax.vmap(jax.random.categorical)(
                jax.random.split(kf, 1), last / temp).astype(jnp.int32)
        arenas, qerr = _scatter_prefill(arenas, cache, dest, qkv)
        darenas, _dqerr = _scatter_prefill(darenas, dcache, dest, dqkv)
        return tok, arenas, darenas, key, qerr

    return spec_prefill


def build_spec_prefill_chunk(eng, Tb: int, nbb: int):
    """Intermediate chunk piece with the draft forward alongside: KV into
    both arenas, no sampling, no key split (the final ``spec_prefill``
    piece does both) — also the replay program for re-prefill recovery,
    which rebuilds BOTH arenas bit-identically (every attended draft slot
    holds the draft K/V of the emitted token at that position)."""
    cfg, dcfg = eng.cfg, eng.spec.draft_cfg
    quantized = eng.quantized
    qkv = eng.pool.quantized_kv
    dqkv = eng.draft_pool.quantized_kv
    cdtype = jnp.dtype(eng.pool.dtype)
    ddtype = jnp.dtype(eng.draft_pool.dtype)
    cap = eng.pool.capacity_tokens(nbb)
    cos, sin = build_rope_cache(cfg, cap)
    cos_d, sin_d = build_rope_cache(dcfg, cap)

    @partial(jax.jit, donate_argnums=(4, 5), **eng._jit_kwargs("spec_prefill_chunk"))
    def spec_prefill_chunk(params, dparams, toks, pos, arenas, darenas,
                           table, dest, lora, slot):
        dense = _gather(arenas, table[None, :], qkv, cdtype)
        _logits, cache = forward_with_cache(
            params, toks, pos, dense, cos, sin, cfg,
            **eng._fwd_kwargs(lora, slot),
        )
        ddense = _gather(darenas, table[None, :], dqkv, ddtype)
        _dlogits, dcache = forward_with_cache(
            dparams, toks, pos, ddense, cos_d, sin_d, dcfg, quantized=quantized)
        arenas, qerr = _scatter_prefill(arenas, cache, dest, qkv)
        darenas, _dqerr = _scatter_prefill(darenas, dcache, dest, dqkv)
        return arenas, darenas, qerr

    return spec_prefill_chunk


def build_draft_decode(eng, Bb: int, nbb: int):
    """K+1 chained single-token draft forwards as one bucket program (the
    solo ``_spec_step`` draft scan over the gathered draft-arena view).

    Key chain per row: ``keys -> split -> (keys_mid, kd)``, ``kd -> K+1``
    iteration keys; a temperature draw at iteration i is
    ``categorical(split(dks[i], 1)[0], rows / T)`` — bit-equal to solo's
    ``vmap(categorical)(split(kk, B), rows / T)`` at B=1.  Greedy rounds
    split once and never draw, exactly like solo.

    All K+1 fresh draft K/V land in the draft arena unconditionally (no
    acceptance mask): solo's draft cache does the same, and slots above the
    accepted prefix are rewritten before the next attend (write-before-
    attend + the ``j <= qpos`` keep mask), so stale tails are unreachable.
    """
    dcfg = eng.spec.draft_cfg
    K, temp, quantized = eng.spec.K, eng.temperature, eng.quantized
    qkv = eng.draft_pool.quantized_kv
    cdtype = jnp.dtype(eng.draft_pool.dtype)
    bs = eng.draft_pool.block_size
    cap = eng.draft_pool.capacity_tokens(nbb)
    cos_d, sin_d = build_rope_cache(dcfg, cap)

    @partial(jax.jit, donate_argnums=(4,), **eng._jit_kwargs("draft_decode"))
    def draft_decode(dparams, toks, pos, tables, darenas, keys):
        dc = _gather(darenas, tables, qkv, cdtype)
        sp = jax.vmap(jax.random.split)(keys)          # per-request key chains
        keys_mid, kds = sp[:, 0], sp[:, 1]
        dks = jax.vmap(lambda k: jax.random.split(k, K + 1))(kds)
        dks = dks.transpose(1, 0, 2)                   # (K+1, B, 2) scan xs

        def dbody(carry, kk):
            tok, dpos, dc = carry
            dlogits, dc = forward_with_cache(
                dparams, tok[:, None], dpos, dc, cos_d, sin_d, dcfg,
                quantized=quantized,
            )
            rows = dlogits[:, -1]                      # (B, V)
            if temp == 0.0:
                nxt = jnp.argmax(rows, axis=-1).astype(jnp.int32)
                qrows = rows                           # unused in the greedy path
            else:
                qrows = jax.nn.softmax(rows / temp, axis=-1)
                nxt = jax.vmap(
                    lambda k, r: jax.random.categorical(
                        jax.random.split(k, 1)[0], r / temp)
                )(kk, rows).astype(jnp.int32)
            return (nxt, dpos + 1, dc), (nxt, qrows)

        (_, _, dc2), (drafts_x, q_rows_x) = jax.lax.scan(
            dbody, (toks, pos, dc), dks)
        drafts = drafts_x[:K].transpose(1, 0)          # (B, K)
        q_rows = q_rows_x[:K].transpose(1, 0, 2)       # (B, K, V)
        kc = dc2["k"].transpose(1, 0, 2, 3, 4)         # (B, L, ng, cap, hs)
        vc = dc2["v"].transpose(1, 0, 2, 3, 4)
        for k in range(K + 1):
            p_k = pos + k
            db = jnp.take_along_axis(tables, (p_k // bs)[:, None], axis=1)[:, 0]
            darenas = _scatter_at(darenas, kc, vc, p_k, db, p_k % bs, qkv)
        return drafts, q_rows, keys_mid, darenas

    return draft_decode


def build_verify(eng, Bb: int, nbb: int):
    """ONE target forward over the K+1 chunk ``[cur, d_1..d_K]`` (per-row
    vector positions; the dense gathered view + the ``j <= qpos`` keep
    mask exactly reproduce solo's cache semantics), the shared rejection
    rule, and a keep-masked commit: offset k's fresh K/V lands at
    ``pos + k`` iff ``k < n_emit``, else it sink-routes — the target arena
    only ever holds committed tokens' K/V."""
    cfg = eng.cfg
    K, temp = eng.spec.K, eng.temperature
    qkv = eng.pool.quantized_kv
    cdtype = jnp.dtype(eng.pool.dtype)
    bs = eng.pool.block_size
    cap = eng.pool.capacity_tokens(nbb)
    cos, sin = build_rope_cache(cfg, cap)

    @partial(jax.jit, donate_argnums=(4,), **eng._jit_kwargs("verify"))
    def verify(params, toks, pos, tables, arenas, drafts, q_rows, keys,
               lora, slots):
        chunk = jnp.concatenate([toks[:, None], drafts], axis=1)  # (B, K+1)
        dense = _gather(arenas, tables, qkv, cdtype)
        tlogits, cache = forward_with_cache(
            params, chunk, pos, dense, cos, sin, cfg,
            **eng._fwd_kwargs(lora, slots),
        )
        emitted, n_emit, y, new_keys = _acceptance(
            tlogits, drafts, q_rows, keys, temp, K)
        kc = cache["k"].transpose(1, 0, 2, 3, 4)
        vc = cache["v"].transpose(1, 0, 2, 3, 4)
        for k in range(K + 1):
            p_k = pos + k
            live = k < n_emit
            db = jnp.where(
                live,
                jnp.take_along_axis(tables, (p_k // bs)[:, None], axis=1)[:, 0],
                SINK_BLOCK,
            )
            ds = jnp.where(live, p_k % bs, 0)
            arenas = _scatter_at(arenas, kc, vc, p_k, db, ds, qkv)
        return emitted, n_emit, y, new_keys, pos + n_emit, arenas

    return verify


def build_verify_paged(eng, Bb: int, nbb: int):
    """The kernel twin of :func:`build_verify`: same signature, same
    acceptance math, same returns — attention runs the multi-token-query
    Pallas paged kernel straight off the arenas (q_len K+1, causal
    intra-chunk mask inside the online softmax) and the accepted prefix
    commits through the keep-masked write kernel, so the compiled program
    touches the arenas with zero gather/scatter primitives (jaxpr-asserted
    by tests, with the gather ``verify`` as the positive control)."""
    from thunder_tpu.serving.paged_attention import (
        forward_paged,
        write_fresh_kv_masked,
    )

    cfg = eng.cfg
    K, temp = eng.spec.K, eng.temperature
    qkv = eng.pool.quantized_kv
    cdtype = jnp.dtype(eng.pool.dtype)
    kv_dtype = jnp.dtype(eng.pool.kv_dtype) if qkv else None
    bs = eng.pool.block_size
    cap = eng.pool.capacity_tokens(nbb)
    cos, sin = build_rope_cache(cfg, cap)
    mesh = eng.mesh

    @partial(jax.jit, donate_argnums=(4,), **eng._jit_kwargs("verify_paged"))
    def verify_paged(params, toks, pos, tables, arenas, drafts, q_rows, keys,
                     lora, slots):
        chunk = jnp.concatenate([toks[:, None], drafts], axis=1)  # (B, K+1)
        logits, fresh = forward_paged(
            params, chunk, pos, arenas, tables, cos, sin, cfg,
            cdtype=cdtype, mesh=mesh, lora_fused=True,
            **eng._fwd_kwargs(lora, slots),
        )
        emitted, n_emit, y, new_keys = _acceptance(
            logits, drafts, q_rows, keys, temp, K)
        arenas = write_fresh_kv_masked(
            arenas, fresh, tables, pos, n_emit, block_size=bs,
            kv_dtype=kv_dtype, mesh=mesh,
        )
        return emitted, n_emit, y, new_keys, pos + n_emit, arenas

    return verify_paged


#
# the speculative decode lane (dispatch/harvest halves, engine calls in)
#


def spec_decode_dispatch(eng) -> dict:
    """One speculative round for the decode-ready batch: draft program →
    verify program, chained on-device through ``eng._spec_state`` exactly
    like plain decode's ``_decode_state`` (steady state moves zero bytes
    host->device; the carried ``toks``/``pos`` are the previous round's
    ``y``/``pos + n_emit``).  ``host_pos`` advances at HARVEST (the round's
    ``n_emit`` is device-side until then), so dispatch reads it as-is."""
    sch, pool, dpool = eng.scheduler, eng.pool, eng.draft_pool
    K = eng.spec.K
    running = (sch.decode_ready() if eng.async_step
               else list(sch.running))                 # FIFO admission order
    eng._fault_point(FP_DRAFT, tuple(r.rid for r in running))
    Bb, _nbb_raw = sch.decode_bucket(running)
    nbb = eng._nbb(_nbb_raw)
    sig = (tuple(r.rid for r in running), Bb, nbb)
    st = eng._spec_state
    if st is not None and st["sig"] == sig:
        toks_d, pos_d = st["toks"], st["pos"]
        tables_d, keys_d, slots_d = st["tables"], st["keys"], st["slots"]
        host_pos = st["host_pos"]
    else:
        toks = np.zeros(Bb, dtype=np.int32)
        host_pos = np.zeros(Bb, dtype=np.int32)
        tables = np.full((Bb, nbb), SINK_BLOCK, dtype=np.int32)
        keys = np.zeros((Bb, *np.shape(running[0].key)),
                        dtype=np.asarray(running[0].key).dtype)
        slots = np.zeros(Bb, dtype=np.int32)           # padding rows: base slot
        for i, r in enumerate(running):
            wpos = r.prompt_len + len(r.generated) - 1  # slot cur's K/V lands in
            toks[i] = r.generated[-1]
            host_pos[i] = wpos
            tables[i, : len(r.block_table)] = r.block_table
            keys[i] = r.key
            slots[i] = r.adapter_slot
        toks_d, pos_d = jnp.asarray(toks), jnp.asarray(host_pos)
        tables_d, keys_d = jnp.asarray(tables), jnp.asarray(keys)
        slots_d = jnp.asarray(slots)
    dprog, dcompiled = eng._program("draft_decode", Bb, nbb)
    drafts, q_rows, keys_mid, darenas = dprog(
        eng.spec.draft_params, toks_d, pos_d, tables_d, dpool.arenas, keys_d)
    dpool.set_arenas(darenas)
    # a fault HERE retries safely even though the draft arenas were donated:
    # the rerun recommits the same deterministic slots (this round's writes
    # depend only on history below pos, which the draft program never
    # touches), so the retried round stays bit-identical
    eng._fault_point(FP_VERIFY, tuple(r.rid for r in running))
    vkind = "verify_paged" if eng.attn == "paged" else "verify"
    vprog, vcompiled = eng._program(vkind, Bb, nbb)
    lora_arenas = eng._lora_arenas()
    if eng.mesh is not None and eng._mesh_collectives is None:
        # census BEFORE the call: the arenas are donated by it
        eng._mesh_collectives = eng._collective_census(
            (vkind, Bb, nbb), vprog,
            (eng.params, toks_d, pos_d, tables_d, pool.arenas,
             drafts, q_rows, keys_mid, lora_arenas, slots_d),
        )
    if eng.attn == "paged":
        eng.attn_kernel_steps += 1
        eng._m_attn_kernel.inc()
    elif eng._attn_requested == "auto":
        eng.attn_fallback_steps += 1
        eng._m_attn_fallback.inc()
    tr = eng._tracer
    if tr is not None:
        for r in running:
            tr.begin(r.rid, "decode", step=eng.decode_steps,
                     compile=dcompiled or vcompiled, bucket=[Bb, nbb],
                     lane="decode", attn=eng.attn, spec=True, K=K)
    emitted, n_emit, y, new_keys, new_pos, arenas = vprog(
        eng.params, toks_d, pos_d, tables_d, pool.arenas,
        drafts, q_rows, keys_mid, lora_arenas, slots_d,
    )
    # past the point of no return: the call consumed the donated arenas
    eng._fault_point(FP_SCATTER, tuple(r.rid for r in running))
    pool.set_arenas(arenas)
    eng._spec_state = {
        "sig": sig, "toks": y, "pos": new_pos, "tables": tables_d,
        "keys": new_keys, "slots": slots_d, "host_pos": host_pos,
    }
    rec = {"kind": "decode", "spec": True, "running": running,
           "emitted": emitted, "n_emit": n_emit, "new_keys": new_keys,
           "pos": host_pos, "bucket": [Bb, nbb], "vkind": vkind,
           "compiled": dcompiled or vcompiled, "step": eng.decode_steps,
           "t_disp": time.perf_counter(), "t_clock": sch.clock()}
    eng.decode_steps += 1
    eng.spec_rounds += 1
    eng._occupancy_sum += len(running)
    eng._m_steps_decode.inc()
    eng._m_spec_rounds.inc()
    eng._m_occupancy.observe(len(running))
    return rec


def spec_decode_harvest(eng, rec: dict) -> None:
    """Materializes one speculative round: per live row, advance the key
    chain and position by the row's own ``n_emit``, then emit the accepted
    prefix + correction token IN ORDER through ``_emit_token`` (EOS/length
    can finish the row mid-round — surplus tokens drop, like solo's
    buffer trim past ``max_new``).  Feeds the acceptance histogram
    (``serving.spec.accept_len``) and the accepted/drafted counters."""
    from thunder_tpu.serving.faults import FP_HARVEST

    sch = eng.scheduler
    running = rec["running"]
    eng._fault_point(FP_HARVEST, tuple(r.rid for r in running))
    t0 = time.perf_counter()
    emitted = np.asarray(rec["emitted"])               # the host block
    n_emit = np.asarray(rec["n_emit"])
    new_keys = np.asarray(rec["new_keys"])
    if eng.async_step:
        stall = time.perf_counter() - t0
        overlapped = t0 - rec["t_disp"]
        frac = overlapped / (overlapped + stall) if (overlapped + stall) > 0 else 0.0
        eng._stall_s_sum += stall
        eng._overlap_frac_sum += frac
        eng._overlap_obs += 1
        eng._m_stall.observe(stall)
        eng._m_overlap.set(frac)
    K = eng.spec.K
    gp, gtag = eng._goodput, None
    if gp is not None:
        # exact pre-emit classification of the round's device slots, two
        # dispatches per round.  Draft (Bb x K): accepted positions are
        # committed from the verifier's ne-1 (trim-independent, so the
        # ledger's acceptance integers reproduce spec_accepted_tokens /
        # spec_draft_tokens exactly); the rest were rejected.  Verify
        # (Bb x (K+1)): committed slots are the tokens that actually
        # stream; unused verify positions are draft_rejected; accepted-
        # but-trimmed (EOS/length mid-round) slots are dead scan rows.
        Bb = rec["bucket"][0]
        d_comm = d_rej = d_dead = 0
        v_comm = v_rej = v_dead = 0
        for i, r in enumerate(running):
            if r.state != "running":
                d_dead += K
                v_dead += K + 1
                continue
            ne = int(n_emit[i])
            d_comm += ne - 1
            d_rej += K - (ne - 1)
            streamed = min(ne, r.max_new_tokens - len(r.generated))
            if eng.eos_id is not None:
                for s in range(streamed):
                    if int(emitted[i, s]) == eng.eos_id:
                        streamed = s + 1
                        break
            v_comm += streamed
            v_rej += (K + 1) - ne
            v_dead += ne - streamed
        npad = Bb - len(running)
        gp.account("draft_decode", Bb, K, committed=d_comm,
                   **{k: v for k, v in (("pad_row", npad * K),
                                        ("draft_rejected", d_rej),
                                        ("dead_scan_row", d_dead)) if v})
        gtag = gp.account(rec["vkind"], Bb, K + 1, committed=v_comm,
                          **{k: v for k, v in (("pad_row", npad * (K + 1)),
                                               ("draft_rejected", v_rej),
                                               ("dead_scan_row", v_dead))
                             if v})
        # one wall interval covers both programs: split by their slot share
        dt = time.perf_counter() - rec["t_disp"]
        gp.note_device_s("draft_decode", dt * K / (2 * K + 1))
        gp.note_device_s(rec["vkind"], dt * (K + 1) / (2 * K + 1))
    tr = eng._tracer
    if tr is not None:                                 # tokens host-visible
        for r in running:
            tr.end(r.rid, "decode",
                   **({"goodput": gtag} if gtag is not None else {}))
    if eng._flight is not None:
        eng._flight.record("decode", step=rec["step"], batch=len(running),
                           bucket=rec["bucket"], compiled=rec["compiled"],
                           rids=[r.rid for r in running], spec=True,
                           accept_len=[int(n_emit[i]) for i in range(len(running))],
                           **({"goodput": gtag} if gtag is not None else {}))
    pos = rec["pos"]
    count = 0
    invalidate = False
    for i, r in enumerate(running):
        if r.state != "running":
            invalidate = True                          # finished mid-flight
            continue
        ne = int(n_emit[i])
        r.key = new_keys[i]
        r.pos = int(pos[i]) + ne
        eng._spec_accept_hist[ne - 1] += 1
        eng.spec_draft_tokens += K
        eng.spec_accepted_tokens += ne - 1
        eng._m_spec_accept_len.observe(ne)
        if ne > 1:
            eng._m_spec_accepted.inc(ne - 1)
        for k in range(ne):
            count += 1
            eng._emit_token(r, int(emitted[i, k]))
            if r.state != "running":
                # EOS/length landed mid-round: the remaining accepted
                # tokens were never promised — drop them (solo trims the
                # same overshoot off its fixed buffer)
                invalidate = True
                break
    if gp is not None:
        gp.commit_tokens(count)
    eng.tokens_generated += count
    eng.decode_lane_tokens += count
    eng.host_visits += 1
    eng._m_host_visits.inc()
    if count:
        eng._m_tokens.inc(count)
    if invalidate:
        # the chained round inputs assumed an unchanged batch/tables;
        # the next dispatch rebuilds from host state
        eng._spec_state = None
    else:
        st = eng._spec_state
        if st is not None:
            # the device chain already carries pos + n_emit; mirror it on
            # the host (a NEW array — rec["pos"] must keep dispatch's view)
            st["host_pos"] = st["host_pos"] + n_emit
