"""Data-parallel serving scale-out: replicated engine lanes behind one
prefix-affinity router.

Every serving layer so far made ONE engine faster; this module multiplies
lanes.  :class:`ReplicatedEngine` owns N independent
:class:`~thunder_tpu.serving.engine.ServingEngine` replicas — each with
its own paged KV arena, in-flight futures table, scheduler, and
program-cache entries keyed by its submesh fingerprint — and fronts them
with a single router that keeps the solo engine's public surface
(submit / stream / run / drain / shutdown / stats / evict).

**Device split.**  ``tt.serve(..., mesh=)`` on a mesh with a ``dp`` axis
splits the device set via :func:`~thunder_tpu.serving.mesh.split_mesh`:
each replica keeps every *other* axis of the parent (a ``(dp=2, tp=2)``
mesh yields two TP-2 engines), and a dp-only mesh degrades each slice to
a trivial single-device submesh.  ``replicas=N`` without a mesh runs N
lanes on the default device — the form the interleaved dp benchmark uses,
where the win is **shape segregation**, not device count: the router
co-locates request families, so each replica's decode runs at its own
narrow block-table bucket instead of every row paying the widest
request's gather width.

**Routing.**  The router owns the global FIFO queue and hands a request
to a replica lazily, only when that replica can admit it on its next
step (:meth:`~thunder_tpu.serving.scheduler.Scheduler.can_accept` — a
free batch slot AND enough uncommitted free blocks).  Placement order:

1. **resident affinity** — the replica whose live prefix index
   (:class:`~thunder_tpu.serving.kv_pool.PrefixIndex`, probed without
   mutation) holds the longest block-aligned prefix of the prompt;
2. **routing-history affinity** — a bounded LRU of block-aligned prompt
   prefixes → the replica they last routed to.  Burst submission means
   nothing is *resident* at routing time (prefills haven't run yet);
   the history map is what keeps a family of shared-prefix requests on
   one lane anyway;
3. **least-loaded** — among replicas that can admit now, the one with
   the most uncommitted free blocks (ties: fewest requests, lowest
   index).

When the affinity-preferred replica cannot admit *now*, the head WAITS
(strict global FIFO; nothing routes around it).  That is safe — submit
validates every request against one replica's full capacity, so the
head always becomes placeable — and it is what preserves segregation:
spilling a long-prefix request onto the short-request lane would drag
that lane's decode bucket up to the long row's width for everyone.

**Drive.**  :meth:`ReplicatedEngine.step` routes, then steps replicas in
rotating round-robin order (replica *i*'s host work overlaps replica
*j*'s device work — PR 9's overlap extended across lanes), then routes
again.  Faults stay replica-scoped: one replica's quarantine / retry /
re-prefill recovery happens inside ITS ``step()`` while the others keep
serving, and a stall names its culprit
(``EngineStalledError(..., replica=i)`` with that replica's flight
state).

**Multi-host.**  The router is host-local: run it on process 0 of a
``dist.multihost.hybrid_mesh`` whose DCN axis is ``dp`` (each submesh is
then one ICI-connected block); ``submit()`` on any other process raises.
Single-process serving — every replica's devices visible to one host —
is the documented fallback and the only mode exercised in CI.

Observability: ``serving.router.*`` (queue depth, routed / affinity-hit
counters, per-replica running gauges, imbalance gauge) beside each
replica's own ``serving.*`` metrics; ``stats()`` aggregates, flight
state nests per-replica snapshots, and routed requests get a
``router.routed`` span instant naming their lane.
"""
from __future__ import annotations

from collections import OrderedDict, deque
from typing import Any, Callable, Sequence

import jax
import numpy as np

from thunder_tpu.observability.goodput import fleet_goodput
from thunder_tpu.observability.metrics import registry
from thunder_tpu.serving.engine import (
    EngineStalledError,
    RequestResult,
    ServingEngine,
)
from thunder_tpu.serving.scheduler import (
    FINISH_DEADLINE,
    FINISH_EVICTED,
    AdmissionError,
)

__all__ = ["ReplicatedEngine", "RoutedHandle"]

# routing-history capacity: block-aligned prefix keys retained (LRU).
# 1024 keys at typical prompt lengths is a few hundred KB of host memory
# and covers far more concurrent request families than fit any arena
_HISTORY_CAP = 1024


class RoutedHandle:
    """Caller's view of a request submitted through the router.

    Mirrors :class:`~thunder_tpu.serving.engine.RequestHandle`: the
    request sits in the router's global queue (state ``"queued"``) until
    the router hands it to a replica, after which every accessor
    delegates to the replica-local handle.  ``replica`` is the lane index
    once routed (``None`` before)."""

    def __init__(self, router: "ReplicatedEngine", rid: int, prompt: np.ndarray,
                 submit_kwargs: dict, deadline_t: float | None, submit_t: float):
        self._router = router
        self._rid = rid
        self._prompt = prompt
        self._kwargs = submit_kwargs
        self._deadline_t = deadline_t
        self._submit_t = submit_t
        self._blocks = 0                 # full reservation, set at submit
        self._level = 1                  # priority level (normal) for queue order
        self._inner = None               # replica-local RequestHandle
        self.replica: int | None = None
        self._synthetic: RequestResult | None = None   # expired/evicted pre-route

    @property
    def rid(self) -> int:
        """Router-level request id (replica-local rids restart per lane)."""
        return self._rid

    @property
    def state(self) -> str:
        if self._synthetic is not None:
            return "finished"
        if self._inner is None:
            return "queued"
        return self._inner.state

    def done(self) -> bool:
        return self._synthetic is not None or (
            self._inner is not None and self._inner.done())

    def tokens_so_far(self) -> tuple[int, ...]:
        return () if self._inner is None else self._inner.tokens_so_far()

    def result(self, *, drive: bool = True) -> RequestResult:
        """The structured result; with ``drive`` (default) steps the whole
        replicated fleet until this request finishes."""
        while drive and not self.done():
            if not self._router.step() and not self.done():
                raise self._router._stall_error(
                    f"request {self._rid} still {self.state}")
        if self._synthetic is not None:
            return self._synthetic
        if not self.done():
            raise RuntimeError(f"request {self._rid} is still {self.state}")
        return self._inner.result(drive=False)


class ReplicatedEngine:
    """N engine lanes + the prefix-affinity router that owns admission."""

    def __init__(
        self,
        params,
        cfg,
        *,
        model_fn: Callable | None = None,
        replicas: int,
        mesh=None,
        fault_plans: Sequence | None = None,
        telemetry=None,
        **engine_kwargs,
    ):
        if replicas < 1:
            raise ValueError(f"replicas must be >= 1, got {replicas}")
        if "fault_plan" in engine_kwargs:
            raise ValueError(
                "fault_plan= is ambiguous under dp replication (a list "
                "already means several specs for ONE plan) — pass "
                "fault_plans=[plan_or_None, ...], one entry per replica"
            )
        if fault_plans is not None and len(fault_plans) != replicas:
            raise ValueError(
                f"fault_plans has {len(fault_plans)} entries for "
                f"{replicas} replicas"
            )
        if mesh is not None:
            from thunder_tpu.serving.mesh import split_mesh

            submeshes = split_mesh(mesh, axis="dp")
            if len(submeshes) != replicas:
                raise ValueError(
                    f"mesh dp axis yields {len(submeshes)} submeshes but "
                    f"replicas={replicas}"
                )
            if engine_kwargs.get("lora") is not None:
                # AdapterRegistry.place() pins the factor arenas to ONE
                # mesh; sharing a registry across submeshes would clobber
                # the placement replica 0's programs compiled against
                raise ValueError(
                    "a shared lora=AdapterRegistry cannot be placed on "
                    "multiple dp submeshes — use LoRA with replicas= (no "
                    "mesh) or one engine per registry"
                )
        else:
            submeshes = [None] * replicas
        # the router runs host-local; in a multi-host deployment only
        # process 0 may drive it (submit enforces this)
        self._process0 = jax.process_index() == 0
        self._engines: list[ServingEngine] = []
        for i in range(replicas):
            self._engines.append(ServingEngine(
                params, cfg,
                model_fn=model_fn,
                mesh=submeshes[i],
                fault_plan=fault_plans[i] if fault_plans is not None else None,
                # owned telemetry (a path) must not be opened N times over;
                # replica 0 carries it, the others run dark
                telemetry=telemetry if i == 0 else None,
                replica_id=i,
                **engine_kwargs,
            ))
        e0 = self._engines[0]
        self._clock = e0.scheduler.clock
        self._max_pending = e0.scheduler.max_queue * replicas
        self._pending: deque[RoutedHandle] = deque()
        self._handles: dict[int, RoutedHandle] = {}
        self._next_rid = 0
        self._rr = 0                                   # round-robin drive offset
        self._closed = False
        # routing-history affinity map: block-aligned prompt-prefix tuple
        # -> replica index, LRU-bounded (see module docstring)
        self._history: OrderedDict[tuple, int] = OrderedDict()
        # router accounting (mirrored into serving.router.* as it changes)
        self.submitted = 0
        self.routed = 0
        self.affinity_hits = 0
        self.expired = 0
        self.routed_by_replica = [0] * replicas
        reg = registry()
        self._m_queue_depth = reg.gauge("serving.router.queue_depth")
        self._m_routed = reg.counter("serving.router.routed")
        self._m_affinity = reg.counter("serving.router.affinity_hits")
        self._m_imbalance = reg.gauge("serving.router.imbalance")
        self._m_running = [
            reg.gauge(f"serving.router.replica{i}.running") for i in range(replicas)
        ]
        reg.gauge("serving.router.replicas").set(replicas)

    @property
    def replicas(self) -> int:
        return len(self._engines)

    @property
    def engines(self) -> tuple[ServingEngine, ...]:
        """The replica lanes (read-only view; tests and operators peek)."""
        return tuple(self._engines)

    #
    # public API (the solo engine's surface)
    #

    def submit(
        self,
        prompt,
        *,
        max_new_tokens: int,
        deadline: float | None = None,
        key=None,
        stream_cb: Callable[[int], Any] | None = None,
        adapter_id: str | None = None,
        session_id: str | None = None,
        priority: str | None = None,
        constraint=None,
    ) -> RoutedHandle:
        """Enqueues one request on the router's global queue; returns
        immediately.  Admission is aggregate: the request is validated
        against one replica's full capacity (replicas are configured
        identically, so feasible-on-one means feasible-anywhere) and the
        global queue bound is ``max_queue × replicas``.  Raises
        :class:`AdmissionError` when the request can never fit or the
        global queue is full.

        ``session_id`` / ``priority`` / ``constraint`` pass through to the
        replica (engines must be built with the matching knob); the router
        adds session affinity (a session's next turn routes to the lane
        holding its parked KV) and class-ordered global queueing."""
        if self._closed:
            raise RuntimeError("engine is shut down")
        if not self._process0:
            raise RuntimeError(
                "the dp router is host-local: submit() is only valid on "
                "process 0 (run single-process serving, or route requests "
                "to process 0 yourself)"
            )
        prompt = np.asarray(prompt, dtype=np.int32).reshape(-1)
        if prompt.size == 0:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError(f"max_new_tokens must be >= 1, got {max_new_tokens}")
        reg = registry()
        try:
            blocks = self._engines[0].scheduler.check_feasible(
                int(prompt.shape[0]), max_new_tokens)
            if len(self._pending) >= self._max_pending:
                raise AdmissionError(
                    f"router queue full ({self._max_pending}); request rejected"
                )
        except AdmissionError:
            reg.counter("serving.requests.rejected").inc()
            raise
        now = self._clock()
        handle = RoutedHandle(
            self, self._next_rid, prompt,
            dict(max_new_tokens=int(max_new_tokens), key=key,
                 stream_cb=stream_cb, adapter_id=adapter_id,
                 session_id=session_id, priority=priority,
                 constraint=constraint),
            (now + deadline) if deadline is not None else None,
            now,
        )
        handle._blocks = blocks
        if priority is not None:
            from thunder_tpu.serving.priority import priority_level

            handle._level = priority_level(priority)[1]
        self._next_rid += 1
        self.submitted += 1
        self._enqueue(handle)
        self._handles[handle.rid] = handle
        self._m_queue_depth.set(len(self._pending))
        return handle

    def _enqueue(self, handle: RoutedHandle) -> None:
        """Class-ordered global queueing: insert before the first pending
        request of a strictly less urgent class (FIFO within a class).
        All-default submissions carry the same level, so this degrades to
        append — the off-path queue order is untouched."""
        for i, h in enumerate(self._pending):
            if h._level > handle._level:
                self._pending.insert(i, handle)
                return
        self._pending.append(handle)

    def step(self) -> bool:
        """One router iteration: route whatever is placeable, drive every
        replica one step in rotating order (so lane *i*'s dispatch
        overlaps lane *j*'s harvest), then route again — admissions freed
        by this step's finishes land without waiting a full turn.
        Returns whether any work happened anywhere."""
        if self._closed:
            raise RuntimeError("engine is shut down")
        worked = self._route()
        n = len(self._engines)
        start, self._rr = self._rr, (self._rr + 1) % n
        for k in range(n):
            if self._engines[(start + k) % n].step():
                worked = True
        if self._route():
            worked = True
        self._update_gauges()
        return worked

    def run(self, requests: Sequence, *, max_new_tokens: int | None = None) -> list[RequestResult]:
        """Convenience driver mirroring ``ServingEngine.run``: submits
        every request (stepping through transient router-queue-full
        backpressure) and drives the fleet to completion."""
        handles = []
        for r in requests:
            kw = dict(r) if isinstance(r, dict) else {"prompt": r}
            if "max_new_tokens" not in kw:
                if max_new_tokens is None:
                    raise ValueError("max_new_tokens missing (argument or per-request)")
                kw["max_new_tokens"] = max_new_tokens
            prompt = kw.pop("prompt")
            while len(self._pending) >= self._max_pending:
                if not self.step():
                    raise AdmissionError(
                        f"router queue full ({self._max_pending}) and the "
                        "fleet cannot make progress"
                    )
            handles.append(self.submit(prompt, **kw))
        self.drain()
        return [h.result(drive=False) for h in handles]

    def drain(self) -> None:
        """Steps until every submitted request has finished.  A stall
        raises :class:`EngineStalledError` naming WHICH replica stalled,
        with that replica's flight-state snapshot attached (an unroutable
        global queue with idle replicas names the router instead)."""
        while self._busy():
            if not self.step():
                raise self._stall_error("fleet stalled during drain")

    def evict(self, handle: RoutedHandle) -> None:
        """Administratively removes a request wherever it is: routed →
        the owning replica frees its blocks (that replica's pool only);
        still pending → dropped from the global queue with a synthetic
        ``"evicted"`` result.  Either way the request's session (if any)
        is closed fleet-wide — an evicted turn must not leave parked
        blocks resident on any lane."""
        if handle.done():
            return
        sid = handle._kwargs.get("session_id")
        if handle._inner is not None:
            self._engines[handle.replica].evict(handle._inner)
            if sid is not None:
                self.close_session(sid)
            return
        self._finish_pending(handle, FINISH_EVICTED)
        try:
            self._pending.remove(handle)
        except ValueError:
            pass
        if sid is not None:
            self.close_session(sid)
        self._m_queue_depth.set(len(self._pending))

    def close_session(self, session_id: str) -> int:
        """Releases a session's parked blocks on EVERY lane; returns the
        total blocks freed.  (A session normally lives on one lane thanks
        to affinity, but the fleet-wide sweep is what guarantees a dead
        session's blocks return to the free list no matter how routing
        history scattered its turns.)"""
        return sum(eng.close_session(session_id) for eng in self._engines)

    def shutdown(self, *, drain: bool = True) -> None:
        """Graceful stop: optionally drains the fleet, evicts whatever
        remains (pending and replica-local), shuts every replica down,
        and rejects further submits."""
        if self._closed:
            return
        if drain:
            self.drain()
        for h in list(self._pending):
            self._finish_pending(h, FINISH_EVICTED)
        self._pending.clear()
        for eng in self._engines:
            eng.shutdown(drain=False)
        self._closed = True

    def __enter__(self) -> "ReplicatedEngine":
        return self

    def __exit__(self, *exc) -> None:
        self.shutdown(drain=exc == (None, None, None))

    def stats(self) -> dict:
        """Router-level statistics beside every replica's own
        ``stats()``.  ``router.imbalance`` is the running-occupancy
        spread (max − min) across lanes; ``aggregate`` sums the fleet."""
        per = [eng.stats() for eng in self._engines]
        running = [p["running"] for p in per]
        return {
            "replicas": len(self._engines),
            "router": {
                "queue_depth": len(self._pending),
                "submitted": self.submitted,
                "routed": self.routed,
                "affinity_hits": self.affinity_hits,
                "expired": self.expired,
                "routed_by_replica": list(self.routed_by_replica),
                "history_size": len(self._history),
                "imbalance": (max(running) - min(running)) if running else 0,
            },
            "per_replica": per,
            "aggregate": {
                "queue_depth": len(self._pending) + sum(p["queue_depth"] for p in per),
                "running": sum(running),
                "pool_free_blocks": sum(p["pool_free_blocks"] for p in per),
                "pool_free_blocks_low_water": [
                    p["pool_free_blocks_low_water"] for p in per
                ],
                "tokens_generated": sum(p["tokens_generated"] for p in per),
                "decode_steps": sum(p["decode_steps"] for p in per),
                "host_visits": sum(p["host_visits"] for p in per),
                "prefix_hits": sum(p["prefix_hits"] for p in per),
                "prefix_lookups": sum(p["prefix_lookups"] for p in per),
                "prefix_hit_rate": (
                    sum(p["prefix_hits"] for p in per)
                    / sum(p["prefix_lookups"] for p in per)
                    if sum(p["prefix_lookups"] for p in per) else None
                ),
                **({
                    "session_resident_blocks": sum(
                        p["sessions"]["resident_blocks"]
                        for p in per if "sessions" in p),
                    "session_reattach_hits": sum(
                        p["sessions"]["reattach_hits"]
                        for p in per if "sessions" in p),
                    "session_evictions": sum(
                        p["sessions"]["evictions"]
                        for p in per if "sessions" in p),
                } if any("sessions" in p for p in per) else {}),
                **({"preempted": sum(p["priority"]["preempted"]
                                     for p in per if "priority" in p)}
                   if any("priority" in p for p in per) else {}),
                **({"goodput": fleet_goodput(
                        [p["goodput"] for p in per if "goodput" in p])}
                   if any("goodput" in p for p in per) else {}),
            },
        }

    def goodput_report(self) -> dict:
        """Fleet goodput: the summed waste taxonomy plus per-lane reports
        and the committed-work imbalance figure (see
        :func:`thunder_tpu.observability.goodput.fleet_goodput`).  Lanes
        with the ledger disabled report ``{"enabled": False}``."""
        per = [eng.goodput_report() for eng in self._engines]
        snaps = [p for p in per if p.get("enabled", True)]
        return {
            "replicas": len(self._engines),
            "per_replica": per,
            **(fleet_goodput(snaps) if snaps else {"enabled": False}),
        }

    #
    # routing
    #

    def _route(self) -> bool:
        """Places global-queue heads onto replicas until the head cannot
        be placed (strict FIFO — see the module docstring for why an
        affinity-blocked head waits rather than routing around)."""
        worked = False
        while self._pending:
            head = self._pending[0]
            now = self._clock()
            if head._deadline_t is not None and now >= head._deadline_t:
                self._finish_pending(head, FINISH_DEADLINE)
                self._pending.popleft()
                sid = head._kwargs.get("session_id")
                if sid is not None:
                    # expiry kills the session: release parked blocks on
                    # every lane, not just wherever affinity last sent it
                    self.close_session(sid)
                worked = True
                continue
            placed = self._place(head)
            if placed is None:
                break
            self._pending.popleft()
            worked = True
        if worked:
            self._m_queue_depth.set(len(self._pending))
        return worked

    def _place(self, head: RoutedHandle) -> int | None:
        """One placement attempt; returns the replica index or ``None``
        when the head must wait this step."""
        idx, kind = self._choose(head)
        if idx is None:
            return None
        eng = self._engines[idx]
        shared = eng.probe_prefix(head._prompt) // eng.pool.block_size
        if not (eng.scheduler.can_accept(head._blocks, shared_blocks=shared)
                and len(eng.scheduler.queue) < eng.scheduler.max_queue):
            # the preferred replica can't admit now: WAIT (affinity-
            # preserving FIFO).  For the least-loaded case _choose already
            # filtered to acceptors, so this only triggers on affinity.
            return None
        kw = dict(head._kwargs)
        if head._deadline_t is not None:
            kw["deadline"] = max(head._deadline_t - self._clock(), 1e-9)
        inner = eng.submit(head._prompt, **kw)
        head._inner = inner
        head.replica = idx
        self.routed += 1
        self.routed_by_replica[idx] += 1
        self._m_routed.inc()
        if kind is not None:
            self.affinity_hits += 1
            self._m_affinity.inc()
        self._remember(head._prompt, idx)
        if eng._tracer is not None:
            eng._tracer.instant(inner.rid, "router.routed",
                                replica=idx, affinity=kind or "least-loaded",
                                router_rid=head.rid)
        if eng._flight is not None:
            eng._flight.record("route", rid=inner.rid, replica=idx,
                               affinity=kind, router_rid=head.rid)
        return idx

    def _choose(self, head: RoutedHandle) -> tuple[int | None, str | None]:
        """Pick the target replica: resident session > resident prefix >
        routing history > least-loaded-that-can-accept."""
        sid = head._kwargs.get("session_id")
        if sid is not None:
            for i, eng in enumerate(self._engines):
                if eng.session_resident(sid):
                    return i, "session"
        best_i, best_k = None, 0
        for i, eng in enumerate(self._engines):
            k = eng.probe_prefix(head._prompt)
            if k > best_k:
                best_i, best_k = i, k
        if best_i is not None:
            return best_i, "resident"
        hist = self._recall(head._prompt)
        if hist is not None:
            return hist, "history"
        # least-loaded among replicas that can admit NOW: most uncommitted
        # free blocks, ties to the emptier then lower-indexed lane
        best = None
        for i, eng in enumerate(self._engines):
            sch = eng.scheduler
            shared = 0   # no affinity anywhere, by construction of this branch
            if not (sch.can_accept(head._blocks, shared_blocks=shared)
                    and len(sch.queue) < sch.max_queue):
                continue
            load = (eng.pool.num_free - sch.committed_blocks(),
                    -(len(sch.running) + len(sch.queue)), -i)
            if best is None or load > best[1]:
                best = (i, load)
        return (best[0], None) if best is not None else (None, None)

    def _remember(self, prompt: np.ndarray, idx: int) -> None:
        """Registers every block-aligned prefix of a routed prompt in the
        history map, so the NEXT member of the family lands on the same
        lane even before anything is resident."""
        bs = self._engines[0].pool.block_size
        hi = ((int(prompt.shape[0]) - 1) // bs) * bs
        toks = prompt.tolist()
        for k in range(bs, hi + 1, bs):
            key = tuple(toks[:k])
            self._history[key] = idx
            self._history.move_to_end(key)
        while len(self._history) > _HISTORY_CAP:
            self._history.popitem(last=False)

    def _recall(self, prompt: np.ndarray) -> int | None:
        """Longest-prefix lookup in the history map (freshening the hit)."""
        bs = self._engines[0].pool.block_size
        hi = ((int(prompt.shape[0]) - 1) // bs) * bs
        toks = prompt.tolist()
        for k in range(hi, 0, -bs):
            idx = self._history.get(tuple(toks[:k]))
            if idx is not None:
                self._history.move_to_end(tuple(toks[:k]))
                return idx
        return None

    #
    # internals
    #

    def _busy(self) -> bool:
        return bool(self._pending) or any(
            eng.scheduler.queue or eng.scheduler.running for eng in self._engines
        )

    def _finish_pending(self, handle: RoutedHandle, reason: str) -> None:
        """Synthesizes a terminal result for a request that never reached
        a replica (router-side deadline expiry or eviction)."""
        now = self._clock()
        handle._synthetic = RequestResult(
            rid=handle.rid,
            prompt=handle._prompt,
            new_tokens=(),
            finish_reason=reason,
            ttft_s=None,
            tpot_s=None,
            tokens_per_sec=None,
            queue_s=None,
            e2e_s=now - handle._submit_t,
            shared_prefix_blocks=0,
        )
        if reason == FINISH_DEADLINE:
            self.expired += 1

    def _stall_error(self, what: str) -> EngineStalledError:
        """Builds the replica-naming stall error: the first replica still
        holding work is the culprit and contributes its flight state; an
        all-idle fleet with an unroutable global queue names the router."""
        for i, eng in enumerate(self._engines):
            if eng.scheduler.queue or eng.scheduler.running:
                return EngineStalledError(
                    what, eng._flight_state(), replica=i)
        return EngineStalledError(
            f"{what} — global queue has {len(self._pending)} unroutable "
            "request(s) but every replica is idle", self._flight_state())

    def _flight_state(self) -> dict:
        """Router-level snapshot (nested per-replica summaries stay
        shallow; a specific replica's full flight state travels on the
        stall error that names it)."""
        return {
            "router": self.stats()["router"],
            "pending": [
                {"rid": h.rid, "prompt_tokens": int(h._prompt.shape[0]),
                 "blocks": h._blocks}
                for h in self._pending
            ],
            "replicas": [
                {"replica": i,
                 "queued": len(eng.scheduler.queue),
                 "running": len(eng.scheduler.running),
                 "pool_free": eng.pool.num_free}
                for i, eng in enumerate(self._engines)
            ],
        }

    def _update_gauges(self) -> None:
        running = [len(eng.scheduler.running) for eng in self._engines]
        for g, r in zip(self._m_running, running):
            g.set(r)
        self._m_imbalance.set((max(running) - min(running)) if running else 0)
        self._m_queue_depth.set(len(self._pending))
