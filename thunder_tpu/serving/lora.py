"""Per-request LoRA adapter serving: one engine, many fine-tunes.

The S-LoRA/punica idea recast for the XLA static-shape world: the engine
holds a **bounded registry** of LoRA adapters — stacked A/B factor arenas
with one slot per adapter — and every bucket program takes the arenas plus
a per-request **slot index** as *data*.  Inside the jitted step the
program gathers each request's factors by slot and applies the low-rank
delta ``scaling * B(A(x))`` next to the target weight's matmul, so a batch
freely mixes tenants without recompiling per adapter: the compiled-program
identity grows only the registry **geometry** (rank, slot count, target
set, scaling), never an adapter id.

Design points:

- **Slot 0 is the reserved base slot** (all-zero factors): requests
  without an ``adapter_id`` ride the same program with an exact-zero
  delta, so one program serves base and adapter traffic alike.
- **Register/evict are data writes**, not compiles: factors land in the
  stacked arenas with ``.at[slot].set``; evicting zeroes the slot (an
  in-flight request of an evicted adapter degrades to base, never to a
  stale tenant's weights).
- **Placed once per mesh like params**: ``place(mesh)`` replicates the
  arenas across the mesh (the factors are tiny next to the weights; a
  replicated delta keeps the SPMD program exactly as collective-free as
  the base matmul it rides on).
- Determinism: the delta of request *i* depends only on row *i*'s
  activations and factors, so a request's tokens are bit-identical
  whether it runs alone or batched with other tenants (tested
  differentially, same contract as the base engine).

Default targets are the attention projections (``wq``/``wk``/``wv``/``wo``)
— the classic LoRA placement; pass a subset to shrink the arenas, or add
the MLP matmuls (``fc_1``/``fc_2``/``proj`` for gated MLPs, ``fc``/``proj``
for GptNeox-style; MoE's stacked expert weights are unsupported) for
full-coverage adapters.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from thunder_tpu.observability.metrics import registry as _metrics

__all__ = [
    "AdapterRegistry",
    "RegistryFullError",
    "gather_adapter_slots",
    "make_lora_factors",
    "valid_targets",
]

BASE_SLOT = 0  # reserved all-zero adapter slot (requests without adapter_id)

_TARGETS = ("wq", "wk", "wv", "wo")


class RegistryFullError(RuntimeError):
    """``register`` found no free slot: the registry is at capacity.
    Evict an adapter (or build a bigger registry) first."""


def valid_targets(cfg) -> tuple[str, ...]:
    """Every LoRA target the model class supports: the attention
    projections always, plus the MLP matmuls by ``mlp_class`` — gated MLPs
    (LLaMA/Gemma) expose ``fc_1``/``fc_2``/``proj``, GptNeox-style exposes
    ``fc``/``proj``, and MoE exposes none (its expert weights are stacked
    ``(E, ...)`` tensors; a per-request delta has no single matmul to ride)."""
    if cfg.mlp_class == "LLaMAMoE":
        return _TARGETS
    if cfg.mlp_class in ("LLaMAMLP", "GemmaMLP"):
        return _TARGETS + ("fc_1", "fc_2", "proj")
    return _TARGETS + ("fc", "proj")


def _target_features(cfg, target: str) -> tuple[int, int]:
    """(in_features, out_features) of one target weight."""
    hs, nh, ng, C = cfg.head_size, cfg.n_head, cfg.n_query_groups, cfg.n_embd
    I = cfg.intermediate_size
    return {
        "wq": (C, nh * hs),
        "wk": (C, ng * hs),
        "wv": (C, ng * hs),
        "wo": (nh * hs, C),
        "fc_1": (C, I),
        "fc_2": (C, I),
        "fc": (C, I),
        "proj": (I, C),
    }[target]


class AdapterRegistry:
    """Bounded slot arena of LoRA A/B factors, shared by one or more
    engines serving the same base model.

    Storage per target ``t``: ``a`` of shape ``(slots, L, rank, in_t)``
    and ``b`` of shape ``(slots, L, out_t, rank)``; the delta applied in
    the model step is ``scaling * (x @ a[slot].T) @ b[slot].T`` per layer,
    with ``scaling = alpha / rank`` (LoRA convention; ``alpha`` defaults
    to ``rank`` → scaling 1.0).
    """

    def __init__(self, cfg, *, rank: int, max_adapters: int = 8,
                 targets=_TARGETS, alpha: float | None = None,
                 dtype=jnp.float32, mesh=None):
        if rank < 1:
            raise ValueError(f"rank must be >= 1, got {rank}")
        if max_adapters < 1:
            raise ValueError(f"max_adapters must be >= 1, got {max_adapters}")
        supported = valid_targets(cfg)
        unknown = [t for t in targets if t not in supported]
        if unknown:
            raise ValueError(
                f"unknown LoRA targets {unknown}; supported for "
                f"mlp_class={cfg.mlp_class!r}: {supported}"
            )
        self.cfg = cfg
        self.rank = int(rank)
        self.max_adapters = int(max_adapters)
        self.n_slots = self.max_adapters + 1           # + the base slot
        self.targets = tuple(targets)
        self.scaling = float(alpha if alpha is not None else rank) / rank
        self.dtype = jnp.dtype(dtype)
        L = cfg.n_layer
        self.arenas = {}
        for t in self.targets:
            fin, fout = _target_features(cfg, t)
            self.arenas[t] = {
                "a": jnp.zeros((self.n_slots, L, self.rank, fin), dtype=self.dtype),
                "b": jnp.zeros((self.n_slots, L, fout, self.rank), dtype=self.dtype),
            }
        self._slot_of: dict[str, int] = {}
        self._free: list[int] = list(range(self.n_slots - 1, BASE_SLOT, -1))
        self._placed_on = None                          # mesh fingerprint once placed
        self.mesh = None
        if mesh is not None:
            self.place(mesh)
        self._gauges()

    #
    # identity (the only thing compiled programs key on)
    #

    @property
    def geometry(self) -> tuple:
        """Hashable registry identity for program-cache keys: everything a
        bucket program's shapes/math depend on — and nothing an adapter
        registration changes.  Two registries of equal geometry share
        compiled programs; registering or evicting adapters never
        invalidates them (the arenas are program *arguments*)."""
        return (self.rank, self.n_slots, self.targets, self.scaling, str(self.dtype))

    #
    # registration
    #

    @property
    def adapter_ids(self) -> tuple[str, ...]:
        return tuple(self._slot_of)

    @property
    def slots_used(self) -> int:
        return len(self._slot_of)

    def slot(self, adapter_id: str) -> int:
        """Slot index of a registered adapter (KeyError when unknown —
        admission-time validation, not a silent base fallback)."""
        if adapter_id not in self._slot_of:
            raise KeyError(
                f"unknown adapter_id {adapter_id!r}; registered: "
                f"{sorted(self._slot_of)}"
            )
        return self._slot_of[adapter_id]

    def register(self, adapter_id: str, factors: dict) -> int:
        """Installs (or overwrites) one adapter's factors; returns its slot.

        ``factors``: ``{target: (A, B)}`` with ``A`` of shape
        ``(n_layer, rank, in_t)`` and ``B`` of shape
        ``(n_layer, out_t, rank)`` for every registry target.  Raises
        :class:`RegistryFullError` when no slot is free."""
        missing = [t for t in self.targets if t not in factors]
        if missing:
            raise ValueError(f"factors missing targets {missing} (registry targets {self.targets})")
        L = self.cfg.n_layer
        staged = {}
        for t in self.targets:
            fin, fout = _target_features(self.cfg, t)
            a, b = (jnp.asarray(x, dtype=self.dtype) for x in factors[t])
            want_a, want_b = (L, self.rank, fin), (L, fout, self.rank)
            if tuple(a.shape) != want_a or tuple(b.shape) != want_b:
                raise ValueError(
                    f"adapter {adapter_id!r} target {t!r}: A/B shapes "
                    f"{tuple(a.shape)}/{tuple(b.shape)} != expected {want_a}/{want_b}"
                )
            staged[t] = (a, b)
        slot = self._slot_of.get(adapter_id)
        if slot is None:
            if not self._free:
                raise RegistryFullError(
                    f"registry full ({self.max_adapters} adapters); evict one "
                    f"before registering {adapter_id!r}"
                )
            slot = self._free.pop()
        for t, (a, b) in staged.items():
            self.arenas[t] = {
                "a": self.arenas[t]["a"].at[slot].set(a),
                "b": self.arenas[t]["b"].at[slot].set(b),
            }
        self._slot_of[adapter_id] = slot
        self._gauges()
        return slot

    def evict(self, adapter_id: str) -> None:
        """Removes an adapter and zeroes its slot (an in-flight request
        still carrying the slot degrades to the base model, never to a
        later tenant's factors)."""
        slot = self.slot(adapter_id)
        for t in self.targets:
            self.arenas[t] = {
                "a": self.arenas[t]["a"].at[slot].set(0.0),
                "b": self.arenas[t]["b"].at[slot].set(0.0),
            }
        del self._slot_of[adapter_id]
        self._free.append(slot)
        self._gauges()

    #
    # placement
    #

    def place(self, mesh) -> None:
        """Replicates the factor arenas across ``mesh`` once (engine
        construction calls this — 'placed once per mesh like params').
        Idempotent per mesh."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        from thunder_tpu.serving.mesh import mesh_fingerprint

        fp = mesh_fingerprint(mesh)
        if fp == self._placed_on:
            return
        repl = NamedSharding(mesh, P())
        self.arenas = jax.tree_util.tree_map(
            lambda x: jax.device_put(x, repl), self.arenas
        )
        self._placed_on = fp
        self.mesh = mesh

    def state_snapshot(self) -> dict:
        """Registry occupancy for the flight recorder / engine stats."""
        return {
            "rank": self.rank,
            "slots": self.max_adapters,
            "slots_used": self.slots_used,
            "targets": list(self.targets),
            "scaling": self.scaling,
            "adapters": sorted(self._slot_of),
        }

    def _gauges(self) -> None:
        reg = _metrics()
        reg.gauge("serving.lora.slots").set(self.max_adapters)
        reg.gauge("serving.lora.adapters").set(self.slots_used)


def gather_adapter_slots(arenas: dict, slots):
    """Gathers per-request factors by slot index inside a jitted program:
    ``{t: {"a": (S, L, r, fin), "b": (S, L, fout, r)}}`` and ``slots``
    (B,) int32 → ``{t: {"a": (B, L, r, fin), "b": (B, L, fout, r)}}`` —
    the per-request layout ``forward_with_cache(lora=...)`` consumes."""
    return {
        t: {"a": jnp.take(ab["a"], slots, axis=0),
            "b": jnp.take(ab["b"], slots, axis=0)}
        for t, ab in arenas.items()
    }


def make_lora_factors(cfg, rank: int, key, targets=_TARGETS, *, std: float = 0.05,
                      dtype=jnp.float32) -> dict:
    """Random LoRA factors for tests/benches (both A and B nonzero so the
    delta actually moves logits; real fine-tunes init B to zero)."""
    out = {}
    keys = jax.random.split(key, 2 * len(targets))
    for i, t in enumerate(targets):
        fin, fout = _target_features(cfg, t)
        a = (jax.random.normal(keys[2 * i], (cfg.n_layer, rank, fin), dtype=jnp.float32) * std)
        b = (jax.random.normal(keys[2 * i + 1], (cfg.n_layer, fout, rank), dtype=jnp.float32) * std)
        out[t] = (a.astype(dtype), b.astype(dtype))
    return out
