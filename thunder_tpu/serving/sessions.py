"""Session KV persistence: finished turns keep their prefix blocks resident.

A multi-turn chat re-submits its whole history every turn; without state
the engine re-prefills all of it.  ``submit(..., session_id=)`` changes
the *lifetime* of a request's KV, not its computation: when a session
turn finishes normally, the engine parks the block-aligned prefix of the
full served sequence (prompt + generated tokens) here instead of freeing
it.  The table holds its own ``pool.share()`` references and registers
the parked tokens in the engine's :class:`PrefixIndex` under a synthetic
negative owner id — so turn k≥2 re-attaches through the *existing*
shared-prefix admission path (``share()`` + ``req.pos = n_shared *
block_size``) and re-prefills only the block-unaligned tail.  No new
device code: the bit-identity of the share path is the bit-identity of
sessions.

The table is budgeted: an LRU over sessions with both a count cap and a
bytes cap (in units of ``pool.block_bytes()``).  Parking evicts
least-recently-used sessions until the new entry fits; ``close()`` (and
the engine's ``close_session()``) releases explicitly.  Eviction frees
the shared references and unregisters the prefix entries, so a dead
session's blocks return to the free list immediately.

Recovery: parked KV lives in the (donated, rebuildable) arenas, so a
fault wipes it.  Each entry keeps the exact token sequence its blocks
hold; ``ServingEngine._recover_once`` replays every resident session
through the sampling-free ``prefill_chunk`` programs — the same replay
that restores running requests — so the re-attach contract survives
recovery bit-identically.
"""

from __future__ import annotations

import dataclasses
import itertools
from collections import OrderedDict

import numpy as np

from thunder_tpu.observability.metrics import registry
from thunder_tpu.serving.kv_pool import SINK_BLOCK

__all__ = ["SessionConfig", "SessionEntry", "SessionTable", "resolve_sessions"]


@dataclasses.dataclass
class SessionConfig:
    """Budget for the resident-session table.

    ``max_bytes=None`` defaults to half the pool's arena bytes at
    resolve time — sessions may cache aggressively but can never crowd
    live requests out of more than half the arena.
    """

    max_sessions: int = 64
    max_bytes: int | None = None

    def __post_init__(self):
        if self.max_sessions < 1:
            raise ValueError("max_sessions must be >= 1")
        if self.max_bytes is not None and self.max_bytes < 0:
            raise ValueError("max_bytes must be >= 0")


def resolve_sessions(spec, pool, prefix_index) -> "SessionTable | None":
    """``sessions=`` engine kwarg → a :class:`SessionTable` (or None).

    Accepts ``None``/``False`` (off), ``True`` (defaults), a dict of
    :class:`SessionConfig` fields, or a ready config.
    """
    if spec is None or spec is False:
        return None
    if spec is True:
        cfg = SessionConfig()
    elif isinstance(spec, SessionConfig):
        cfg = spec
    elif isinstance(spec, dict):
        cfg = SessionConfig(**spec)
    else:
        raise TypeError(
            f"sessions= must be None, True, a dict, or SessionConfig; "
            f"got {type(spec).__name__}")
    return SessionTable(pool, prefix_index, cfg)


@dataclasses.dataclass
class SessionEntry:
    """One resident session: the tokens its parked blocks hold."""

    session_id: str
    owner_rid: int            # synthetic negative id in the PrefixIndex
    tokens: np.ndarray        # exactly len(blocks) * block_size tokens
    blocks: tuple[int, ...]   # table-held pool.share() references
    adapter_slot: int         # LoRA slot the KV was computed under
    nbytes: int
    # cache positions the parked turn had written in total: positions past
    # len(blocks)*block_size were truncated at park and must be recomputed
    # on re-attach (goodput cause "replay_session_tail")
    full_pos: int = 0


class SessionTable:
    """LRU + bytes-budgeted table of parked session prefixes."""

    def __init__(self, pool, prefix_index, config: SessionConfig | None = None):
        cfg = config or SessionConfig()
        self.pool = pool
        self.index = prefix_index
        self.max_sessions = cfg.max_sessions
        self.max_bytes = (pool.arena_bytes() // 2 if cfg.max_bytes is None
                          else cfg.max_bytes)
        self._entries: OrderedDict[str, SessionEntry] = OrderedDict()
        self._by_owner: dict[int, SessionEntry] = {}
        self._owner_ids = itertools.count(-1, -1)
        reg = registry()
        self._m_resident = reg.gauge("serving.session.resident_blocks")
        self._m_reattach = reg.counter("serving.session.reattach_hits")
        self._m_evictions = reg.counter("serving.session.evictions")
        self.reattach_hits = 0
        self.evictions = 0

    # -- queries ------------------------------------------------------------
    def __len__(self) -> int:
        return len(self._entries)

    def resident(self, session_id: str) -> bool:
        return session_id in self._entries

    @property
    def resident_blocks(self) -> int:
        return sum(len(e.blocks) for e in self._entries.values())

    @property
    def resident_bytes(self) -> int:
        return sum(e.nbytes for e in self._entries.values())

    def entries(self) -> list[SessionEntry]:
        """Snapshot of live entries (recovery replay iterates this)."""
        return list(self._entries.values())

    def alive(self, owner_rid: int, blocks) -> bool:
        """Prefix-index liveness for parked owners (negative rids)."""
        entry = self._by_owner.get(owner_rid)
        if entry is None:
            return False
        blocks = tuple(blocks)
        return entry.blocks[:len(blocks)] == blocks

    def owner_entry(self, owner_rid: int) -> SessionEntry | None:
        return self._by_owner.get(owner_rid)

    # -- mutation -----------------------------------------------------------
    def park(self, session_id: str, tokens, blocks, *,
             adapter_slot: int = 0, full_pos: int = 0) -> SessionEntry | None:
        """Retain ``blocks`` (holding exactly ``tokens``) for the session.

        Shares the blocks *before* releasing any prior entry for the same
        id, so re-parking a grown turn never drops overlap blocks to
        refcount zero.  Returns ``None`` (parking nothing) when the entry
        alone exceeds the bytes budget or the block list is empty/sunk.
        """
        blocks = tuple(int(b) for b in blocks)
        tokens = np.asarray(tokens, dtype=np.int64)
        bs = self.pool.block_size
        if SINK_BLOCK in blocks:
            blocks = blocks[:blocks.index(SINK_BLOCK)]
        blocks = blocks[:len(tokens) // bs]
        tokens = tokens[:len(blocks) * bs]
        nbytes = len(blocks) * self.pool.block_bytes()
        if not blocks or nbytes > self.max_bytes:
            self.close(session_id)
            return None
        self.pool.share(blocks)
        self.close(session_id, _count_eviction=False)
        while self._entries and (
                len(self._entries) >= self.max_sessions
                or self.resident_bytes + nbytes > self.max_bytes):
            victim = next(iter(self._entries))
            self.close(victim)
        entry = SessionEntry(session_id=session_id,
                             owner_rid=next(self._owner_ids),
                             tokens=tokens, blocks=blocks,
                             adapter_slot=int(adapter_slot), nbytes=nbytes,
                             full_pos=int(full_pos))
        self._entries[session_id] = entry
        self._by_owner[entry.owner_rid] = entry
        self.index.register(entry.owner_rid, tokens, list(blocks),
                            lambda hit: self.alive(*hit), full=True)
        self._m_resident.set(self.resident_blocks)
        return entry

    def touch(self, session_id: str) -> None:
        """LRU-bump a session whose prefix a new turn just re-attached."""
        if session_id in self._entries:
            self._entries.move_to_end(session_id)

    def note_reattach(self, owner_rid: int) -> None:
        """Count a shared-prefix hit served from a parked session."""
        entry = self._by_owner.get(owner_rid)
        if entry is not None:
            self._entries.move_to_end(entry.session_id)
            self.reattach_hits += 1
            self._m_reattach.inc()

    def close(self, session_id: str, *, _count_eviction: bool = True) -> int:
        """Release a session's references; returns blocks freed (0 if absent)."""
        entry = self._entries.pop(session_id, None)
        if entry is None:
            return 0
        self._by_owner.pop(entry.owner_rid, None)
        self.index.unregister(entry.owner_rid)
        self.pool.free(list(entry.blocks))
        if _count_eviction:
            self.evictions += 1
            self._m_evictions.inc()
        self._m_resident.set(self.resident_blocks)
        return len(entry.blocks)

    def clear(self) -> int:
        """Release everything (engine shutdown); returns blocks freed."""
        freed = 0
        for sid in list(self._entries):
            freed += self.close(sid)
        return freed

    # -- introspection ------------------------------------------------------
    def snapshot(self) -> dict:
        return {
            "sessions": len(self._entries),
            "resident_blocks": self.resident_blocks,
            "resident_bytes": self.resident_bytes,
            "max_sessions": self.max_sessions,
            "max_bytes": self.max_bytes,
            "reattach_hits": self.reattach_hits,
            "evictions": self.evictions,
            "ids": list(self._entries),
        }
