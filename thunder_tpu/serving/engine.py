"""Serving engine front-end: ``tt.serve(...)`` → :class:`ServingEngine`.

Continuous (in-flight) batching over the compiled decode step: independent
requests share one bucketed decode program, join the batch the step after
their prefill, and leave it the step they finish — batch occupancy is a
scheduling property, not a caller-visible one.  The engine composes the
pieces the repo already has:

- ``models.generate.forward_with_cache`` is the model step — the pool's
  gathered block views reassemble exactly the dense cache layout it
  consumes, and per-row vector positions (the speculative-decode machinery)
  drive mixed-progress batches;
- the **paged pool** (:mod:`serving.kv_pool`) owns cache memory; every
  program donates the arenas so updates stay in place (PR 4);
- the **scheduler** (:mod:`serving.scheduler`) owns admission, FIFO order,
  deadlines, and the bucket sets that bound recompiles (absorbed by the
  PR-1 dispatch cache when the model fn is a ``tt.jit`` product);
- **observability** (PRs 2–3): queue/occupancy/pool gauges, TTFT/TPOT and
  tokens/sec histograms in the metrics registry, per-request JSONL records
  through :class:`observability.telemetry.StepLogger`;
- **multi-tenancy**: ``kv_dtype="int8"`` stores the arenas quantized
  (:mod:`serving.quant`), and ``lora=AdapterRegistry(...)`` routes each
  request through a per-request LoRA adapter (:mod:`serving.lora`) — both
  live inside the same bucket programs, keyed only by storage dtype and
  registry geometry.

Reproducibility contract: each request carries its own PRNG key chain and
splits it exactly like a solo ``generate()`` call (one split at prefill, one
per decode step; per-row sampling under ``vmap`` is bit-equivalent to the
unbatched call), so a request's tokens do not depend on what else shares
the batch — and greedy tokens match ``generate()`` exactly.

The drive loop is synchronous and explicit: ``step()`` runs one scheduler
iteration (expire → admit+prefill → one decode step); ``run()``/``drain()``
loop it.  No threads — integrate into any host loop.

Serving-plane observability (all off by default; the off path is an
``is None`` check per touch point):

- ``trace=True`` / ``THUNDER_TPU_TRACE_SERVING=1`` — per-request lifecycle
  spans (queued / prefill split into compile-or-dispatch + host / every
  decode step / finish) plus ``engine.step`` spans into the shared event
  ring; ``tt.export_chrome_trace`` merges them with the compile-pipeline
  rows into one Perfetto timeline (:mod:`observability.tracing`);
- ``slo={"ttft_s": ..., "tpot_s": ...}`` — windowed good/bad counters and
  burn-rate gauges per finished request, surfaced by
  :meth:`ServingEngine.slo_report` (:mod:`observability.slo`);
- ``flight_recorder=True`` / ``THUNDER_TPU_FLIGHT_RECORDER=1`` — bounded
  ring of engine events + scheduler/pool state, auto-dumped to JSON when
  ``step()`` raises, exportable any time via ``tt.flight_record(path)``
  (:mod:`observability.flight`).
"""
from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from thunder_tpu.models.generate import (
    build_rope_cache,
    forward_with_cache,
    sample_token,
)
from thunder_tpu.observability.config import (
    flight_recorder_env_enabled,
    serving_trace_env_enabled,
)
from thunder_tpu.observability.flight import FlightRecorder
from thunder_tpu.observability.metrics import registry
from thunder_tpu.observability.slo import resolve_slo
from thunder_tpu.observability.tracing import RequestTracer
from thunder_tpu.serving.kv_pool import (
    SINK_BLOCK,
    PagedKVPool,
    gather_dense,
    scatter_blocks,
    scatter_token,
)
from thunder_tpu.serving.lora import gather_adapter_slots
from thunder_tpu.serving.quant import (
    gather_dense_q,
    scatter_blocks_q,
    scatter_token_q,
)
from thunder_tpu.serving.scheduler import (
    FINISH_DEADLINE,
    FINISH_EOS,
    FINISH_EVICTED,
    FINISH_LENGTH,
    AdmissionError,
    Request,
    Scheduler,
    pick_bucket,
)

__all__ = [
    "serve",
    "ServingEngine",
    "RequestHandle",
    "RequestResult",
    "AdmissionError",
    "EngineStalledError",
]


class EngineStalledError(RuntimeError):
    """``drain()``/``result()`` could not make progress: requests remain
    queued/running but ``step()`` did no work (e.g. blocks leaked outside
    the scheduler, or a queue head that can never fit the live pool).
    Carries the flight-recorder state snapshot — queued/running request
    rows, pool free/lease counts, compile log — as ``.state`` and inlines
    the headline numbers in the message so a stall is diagnosable from the
    traceback alone."""

    def __init__(self, msg: str, state: dict | None = None):
        self.state = state or {}
        sched = self.state.get("scheduler", {})
        pool = self.state.get("pool", {})
        rows = sched.get("requests", [])
        rids = {
            "queued": [r["rid"] for r in rows if r.get("state") == "queued"],
            "running": [r["rid"] for r in rows if r.get("state") == "running"],
        }
        detail = (
            f" [queued rids={rids['queued']} running rids={rids['running']} "
            f"pool free={pool.get('num_free')}/{pool.get('num_blocks')} "
            f"leased={pool.get('leased_blocks')} shared={pool.get('shared_blocks')}]"
            if self.state else ""
        )
        super().__init__(msg + detail)


@dataclass(frozen=True)
class RequestResult:
    """Structured outcome of one served request."""

    rid: int
    prompt: np.ndarray
    new_tokens: tuple[int, ...]
    finish_reason: str                      # length | eos | deadline | evicted
    ttft_s: float | None                    # submit → first token
    tpot_s: float | None                    # mean per-token after the first
    tokens_per_sec: float | None
    queue_s: float | None                   # submit → admission
    e2e_s: float | None                     # submit → finish wall time
    shared_prefix_blocks: int
    prefill_compiled: bool = False          # the prefill run paid an XLA compile

    @property
    def tokens(self) -> np.ndarray:
        """Full sequence (prompt + generated), the solo ``generate()`` row."""
        return np.concatenate([self.prompt, np.asarray(self.new_tokens, dtype=np.int32)])


class RequestHandle:
    """Caller's view of a submitted request."""

    def __init__(self, engine: "ServingEngine", req: Request):
        self._engine = engine
        self._req = req

    @property
    def rid(self) -> int:
        return self._req.rid

    @property
    def state(self) -> str:
        return self._req.state

    def done(self) -> bool:
        return self._req.state == "finished"

    def tokens_so_far(self) -> tuple[int, ...]:
        return tuple(self._req.generated)

    def result(self, *, drive: bool = True) -> RequestResult:
        """The structured result; with ``drive`` (default) steps the engine
        until this request finishes."""
        while drive and not self.done():
            if not self._engine.step() and not self.done():
                raise EngineStalledError(
                    f"engine stalled with request {self.rid} still {self._req.state}",
                    self._engine._flight_state(),
                )
        if not self.done():
            raise RuntimeError(f"request {self.rid} is still {self._req.state}")
        return self._engine._result(self._req)


# jitted bucket programs, shared across engines with identical static
# configuration (the _generate_cache idiom): an engine restart — or a test
# suite full of small engines — reuses steady-state compiled programs
_program_cache: dict = {}

# one decode program's collective census per (mesh, static config, bucket):
# the census pays an extra AOT compile, so it is module-cached like programs
_collectives_cache: dict = {}


class ServingEngine:
    """Continuous-batching inference engine over a paged KV pool."""

    def __init__(
        self,
        params,
        cfg,
        *,
        model_fn: Callable | None = None,
        block_size: int = 16,
        num_blocks: int = 64,
        max_batch: int = 8,
        max_queue: int = 64,
        temperature: float = 0.0,
        eos_id: int | None = None,
        quantized: bool = False,
        cache_dtype=None,
        kv_dtype=None,
        lora=None,
        prefix_sharing: bool = True,
        clock: Callable[[], float] | None = None,
        telemetry=None,
        batch_buckets: Sequence[int] | None = None,
        block_buckets: Sequence[int] | None = None,
        prefill_buckets: Sequence[int] | None = None,
        trace: bool | None = None,
        slo=None,
        flight_recorder=None,
        mesh=None,
        shardings=None,
    ):
        if shardings is not None and mesh is None:
            raise ValueError("shardings= requires mesh= (param placement needs a mesh)")
        self.mesh = mesh
        if mesh is not None:
            # SPMD serving: place params once (tp_fsdp-style rules unless
            # the caller brings their own), shard the KV arenas heads-over-
            # tp, and compile every bucket program with explicit shardings
            from thunder_tpu.serving.mesh import mesh_fingerprint, place_params

            params = place_params(params, mesh, shardings)
            # the param placement is baked into every program's
            # in_shardings, so it is part of the program identity too
            self._mesh_key = (
                mesh_fingerprint(mesh),
                tuple(str(x.sharding.spec) for x in jax.tree_util.tree_leaves(params)),
            )
        else:
            self._mesh_key = None
        self._mesh_collectives: dict | None = None         # lazy decode census
        self.params = params
        self.cfg = cfg
        self._forward = model_fn if model_fn is not None else forward_with_cache
        self.temperature = float(temperature)
        self.eos_id = eos_id
        self.quantized = bool(quantized)
        self.prefix_sharing = bool(prefix_sharing)
        dtype = cache_dtype if cache_dtype is not None else params["wte"].dtype
        self.pool = PagedKVPool(
            cfg, num_blocks=num_blocks, block_size=block_size, dtype=dtype,
            kv_dtype=kv_dtype, mesh=mesh,
        )
        # multi-tenant LoRA: a bounded AdapterRegistry shared across engines;
        # its stacked factor arenas are program *arguments* (register/evict
        # are data writes), only its geometry enters the program identity
        self._registry = lora
        if lora is not None:
            for dim in ("n_layer", "n_head", "n_query_groups", "head_size", "n_embd"):
                if getattr(lora.cfg, dim) != getattr(cfg, dim):
                    raise ValueError(
                        f"lora registry was built for {dim}="
                        f"{getattr(lora.cfg, dim)} but the engine serves "
                        f"{dim}={getattr(cfg, dim)}"
                    )
            if mesh is not None:
                lora.place(mesh)   # placed once per mesh, like params
        self.scheduler = Scheduler(
            self.pool,
            max_batch=max_batch,
            max_queue=max_queue,
            clock=clock,
            batch_buckets=batch_buckets,
            block_buckets=block_buckets,
            prefill_buckets=prefill_buckets,
            sliding_window=cfg.sliding_window,
        )
        if getattr(cfg, "learned_pos_embedding", False):
            # wpe has block_size rows and dynamic_slice clamps silently past
            # them: cap the bucket sets so no program's dense capacity can
            # reach beyond the learned table
            sch = self.scheduler
            blk = tuple(
                b for b in sch.block_buckets
                if self.pool.capacity_tokens(b) <= cfg.block_size
            )
            assert blk, (
                f"block_size(cfg)={cfg.block_size} admits no pool bucket at "
                f"pool block_size={block_size} with learned position embeddings"
            )
            sch.block_buckets = blk
            sch.prefill_buckets = tuple(
                t for t in sch.prefill_buckets if t <= cfg.block_size
            ) or (cfg.block_size,)
            # a block-aligned resume point near block_size would push the
            # padded prefill window past the wpe table (dynamic_slice clamps
            # the start — real tokens would read shifted embeddings), so
            # suffix prefill is off the table for learned-pos models
            self.prefix_sharing = False
        self._table_widths = self._table_width_buckets()
        # telemetry: a StepLogger, a path for one, or None
        self._owns_telemetry = isinstance(telemetry, (str, bytes)) or hasattr(telemetry, "__fspath__")
        if self._owns_telemetry:
            from thunder_tpu.observability.telemetry import StepLogger

            telemetry = StepLogger(telemetry, meta={
                "kind": "serving", "block_size": block_size, "num_blocks": num_blocks,
                "max_batch": max_batch, "model": getattr(cfg, "name", "?"),
            })
        self.telemetry = telemetry
        self._handles: dict[int, RequestHandle] = {}
        self._prefix_index: dict[tuple, tuple[int, tuple[int, ...]]] = {}
        self._programs: dict[tuple, Callable] = {}
        self._closed = False
        # drive-loop accounting (mirrored into the registry as it changes)
        self.decode_steps = 0
        self.prefill_runs = 0
        self.tokens_generated = 0
        self._occupancy_sum = 0
        self.compile_counts = {"prefill": 0, "decode": 0}
        self._compile_log: list[dict] = []               # per-bucket compile causes
        self._prefix_lookups = 0
        self._prefix_hits = 0
        # serving-plane observability (all off by default; the off path is
        # one `is None` check per touch point — measured by bench.py tracing)
        if trace is None:
            trace = serving_trace_env_enabled()
        self._tracer = RequestTracer() if trace else None
        self._slo = resolve_slo(slo)
        if flight_recorder is None:
            flight_recorder = flight_recorder_env_enabled()
        if isinstance(flight_recorder, FlightRecorder):
            flight_recorder.state_provider = self._flight_state
            self._flight = flight_recorder
        else:
            self._flight = (
                FlightRecorder(state_provider=self._flight_state)
                if flight_recorder else None
            )
        if mesh is not None:
            # serving.mesh.* gauges: static facts land at construction; the
            # decode collective count follows once a decode program exists
            reg = registry()
            reg.gauge("serving.mesh.devices").set(int(mesh.devices.size))
            for a in mesh.axis_names:
                reg.gauge(f"serving.mesh.axis.{a}").set(int(mesh.shape[a]))
            reg.gauge("serving.mesh.arena_shard_bytes").set(self.pool.per_shard_bytes())

    #
    # public API
    #

    def submit(
        self,
        prompt,
        *,
        max_new_tokens: int,
        deadline: float | None = None,
        key=None,
        stream_cb: Callable[[int], Any] | None = None,
        adapter_id: str | None = None,
    ) -> RequestHandle:
        """Enqueues one request; returns immediately with a handle.

        ``deadline`` is seconds from now; past it the request finishes with
        reason ``"deadline"`` wherever it is.  ``key`` seeds the request's
        private sampling chain (default ``PRNGKey(0)``, like ``generate``).
        ``stream_cb`` receives each generated token id, in order, as soon as
        the host sees it.  ``adapter_id`` routes the request through a LoRA
        adapter registered in the engine's ``lora=`` registry (resolved to
        its slot here, at admission time — an unknown id raises KeyError
        immediately, never a silent base fallback).  Raises
        :class:`AdmissionError` when the wait queue is full or the request
        can never fit the pool."""
        if self._closed:
            raise RuntimeError("engine is shut down")
        if key is None:
            key = jax.random.PRNGKey(0)
        adapter_slot = 0
        if adapter_id is not None:
            if self._registry is None:
                raise ValueError(
                    f"adapter_id={adapter_id!r} requires an engine built with "
                    f"lora=AdapterRegistry(...)"
                )
            adapter_slot = self._registry.slot(adapter_id)
        reg = registry()
        try:
            req = self.scheduler.submit(
                prompt, max_new_tokens, key=key, deadline_s=deadline, stream_cb=stream_cb,
                adapter_id=adapter_id, adapter_slot=adapter_slot,
            )
        except AdmissionError:
            reg.counter("serving.requests.rejected").inc()
            raise
        reg.counter("serving.requests.submitted").inc()
        reg.gauge("serving.queue_depth").set(len(self.scheduler.queue))
        if self._tracer is not None:
            self._tracer.register_request(req.rid)
            self._tracer.begin(req.rid, "queued",
                               prompt_tokens=req.prompt_len,
                               max_new_tokens=req.max_new_tokens)
        if self._flight is not None:
            self._flight.record("submit", rid=req.rid,
                                prompt_tokens=req.prompt_len,
                                max_new_tokens=req.max_new_tokens,
                                queue_depth=len(self.scheduler.queue))
        handle = RequestHandle(self, req)
        self._handles[req.rid] = handle
        return handle

    def step(self) -> bool:
        """One scheduler iteration: expire deadlines, admit + prefill while
        capacity allows, then one decode step for the running batch.
        Returns whether any work happened.  When a flight recorder is armed,
        any exception out of the step auto-dumps the flight record before
        propagating; when tracing is on, the step lands as an
        ``engine.step`` span."""
        if self._closed:
            raise RuntimeError("engine is shut down")
        tr = self._tracer
        if tr is not None:
            tr.engine_begin("engine.step",
                            queued=len(self.scheduler.queue),
                            running=len(self.scheduler.running))
        try:
            worked = self._step_inner()
        except Exception as e:
            if self._flight is not None:
                self._flight.crash_dump(e)
            if tr is not None:
                tr.engine_end("engine.step", error=type(e).__name__)
            raise
        if tr is not None:
            tr.engine_end("engine.step", worked=worked)
        return worked

    def _step_inner(self) -> bool:
        worked = False
        for req in self.scheduler.deadline_expired():
            self._finish(req, FINISH_DEADLINE)
            worked = True
        while self._try_admit():
            worked = True
        if self.scheduler.running:
            self._decode_once()
            worked = True
        self._update_gauges()
        return worked

    def run(self, requests: Sequence, *, max_new_tokens: int | None = None) -> list[RequestResult]:
        """Convenience driver: submits every request (stepping through
        transient queue-full rejections) and drives to completion.  Each
        request is a prompt array or a dict of :meth:`submit` kwargs."""
        handles = []
        for r in requests:
            kw = dict(r) if isinstance(r, dict) else {"prompt": r}
            if "max_new_tokens" not in kw:
                if max_new_tokens is None:
                    raise ValueError("max_new_tokens missing (argument or per-request)")
                kw["max_new_tokens"] = max_new_tokens
            prompt = kw.pop("prompt")
            # transient queue-full backpressure is not a rejection: make room
            # by stepping instead of bouncing off submit() (which counts every
            # AdmissionError it raises in serving.requests.rejected)
            while len(self.scheduler.queue) >= self.scheduler.max_queue:
                if not self.step():
                    raise AdmissionError(
                        f"wait queue full ({self.scheduler.max_queue}) and the "
                        "engine cannot make progress"
                    )
            handles.append(self.submit(prompt, **kw))
        self.drain()
        return [h.result(drive=False) for h in handles]

    def drain(self) -> None:
        """Steps until every submitted request has finished.  A stall (work
        remains but no step can progress) raises :class:`EngineStalledError`
        carrying the flight-recorder state snapshot."""
        while self.scheduler.queue or self.scheduler.running:
            if not self.step():
                raise EngineStalledError(
                    "engine stalled during drain", self._flight_state()
                )

    def evict(self, handle: RequestHandle) -> None:
        """Administratively removes a queued/running request (finish reason
        ``"evicted"``); its blocks return to the pool immediately."""
        if not handle.done():
            self._finish(handle._req, FINISH_EVICTED)

    def shutdown(self, *, drain: bool = True) -> None:
        """Graceful stop: optionally drains, evicts whatever remains, closes
        owned telemetry, and rejects further submits."""
        if self._closed:
            return
        if drain:
            self.drain()
        for req in (*self.scheduler.running, *self.scheduler.queue):
            self._finish(req, FINISH_EVICTED)
        self._closed = True
        if self._owns_telemetry and self.telemetry is not None:
            self.telemetry.close()

    def __enter__(self) -> "ServingEngine":
        return self

    def __exit__(self, *exc) -> None:
        self.shutdown(drain=exc == (None, None, None))

    def mesh_stats(self) -> dict | None:
        """Mesh-serving facts (``None`` on a single-device engine): mesh
        shape, per-shard arena bytes, and — once the first decode step has
        run its program census — the collective count of one compiled
        decode program."""
        if self.mesh is None:
            return None
        return {
            "devices": int(self.mesh.devices.size),
            "axes": {a: int(self.mesh.shape[a]) for a in self.mesh.axis_names},
            "arena_spec": str(self.pool.arena_sharding.spec),
            "arena_shard_bytes": self.pool.per_shard_bytes(),
            "arena_total_bytes": int(self.pool.k_arena.nbytes) * 2,
            "collectives_decode": self._mesh_collectives,  # None until censused
        }

    def stats(self) -> dict:
        """Host-side engine statistics (registry-independent)."""
        occ = (self._occupancy_sum / self.decode_steps) if self.decode_steps else 0.0
        mesh = self.mesh_stats()
        return {
            **({"mesh": mesh} if mesh is not None else {}),
            **({"lora": self._registry.state_snapshot()} if self._registry is not None else {}),
            "queue_depth": len(self.scheduler.queue),
            "running": len(self.scheduler.running),
            "pool_free_blocks": self.pool.num_free,
            "pool_free_blocks_low_water": self.pool.free_blocks_low_water,
            "pool_utilization": self.pool.utilization(),
            "kv_dtype": str(self.pool.kv_dtype),
            "arena_bytes": self.pool.arena_bytes(),
            "decode_steps": self.decode_steps,
            "prefill_runs": self.prefill_runs,
            "tokens_generated": self.tokens_generated,
            "mean_batch_occupancy": occ,
            "compile_counts": dict(self.compile_counts),
            "bucket_bound": (
                (len(self.scheduler.batch_buckets) + len(self.scheduler.prefill_buckets))
                * len(self._table_widths)
            ),
            "prefix_lookups": self._prefix_lookups,
            "prefix_hits": self._prefix_hits,
        }

    def slo_report(self) -> dict:
        """Burn rates against the configured SLO targets (``slo=`` at
        construction; see :mod:`thunder_tpu.observability.slo`).  Without a
        configured SLO the report is ``{"enabled": False}`` — the engine
        carries no monitor and no per-request classification cost."""
        if self._slo is None:
            return {"enabled": False}
        return self._slo.report()

    def _flight_state(self) -> dict:
        """State snapshot the flight recorder embeds in every dump."""
        lookups = self._prefix_lookups
        return {
            "engine": self.stats(),                      # includes "mesh" when SPMD
            "scheduler": self.scheduler.state_snapshot(),
            "pool": self.pool.state_snapshot(),
            "prefix_share_hit_rate": (self._prefix_hits / lookups) if lookups else None,
            "compiles": list(self._compile_log),         # per-bucket compile causes
            "slo": self.slo_report(),
        }

    #
    # admission + prefill
    #

    def _table_width_buckets(self) -> tuple[int, ...]:
        """Every table width a compiled program may use: the scheduler's
        block buckets, shifted off any width whose gathered capacity equals
        ``sliding_window`` (which ``forward_with_cache`` would interpret as
        the ring layout — the pool always uses the plain slot-=-position
        layout; the window lives in the keep-mask), then extended so a
        shared-prefix resume point plus prefill-bucket padding past the
        largest block bucket still rounds up into the set.  ``stats()``'s
        ``bucket_bound`` counts these widths, so :meth:`_nbb` may never
        produce one outside them."""
        sch, bs = self.scheduler, self.pool.block_size
        W = self.cfg.sliding_window

        def dodge(b: int) -> int:
            return b + 1 if W is not None and self.pool.capacity_tokens(b) == W else b

        widths = {dodge(b) for b in sch.block_buckets}
        # widest dense window a prefill can touch: the largest block-aligned
        # resume point plus a padded prefill bucket (prompts are capped by
        # both the prefill buckets and the admission hard cap on blocks)
        max_prompt = min(
            sch.prefill_buckets[-1],
            self.pool.capacity_tokens(min(self.pool.num_usable, sch.block_buckets[-1])),
        )
        max_resume = ((max_prompt - 1) // bs) * bs if self.prefix_sharing else 0
        need = -(-(max_resume + pick_bucket(max_prompt, sch.prefill_buckets)) // bs)
        b = max(widths)
        while b < need:
            b *= 2
            widths.add(dodge(b))
        return tuple(sorted(widths))

    def _nbb(self, min_blocks: int) -> int:
        """Table-width bucket for ``min_blocks``, from the precomputed
        width set (see :meth:`_table_width_buckets`)."""
        return pick_bucket(min_blocks, self._table_widths)

    def _try_admit(self) -> bool:
        sch = self.scheduler
        if not sch.queue:
            return False
        head = sch.queue[0]
        shared = self._find_shared_prefix(head)
        req = sch.next_admittable(shared_blocks=len(shared))
        if req is None:
            return False
        n_needed = sch.blocks_needed(req)
        table = self.pool.share(shared) + self.pool.alloc(n_needed - len(shared))
        sch.admit(req, table, len(shared))
        if self._tracer is not None:
            self._tracer.end(req.rid, "queued",
                             queue_s=req.admit_t - req.submit_t)
        if self._flight is not None:
            self._flight.record("admit", rid=req.rid, blocks=n_needed,
                                shared_blocks=len(shared),
                                pool_free=self.pool.num_free)
        self._prefill(req)
        return True

    def _find_shared_prefix(self, req: Request) -> list[int]:
        """Longest block-aligned prompt prefix already resident in a live
        request's blocks (the last prompt token always re-prefills, so the
        share is capped one token short of the full prompt)."""
        if not self.prefix_sharing:
            return []
        self._prefix_lookups += 1
        bs = self.pool.block_size
        max_share = ((req.prompt_len - 1) // bs) * bs
        for k in range(max_share, 0, -bs):
            key = tuple(req.prompt[:k].tolist())
            hit = self._prefix_index.get(key)
            if hit is None:
                continue
            if self._prefix_alive(hit):
                self._prefix_hits += 1
                return list(hit[1])
            # stale snapshot (the owner's blocks were freed or sunk, e.g. by
            # sliding-window expiry): sharing it would lease dead block ids
            del self._prefix_index[key]
        return []

    def _prefix_alive(self, hit: tuple[int, tuple[int, ...]]) -> bool:
        """A registered prefix is shareable only while its owner is still
        running AND every snapshot block id is still the live table entry
        (window expiry sinks leading entries without finishing the owner)."""
        rid, blocks = hit
        owner = next((r for r in self.scheduler.running if r.rid == rid), None)
        if owner is None or len(owner.block_table) < len(blocks):
            return False
        return all(t == b != SINK_BLOCK for t, b in zip(owner.block_table, blocks))

    def _register_prefix(self, req: Request) -> None:
        if not self.prefix_sharing:
            return
        bs = self.pool.block_size
        toks = req.prompt.tolist()
        for k in range(bs, ((req.prompt_len - 1) // bs) * bs + 1, bs):
            key = tuple(toks[:k])
            cur = self._prefix_index.get(key)
            if cur is None or not self._prefix_alive(cur):
                self._prefix_index[key] = (req.rid, tuple(req.block_table[: k // bs]))

    def _unregister_prefix(self, req: Request) -> None:
        if self._prefix_index:
            stale = [k for k, (rid, _) in self._prefix_index.items() if rid == req.rid]
            for k in stale:
                del self._prefix_index[k]

    def _prefill(self, req: Request) -> None:
        sch, pool = self.scheduler, self.pool
        bs = pool.block_size
        pos = req.n_shared_blocks * bs                     # block-aligned resume point
        remainder = req.prompt[pos:]
        Tb = sch.prefill_bucket(len(remainder))
        nbb = self._nbb(max(len(req.block_table), -(-(pos + Tb) // bs)))
        toks = np.zeros(Tb, dtype=np.int32)
        toks[: len(remainder)] = remainder
        table = np.full(nbb, SINK_BLOCK, dtype=np.int32)
        table[: len(req.block_table)] = req.block_table
        # scatter back only the freshly written block range; everything else
        # (shared prefix, future decode blocks, bucket padding) sinks
        dest = np.full(nbb, SINK_BLOCK, dtype=np.int32)
        lo, hi = pos // bs, min(len(req.block_table), -(-(pos + Tb) // bs))
        dest[lo:hi] = req.block_table[lo:hi]
        prog, compiled = self._program("prefill", Tb, nbb)
        req.prefill_compiled = compiled
        tr = self._tracer
        if tr is not None:
            tr.begin(req.rid, "prefill", compile=compiled, bucket=[Tb, nbb],
                     shared_blocks=req.n_shared_blocks)
            # the dispatch phase is named by its dominant cost: a fresh
            # program pays the XLA compile here, a cached one only dispatches
            tr.begin(req.rid, "prefill.compile" if compiled else "prefill.dispatch")
        tok, arenas, key, qerr = prog(
            self.params, jnp.asarray(toks)[None], jnp.int32(pos), jnp.int32(len(remainder)),
            pool.arenas, jnp.asarray(table), jnp.asarray(dest),
            jnp.asarray(req.key),
            self._lora_arenas(), jnp.asarray([req.adapter_slot], dtype=jnp.int32),
        )
        pool.set_arenas(arenas)
        if tr is not None:
            tr.end(req.rid, "prefill.compile" if compiled else "prefill.dispatch")
            tr.begin(req.rid, "prefill.host")
        req.key = np.asarray(key)
        req.pos = req.prompt_len                           # prompt KV resident
        tok0 = int(np.asarray(tok)[0])                     # blocks until the device delivers
        req.first_token_t = sch.clock()                    # TTFT = token availability, not dispatch
        if tr is not None:
            tr.end(req.rid, "prefill.host")
            tr.end(req.rid, "prefill", compile=compiled)
        self.prefill_runs += 1
        self.tokens_generated += 1                         # prefill samples token 0
        self._register_prefix(req)
        reg = registry()
        reg.counter("serving.steps.prefill").inc()
        reg.counter("serving.tokens").inc()
        if pool.quantized_kv:
            # measured int8 quantization error of THIS prefill's written
            # blocks (sum|dq-x|/sum|x| over non-sink destinations)
            reg.gauge("serving.kv_quant.rel_err").set(float(np.asarray(qerr)))
        if compiled:
            # cold-compile TTFT outliers must be distinguishable from queue
            # delay: count prefill RUNS that paid a compile (vs
            # serving.compiles.prefill, which counts program builds)
            reg.counter("serving.prefill.compiles").inc()
        if req.n_shared_blocks:
            reg.counter("serving.prefix.shared_blocks").inc(req.n_shared_blocks)
        if self._flight is not None:
            self._flight.record("prefill", rid=req.rid, compiled=compiled,
                                bucket=[Tb, nbb], shared_blocks=req.n_shared_blocks)
        self._emit_token(req, tok0)

    #
    # decode
    #

    def _decode_once(self) -> None:
        sch, pool = self.scheduler, self.pool
        running = list(sch.running)                        # FIFO admission order
        Bb, _nbb_raw = sch.decode_bucket()
        nbb = self._nbb(_nbb_raw)
        bs = pool.block_size
        toks = np.zeros(Bb, dtype=np.int32)
        pos = np.zeros(Bb, dtype=np.int32)
        tables = np.full((Bb, nbb), SINK_BLOCK, dtype=np.int32)
        dest_block = np.full(Bb, SINK_BLOCK, dtype=np.int32)
        dest_slot = np.zeros(Bb, dtype=np.int32)
        keys = np.zeros((Bb, *np.shape(running[0].key)), dtype=np.asarray(running[0].key).dtype)
        slots = np.zeros(Bb, dtype=np.int32)               # padding rows: base slot
        for i, r in enumerate(running):
            wpos = r.prompt_len + len(r.generated) - 1     # slot this step writes
            toks[i] = r.generated[-1]
            pos[i] = wpos
            tables[i, : len(r.block_table)] = r.block_table
            dest_block[i] = r.block_table[wpos // bs]
            dest_slot[i] = wpos % bs
            keys[i] = r.key
            slots[i] = r.adapter_slot
        prog, compiled = self._program("decode", Bb, nbb)
        lora_arenas = self._lora_arenas()
        if self.mesh is not None and self._mesh_collectives is None:
            # census BEFORE the call: the arenas are donated by it
            self._mesh_collectives = self._collective_census(
                ("decode", Bb, nbb), prog,
                (self.params, toks, pos, tables, pool.arenas,
                 dest_block, dest_slot, keys, lora_arenas, slots),
            )
        tr = self._tracer
        if tr is not None:
            for r in running:
                tr.begin(r.rid, "decode", step=self.decode_steps,
                         compile=compiled, bucket=[Bb, nbb])
        nxt, new_keys, arenas = prog(
            self.params, jnp.asarray(toks), jnp.asarray(pos), jnp.asarray(tables),
            pool.arenas, jnp.asarray(dest_block), jnp.asarray(dest_slot),
            jnp.asarray(keys), lora_arenas, jnp.asarray(slots),
        )
        pool.set_arenas(arenas)
        nxt = np.asarray(nxt)
        new_keys = np.asarray(new_keys)
        if tr is not None:                                 # tokens host-visible
            for r in running:
                tr.end(r.rid, "decode")
        if self._flight is not None:
            self._flight.record("decode", step=self.decode_steps,
                                batch=len(running), bucket=[Bb, nbb],
                                compiled=compiled,
                                rids=[r.rid for r in running])
        self.decode_steps += 1
        self._occupancy_sum += len(running)
        self.tokens_generated += len(running)
        reg = registry()
        reg.counter("serving.steps.decode").inc()
        reg.counter("serving.tokens").inc(len(running))
        reg.histogram("serving.batch_occupancy").observe(len(running))
        for i, r in enumerate(running):
            r.key = new_keys[i]
            r.pos = int(pos[i]) + 1
            released = sch.expire_window_blocks(r)
            if released:
                # every registered prefix of r starts at its (just-sunk)
                # leading blocks — scrub before anyone can share them
                self._unregister_prefix(r)
                if self._flight is not None:
                    self._flight.record("window_expire", rid=r.rid,
                                        released=released)
            self._emit_token(r, int(nxt[i]))

    #
    # finishing / results
    #

    def _emit_token(self, req: Request, tok: int) -> None:
        req.generated.append(tok)
        if req.stream_cb is not None:
            req.stream_cb(tok)
        if self.eos_id is not None and tok == self.eos_id:
            self._finish(req, FINISH_EOS)
        elif len(req.generated) >= req.max_new_tokens:
            self._finish(req, FINISH_LENGTH)

    def _finish(self, req: Request, reason: str) -> None:
        never_admitted = req.admit_t is None
        self._unregister_prefix(req)                       # before blocks free
        self.scheduler.finish(req, reason)
        reg = registry()
        reg.counter("serving.requests.completed").inc()
        reg.counter(f"serving.finish.{reason}").inc()
        res = self._result(req)
        if self._tracer is not None:
            if never_admitted:                             # died in the queue
                self._tracer.end(req.rid, "queued", finish_reason=reason)
            self._tracer.instant(req.rid, "finish", reason=reason,
                                 new_tokens=len(req.generated))
        if self._flight is not None:
            self._flight.record("finish", rid=req.rid, reason=reason,
                                new_tokens=len(req.generated))
        if self._slo is not None:
            self._slo.observe(res)
        if res.ttft_s is not None:
            reg.histogram("serving.ttft_s").observe(res.ttft_s)
        if res.tpot_s is not None:
            reg.histogram("serving.tpot_s").observe(res.tpot_s)
        if res.tokens_per_sec is not None:
            reg.histogram("serving.tokens_per_sec").observe(res.tokens_per_sec)
        if req.adapter_id is not None:
            # per-tenant accounting: which adapter consumed the tokens and
            # what latency its requests saw
            reg.counter(f"serving.tenant.{req.adapter_id}.tokens").inc(len(req.generated))
            reg.counter(f"serving.tenant.{req.adapter_id}.requests").inc()
            if res.ttft_s is not None:
                reg.histogram(f"serving.tenant.{req.adapter_id}.ttft_s").observe(res.ttft_s)
            if res.e2e_s is not None:
                reg.histogram(f"serving.tenant.{req.adapter_id}.e2e_s").observe(res.e2e_s)
        if self.telemetry is not None:
            self.telemetry.log_request(
                rid=req.rid,
                prompt_tokens=req.prompt_len,
                new_tokens=len(req.generated),
                finish_reason=reason,
                ttft_s=res.ttft_s,
                tpot_s=res.tpot_s,
                tokens_per_sec=res.tokens_per_sec,
                queue_s=res.queue_s,
                e2e_s=res.e2e_s,
                prefill_compiled=req.prefill_compiled,
                shared_prefix_blocks=req.n_shared_blocks,
            )

    def _result(self, req: Request) -> RequestResult:
        n = len(req.generated)
        ttft = (req.first_token_t - req.submit_t) if req.first_token_t is not None else None
        tpot = None
        tps = None
        if req.first_token_t is not None and req.finish_t is not None and n > 1:
            span = max(req.finish_t - req.first_token_t, 0.0)
            tpot = span / (n - 1)
        if req.finish_t is not None and n and (req.finish_t - req.submit_t) > 0:
            tps = n / (req.finish_t - req.submit_t)
        return RequestResult(
            rid=req.rid,
            prompt=req.prompt,
            new_tokens=tuple(req.generated),
            finish_reason=req.finish_reason or "?",
            ttft_s=ttft,
            tpot_s=tpot,
            tokens_per_sec=tps,
            queue_s=(req.admit_t - req.submit_t) if req.admit_t is not None else None,
            e2e_s=(req.finish_t - req.submit_t) if req.finish_t is not None else None,
            shared_prefix_blocks=req.n_shared_blocks,
            prefill_compiled=req.prefill_compiled,
        )

    def _update_gauges(self) -> None:
        reg = registry()
        reg.gauge("serving.queue_depth").set(len(self.scheduler.queue))
        reg.gauge("serving.running").set(len(self.scheduler.running))
        reg.gauge("serving.pool.utilization").set(self.pool.utilization())
        reg.gauge("serving.pool.free_blocks").set(self.pool.num_free)
        # the post-mortem capacity floor: how close the pool ever came to
        # exhaustion (also in the flight-recorder pool snapshot)
        reg.gauge("serving.pool.free_blocks_low_water").set(self.pool.free_blocks_low_water)

    #
    # compiled bucket programs
    #

    def _lora_arenas(self) -> dict:
        """The registry's stacked factor arenas as a program argument
        ({} without a registry — an empty pytree, zero buffers).  Fetched
        per call so registrations/evictions land without recompiling."""
        return self._registry.arenas if self._registry is not None else {}

    def _static_key(self) -> tuple | None:
        """Global program-cache key for everything baked into a bucket
        program besides its bucket dims — or None (per-engine programs only)
        when a custom ``model_fn`` makes the closure unkeyable.  Mesh
        engines extend the key with the mesh fingerprint (axis layout +
        device ids), so programs compile once per (mesh, bucket) and a
        different device set never reuses a stale placement.  The LoRA
        component is the registry *geometry* only — adapter ids and factor
        values are program arguments, so a batch mixing tenants can never
        grow the program set."""
        if self._forward is not forward_with_cache:
            return None
        import dataclasses

        return (
            tuple(sorted(dataclasses.asdict(self.cfg).items())),
            self.pool.block_size, str(self.pool.dtype), str(self.pool.kv_dtype),
            self.temperature, self.quantized,
            self._registry.geometry if self._registry is not None else None,
            self._mesh_key,
        )

    def _program(self, kind: str, a: int, b: int) -> tuple[Callable, bool]:
        """The bucket program for ``(kind, a, b)`` plus whether THIS lookup
        built it fresh — i.e. the imminent call pays the XLA compile (a
        cached program, per-engine or module-wide, was already traced and
        compiled by its first caller)."""
        key = (kind, a, b)
        prog = self._programs.get(key)
        if prog is not None:
            return prog, False
        static = self._static_key()
        gkey = (static, kind, a, b) if static is not None else None
        prog = _program_cache.get(gkey) if gkey is not None else None
        compiled = prog is None
        if compiled:
            prog = self._build_prefill(a, b) if kind == "prefill" else self._build_decode(a, b)
            # a genuinely new program for this geometry: count the compile
            self.compile_counts[kind] += 1
            self._compile_log.append({"kind": kind, "bucket": [a, b],
                                      "cause": f"new {kind} geometry"})
            registry().counter(f"serving.compiles.{kind}").inc()
            if gkey is not None:
                # LRU-ish bound (the _generate_cache idiom).  64, not 32: a
                # multi-tenant deployment legitimately runs several static
                # configs at once (f32 + int8 pools, per-registry-geometry
                # LoRA variants), and evicting a live config's programs
                # re-pays its compiles on the next request
                if len(_program_cache) >= 64:
                    _program_cache.pop(next(iter(_program_cache)))
                _program_cache[gkey] = prog
        self._programs[key] = prog
        return prog, compiled

    def _jit_kwargs(self, kind: str) -> dict:
        """Extra ``jax.jit`` kwargs for a bucket program: empty single-
        device; explicit in/out shardings under a mesh (params as placed,
        arenas per the pool's NamedSharding, host arrays replicated) so the
        compiled program is pjit-partitioned with per-shard arena donation."""
        if self.mesh is None:
            return {}
        from thunder_tpu.serving.mesh import program_shardings

        return program_shardings(kind, self.params, self.mesh, self.pool.arena_sharding)

    def _collective_census(self, bucket_key: tuple, prog, example_args) -> dict:
        """Collective count of one compiled decode program (mesh mode):
        how many cross-device ops one token step costs.  The census is an
        extra AOT compile, so it is cached module-wide next to the program
        cache — one census per (mesh, static config, bucket) per process —
        and mirrored into the ``serving.mesh.collectives.decode`` gauge."""
        static = self._static_key()
        gkey = ("collectives", static, *bucket_key) if static is not None else None
        got = _collectives_cache.get(gkey) if gkey is not None else None
        if got is None:
            from thunder_tpu.serving.mesh import collective_counts

            got = collective_counts(prog, *example_args)
            if gkey is not None:
                _collectives_cache[gkey] = got
        registry().gauge("serving.mesh.collectives.decode").set(got.get("total", 0))
        return got

    def _fwd_kwargs(self, lora_arenas, slots) -> dict:
        """The forward kwargs one bucket step adds on top of the base call:
        weight quantization (``quantized=``, PR-era int8 matmuls) plus the
        per-request LoRA factors gathered by slot — called inside the jit
        trace, so the gather is part of the compiled step."""
        kw = {"quantized": self.quantized}
        if self._registry is not None:
            kw["lora"] = gather_adapter_slots(lora_arenas, slots)
            kw["lora_scaling"] = self._registry.scaling
        return kw

    def _build_prefill(self, Tb: int, nbb: int) -> Callable:
        cfg, fwd, temp = self.cfg, self._forward, self.temperature
        qkv = self.pool.quantized_kv
        cdtype = jnp.dtype(self.pool.dtype)
        cap = self.pool.capacity_tokens(nbb)
        cos_all, sin_all = build_rope_cache(cfg, cap)

        @partial(jax.jit, donate_argnums=(4,), **self._jit_kwargs("prefill"))
        def prefill(params, toks, pos, n_real, arenas, table, dest, key, lora, slot):
            if qkv:
                kd, vd = gather_dense_q(
                    arenas["k"], arenas["v"], arenas["k_scale"], arenas["v_scale"],
                    table[None, :], cdtype,
                )
            else:
                kd, vd = gather_dense(arenas["k"], arenas["v"], table[None, :])
            logits, cache = fwd(
                params, toks, pos, {"k": kd, "v": vd}, cos_all, sin_all, cfg,
                **self._fwd_kwargs(lora, slot),
            )
            last = jax.lax.dynamic_index_in_dim(logits, n_real - 1, axis=1, keepdims=False)
            key, sub = jax.random.split(key)
            tok = sample_token(last, temp, sub)            # (1,) — solo-prefill parity
            if qkv:
                k_arena, k_scale, k_err = scatter_blocks_q(
                    arenas["k"], arenas["k_scale"], cache["k"], dest)
                v_arena, v_scale, v_err = scatter_blocks_q(
                    arenas["v"], arenas["v_scale"], cache["v"], dest)
                arenas = {"k": k_arena, "v": v_arena,
                          "k_scale": k_scale, "v_scale": v_scale}
                qerr = 0.5 * (k_err + v_err)
            else:
                arenas = {"k": scatter_blocks(arenas["k"], cache["k"], dest),
                          "v": scatter_blocks(arenas["v"], cache["v"], dest)}
                qerr = jnp.float32(0.0)
            return tok, arenas, key, qerr

        return prefill

    def _build_decode(self, Bb: int, nbb: int) -> Callable:
        cfg, fwd, temp = self.cfg, self._forward, self.temperature
        qkv = self.pool.quantized_kv
        cdtype = jnp.dtype(self.pool.dtype)
        cap = self.pool.capacity_tokens(nbb)
        cos_all, sin_all = build_rope_cache(cfg, cap)

        @partial(jax.jit, donate_argnums=(4,), **self._jit_kwargs("decode"))
        def decode(params, toks, pos, tables, arenas, dest_block, dest_slot, keys,
                   lora, slots):
            if qkv:
                kd, vd = gather_dense_q(
                    arenas["k"], arenas["v"], arenas["k_scale"], arenas["v_scale"],
                    tables, cdtype,
                )
            else:
                kd, vd = gather_dense(arenas["k"], arenas["v"], tables)
            logits, cache = fwd(
                params, toks[:, None], pos, {"k": kd, "v": vd}, cos_all, sin_all, cfg,
                **self._fwd_kwargs(lora, slots),
            )
            sp = jax.vmap(jax.random.split)(keys)          # per-request key chains
            new_keys, subs = sp[:, 0], sp[:, 1]
            # (1, V) per row under vmap == the unbatched B=1 generate() draw
            nxt = jax.vmap(lambda l, k: sample_token(l[None], temp, k)[0])(
                logits[:, 0], subs
            )
            kc = cache["k"].transpose(1, 0, 2, 3, 4)       # (B, L, ng, cap, hs)
            vc = cache["v"].transpose(1, 0, 2, 3, 4)
            pick = jax.vmap(
                lambda c, p: jax.lax.dynamic_index_in_dim(c, p, axis=2, keepdims=False)
            )
            if qkv:
                # the picked values are THIS step's freshly computed K/V (the
                # dense cache write at pos), so quantize-on-scatter sees exact
                # inputs — no requantization drift across steps
                k_arena, k_scale = scatter_token_q(
                    arenas["k"], arenas["k_scale"], pick(kc, pos), dest_block, dest_slot)
                v_arena, v_scale = scatter_token_q(
                    arenas["v"], arenas["v_scale"], pick(vc, pos), dest_block, dest_slot)
                arenas = {"k": k_arena, "v": v_arena,
                          "k_scale": k_scale, "v_scale": v_scale}
            else:
                arenas = {"k": scatter_token(arenas["k"], pick(kc, pos), dest_block, dest_slot),
                          "v": scatter_token(arenas["v"], pick(vc, pos), dest_block, dest_slot)}
            return nxt, new_keys, arenas

        return decode


def serve(model_fn, params, cfg, **kwargs) -> ServingEngine:
    """Builds a :class:`ServingEngine` over ``model_fn`` (``None`` → the
    in-tree ``models.generate.forward_with_cache``).  See
    :class:`ServingEngine` for the knobs; nothing about constructing an
    engine touches any other compiled program (strictly additive).

    Mesh serving: ``serve(None, params, cfg, mesh=mesh)`` makes the whole
    engine SPMD — params are placed once (``shardings=`` overrides the
    default llama TP×FSDP rules), the paged K/V arenas shard their heads
    dim over ``tp`` (:func:`thunder_tpu.distributed.kv_cache_spec`), and
    every bucket program compiles once per (mesh, bucket) with explicit
    shardings and per-shard arena donation.  Served tokens stay
    bit-identical to solo ``generate(..., mesh=mesh)`` on the same mesh.

    Multi-tenant serving: ``kv_dtype="int8"`` stores the KV block arenas
    quantized (~``hs*itemsize/(hs+4)``x the resident requests per arena
    byte, quantize-on-scatter / dequant-on-gather inside the bucket
    programs, measured error in the ``serving.kv_quant.rel_err`` gauge);
    ``lora=AdapterRegistry(...)`` lets ``submit(..., adapter_id=...)``
    route each request through a registered LoRA adapter — batches freely
    mix tenants, and the compiled-program set grows only with the registry
    *geometry* (rank, slots, targets), never with adapter ids."""
    return ServingEngine(params, cfg, model_fn=model_fn, **kwargs)
