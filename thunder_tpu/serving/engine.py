"""Serving engine front-end: ``tt.serve(...)`` → :class:`ServingEngine`.

Continuous (in-flight) batching over the compiled decode step: independent
requests share one bucketed decode program, join the batch the step after
their prefill, and leave it the step they finish — batch occupancy is a
scheduling property, not a caller-visible one.  The engine composes the
pieces the repo already has:

- ``models.generate.forward_with_cache`` is the model step — the pool's
  gathered block views reassemble exactly the dense cache layout it
  consumes, and per-row vector positions (the speculative-decode machinery)
  drive mixed-progress batches;
- the **paged pool** (:mod:`serving.kv_pool`) owns cache memory; every
  program donates the arenas so updates stay in place (PR 4);
- the **scheduler** (:mod:`serving.scheduler`) owns admission, FIFO order,
  deadlines, and the bucket sets that bound recompiles (absorbed by the
  PR-1 dispatch cache when the model fn is a ``tt.jit`` product);
- **observability** (PRs 2–3): queue/occupancy/pool gauges, TTFT/TPOT and
  tokens/sec histograms in the metrics registry, per-request JSONL records
  through :class:`observability.telemetry.StepLogger`;
- **multi-tenancy**: ``kv_dtype="int8"`` stores the arenas quantized
  (:mod:`serving.quant`), and ``lora=AdapterRegistry(...)`` routes each
  request through a per-request LoRA adapter (:mod:`serving.lora`) — both
  live inside the same bucket programs, keyed only by storage dtype and
  registry geometry.

Reproducibility contract: each request carries its own PRNG key chain and
splits it exactly like a solo ``generate()`` call (one split at prefill, one
per decode step; per-row sampling under ``vmap`` is bit-equivalent to the
unbatched call), so a request's tokens do not depend on what else shares
the batch — and greedy tokens match ``generate()`` exactly.

The drive loop is an explicit, threadless **event loop** with two lanes
(``async_step=True``, the default):

- the **decode lane** dispatches the jitted decode program for batch *k*
  and — exploiting JAX's async dispatch, which the CPU backend shares —
  returns to the host immediately; admissions, scheduling, chunk
  dispatches, and token streaming for batch *k−1* all run while the device
  computes, and the next ``step()`` harvests the in-flight tokens (the
  only host block, measured into ``serving.decode.stall_s`` and the
  ``serving.step.overlap_frac`` gauge);
- the **prefill lane** splits prompts longer than ``prefill_chunk`` into
  block-aligned pow-2 chunks (program kind ``prefill_chunk``, bounded by
  the same ``_table_widths``/bucket accounting) and dispatches at most one
  chunk per request per step, interleaved between decode dispatches — a
  long prompt can no longer stall TPOT for running requests.

``async_step=False`` keeps the original fully synchronous path
byte-identical (admit → prefill → one decode → block on host
materialization); either way ``step()`` runs one scheduler iteration and
``run()``/``drain()`` loop it.  Served tokens are bit-identical across the
two modes and to solo ``generate()`` — deferred materialization reorders
host work, never device math, and each request's PRNG chain still splits
exactly like the solo path.  No threads — integrate into any host loop.

Serving-plane observability (all off by default; the off path is an
``is None`` check per touch point):

- ``trace=True`` / ``THUNDER_TPU_TRACE_SERVING=1`` — per-request lifecycle
  spans (queued / prefill split into compile-or-dispatch + host / every
  decode step / finish) plus ``engine.step`` spans into the shared event
  ring; ``tt.export_chrome_trace`` merges them with the compile-pipeline
  rows into one Perfetto timeline (:mod:`observability.tracing`);
- ``slo={"ttft_s": ..., "tpot_s": ...}`` — windowed good/bad counters and
  burn-rate gauges per finished request, surfaced by
  :meth:`ServingEngine.slo_report` (:mod:`observability.slo`);
- ``flight_recorder=True`` / ``THUNDER_TPU_FLIGHT_RECORDER=1`` — bounded
  ring of engine events + scheduler/pool state, auto-dumped to JSON when
  ``step()`` raises, exportable any time via ``tt.flight_record(path)``
  (:mod:`observability.flight`).
"""
from __future__ import annotations

import time
from dataclasses import dataclass
from functools import partial
from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from thunder_tpu.models.generate import (
    build_rope_cache,
    forward_with_cache,
    sample_token,
)
from thunder_tpu.observability.config import (
    flight_recorder_env_enabled,
    serving_trace_env_enabled,
)
from thunder_tpu.observability.flight import FlightRecorder
from thunder_tpu.observability.goodput import resolve_goodput
from thunder_tpu.observability.metrics import registry
from thunder_tpu.observability.slo import resolve_slo
from thunder_tpu.observability.tracing import RequestTracer
from thunder_tpu.serving.faults import (
    CLASS_REQUEST,
    CLASS_TRANSIENT,
    FP_DECODE,
    FP_HARVEST,
    FP_PREFILL,
    FP_SCATTER,
    RecoveryError,
    RetryPolicy,
    WatchdogTimeout,
    classify_fault,
    fault_cause,
    resolve_fault_plan,
)
from thunder_tpu.serving.kv_pool import (
    SINK_BLOCK,
    PagedKVPool,
    PrefixIndex,
    chunk_tables,
    dest_for_pos,
    gather_dense,
    scatter_blocks,
    scatter_token,
)
from thunder_tpu.serving.lora import gather_adapter_slots
from thunder_tpu.serving.quant import (
    gather_dense_q,
    scatter_blocks_q,
    scatter_token_q,
)
from thunder_tpu.serving.scheduler import (
    FINISH_DEADLINE,
    FINISH_EOS,
    FINISH_ERROR,
    FINISH_EVICTED,
    FINISH_LENGTH,
    AdmissionError,
    Request,
    Scheduler,
    pick_bucket,
)

__all__ = [
    "serve",
    "ServingEngine",
    "RequestHandle",
    "RequestResult",
    "AdmissionError",
    "EngineStalledError",
    "RecoveryError",
]


class EngineStalledError(RuntimeError):
    """``drain()``/``result()`` could not make progress: requests remain
    queued/running but ``step()`` did no work (e.g. blocks leaked outside
    the scheduler, or a queue head that can never fit the live pool).
    Carries the flight-recorder state snapshot — queued/running request
    rows, pool free/lease counts, compile log — as ``.state`` and inlines
    the headline numbers in the message so a stall is diagnosable from the
    traceback alone.  Under dp-replicated serving the router sets
    ``replica`` to the stalled engine's index and passes THAT replica's
    flight state, so a fleet stall names its culprit instead of assuming
    one engine."""

    def __init__(self, msg: str, state: dict | None = None, *,
                 replica: int | None = None):
        self.replica = replica
        if replica is not None:
            msg = f"replica {replica}: {msg}"
        self.state = state or {}
        sched = self.state.get("scheduler", {})
        pool = self.state.get("pool", {})
        rows = sched.get("requests", [])
        rids = {
            "queued": [r["rid"] for r in rows if r.get("state") == "queued"],
            "running": [r["rid"] for r in rows if r.get("state") == "running"],
        }
        detail = (
            f" [queued rids={rids['queued']} running rids={rids['running']} "
            f"pool free={pool.get('num_free')}/{pool.get('num_blocks')} "
            f"leased={pool.get('leased_blocks')} shared={pool.get('shared_blocks')}]"
            if self.state else ""
        )
        super().__init__(msg + detail)


@dataclass(frozen=True)
class RequestResult:
    """Structured outcome of one served request."""

    rid: int
    prompt: np.ndarray
    new_tokens: tuple[int, ...]
    finish_reason: str                      # length | eos | deadline | evicted | error
    ttft_s: float | None                    # submit → first token
    tpot_s: float | None                    # mean per-token after the first
    tokens_per_sec: float | None
    queue_s: float | None                   # submit → admission
    e2e_s: float | None                     # submit → finish wall time
    shared_prefix_blocks: int
    prefill_compiled: bool = False          # the prefill run paid an XLA compile
    error: dict | None = None               # structured cause when quarantined
    tokens_recomputed: int = 0              # prompt positions re-dispatched by replay
    recompute_causes: tuple = ()            # why (goodput waste-cause names)

    @property
    def tokens(self) -> np.ndarray:
        """Full sequence (prompt + generated), the solo ``generate()`` row."""
        return np.concatenate([self.prompt, np.asarray(self.new_tokens, dtype=np.int32)])


class RequestHandle:
    """Caller's view of a submitted request."""

    def __init__(self, engine: "ServingEngine", req: Request):
        self._engine = engine
        self._req = req

    @property
    def rid(self) -> int:
        return self._req.rid

    @property
    def state(self) -> str:
        return self._req.state

    def done(self) -> bool:
        return self._req.state == "finished"

    def tokens_so_far(self) -> tuple[int, ...]:
        return tuple(self._req.generated)

    def result(self, *, drive: bool = True) -> RequestResult:
        """The structured result; with ``drive`` (default) steps the engine
        until this request finishes."""
        while drive and not self.done():
            if not self._engine.step() and not self.done():
                raise EngineStalledError(
                    f"engine stalled with request {self.rid} still {self._req.state}",
                    self._engine._flight_state(),
                )
        if not self.done():
            raise RuntimeError(f"request {self.rid} is still {self._req.state}")
        return self._engine._result(self._req)


# jitted bucket programs, shared across engines with identical static
# configuration (the _generate_cache idiom): an engine restart — or a test
# suite full of small engines — reuses steady-state compiled programs
_program_cache: dict = {}

# one decode program's collective census per (mesh, static config, bucket):
# the census pays an extra AOT compile, so it is module-cached like programs
_collectives_cache: dict = {}


class ServingEngine:
    """Continuous-batching inference engine over a paged KV pool."""

    def __init__(
        self,
        params,
        cfg,
        *,
        model_fn: Callable | None = None,
        block_size: int = 16,
        num_blocks: int = 64,
        max_batch: int = 8,
        max_queue: int = 64,
        temperature: float = 0.0,
        eos_id: int | None = None,
        quantized: bool = False,
        cache_dtype=None,
        kv_dtype=None,
        lora=None,
        prefix_sharing: bool = True,
        clock: Callable[[], float] | None = None,
        telemetry=None,
        batch_buckets: Sequence[int] | None = None,
        block_buckets: Sequence[int] | None = None,
        prefill_buckets: Sequence[int] | None = None,
        trace: bool | None = None,
        slo=None,
        flight_recorder=None,
        mesh=None,
        shardings=None,
        attn: str = "auto",
        async_step: bool = True,
        prefill_chunk: int | None = None,
        fault_plan=None,
        retry: RetryPolicy | None = None,
        watchdog_timeout_s: float | None = None,
        speculative=None,
        replica_id: int | None = None,
        decode_steps: int = 1,
        sessions=None,
        priorities=None,
        constraints=None,
        goodput=None,
    ):
        if shardings is not None and mesh is None:
            raise ValueError("shardings= requires mesh= (param placement needs a mesh)")
        self.async_step = bool(async_step)
        if prefill_chunk is not None and not self.async_step:
            raise ValueError(
                "prefill_chunk= requires async_step=True — the chunked "
                "prefill lane lives in the async event loop"
            )
        self.mesh = mesh
        if mesh is not None:
            # SPMD serving: place params once (tp_fsdp-style rules unless
            # the caller brings their own), shard the KV arenas heads-over-
            # tp, and compile every bucket program with explicit shardings
            from thunder_tpu.serving.mesh import mesh_fingerprint, place_params

            params = place_params(params, mesh, shardings)
            # the param placement is baked into every program's
            # in_shardings, so it is part of the program identity too
            self._mesh_key = (
                mesh_fingerprint(mesh),
                tuple(str(x.sharding.spec) for x in jax.tree_util.tree_leaves(params)),
            )
        else:
            self._mesh_key = None
        self._mesh_collectives: dict | None = None         # lazy decode census
        self.params = params
        self.cfg = cfg
        self._forward = model_fn if model_fn is not None else forward_with_cache
        self.temperature = float(temperature)
        self.eos_id = eos_id
        self.quantized = bool(quantized)
        self.prefix_sharing = bool(prefix_sharing)
        dtype = cache_dtype if cache_dtype is not None else params["wte"].dtype
        self.pool = PagedKVPool(
            cfg, num_blocks=num_blocks, block_size=block_size, dtype=dtype,
            kv_dtype=kv_dtype, mesh=mesh,
        )
        # decode attention path, resolved ONCE at construction (each engine
        # builds exactly one decode program kind, so the program-set bound
        # in stats() is unchanged): "paged" runs the Pallas flash-decoding
        # kernel straight off the block arena (interpret mode off-TPU),
        # "gather" keeps the dense gather/scatter pair, "auto" takes the
        # kernel when it is structurally supported AND Pallas is enabled on
        # this backend (TPU, or THUNDER_TPU_PALLAS_INTERPRET=1), else falls
        # back to gather and counts serving.attn.fallback_steps
        if attn not in ("auto", "paged", "gather"):
            raise ValueError(
                f"attn= must be 'auto', 'paged', or 'gather', got {attn!r}")
        from thunder_tpu.executors.pallasex import paged_available
        from thunder_tpu.serving.paged_attention import paged_supported

        ok, why = paged_supported(cfg, self._forward is forward_with_cache, mesh)
        self._attn_requested = attn
        if attn == "paged":
            if not ok:
                raise ValueError(f"attn='paged' is unsupported here: {why}")
            self.attn, self._attn_fallback_reason = "paged", None
        elif attn == "auto" and ok and paged_available():
            self.attn, self._attn_fallback_reason = "paged", None
        elif attn == "auto":
            self.attn = "gather"
            self._attn_fallback_reason = why or "pallas disabled on this backend"
        else:
            self.attn, self._attn_fallback_reason = "gather", None
        self.attn_kernel_steps = 0
        self.attn_fallback_steps = 0
        # multi-tenant LoRA: a bounded AdapterRegistry shared across engines;
        # its stacked factor arenas are program *arguments* (register/evict
        # are data writes), only its geometry enters the program identity
        self._registry = lora
        if lora is not None:
            for dim in ("n_layer", "n_head", "n_query_groups", "head_size", "n_embd"):
                if getattr(lora.cfg, dim) != getattr(cfg, dim):
                    raise ValueError(
                        f"lora registry was built for {dim}="
                        f"{getattr(lora.cfg, dim)} but the engine serves "
                        f"{dim}={getattr(cfg, dim)}"
                    )
            if mesh is not None:
                lora.place(mesh)   # placed once per mesh, like params
        # speculative serving: a draft KV block arena BESIDE the target
        # arena — its own PagedKVPool storage (same dtype/quantization/mesh
        # sharding), but block ids are allocated once per request from the
        # target pool and index both arenas (the draft pool's free list is
        # never consulted), so the allocator/prefix machinery stays single
        # device-resident multi-step decode: N tokens per host visit via an
        # in-program lax.scan over the decode body.  Stored as
        # n_decode_steps (self.decode_steps is the dispatch counter); N=1
        # is byte-identical to the single-step engine (same program kinds,
        # same static keys, shared module program cache).
        self.n_decode_steps = int(decode_steps)
        if self.n_decode_steps < 1:
            raise ValueError(f"decode_steps= must be >= 1, got {decode_steps}")
        if speculative is not None and self.n_decode_steps > 1:
            from thunder_tpu.serving.speculative import multi_step_supported

            ok_ms, why_ms = multi_step_supported(speculative)
            if not ok_ms:
                raise ValueError(
                    f"decode_steps={self.n_decode_steps} with speculative= "
                    f"is unsupported: {why_ms}"
                )
        self.spec = speculative
        if speculative is not None:
            from thunder_tpu.serving.speculative import validate_spec

            validate_spec(
                speculative, cfg,
                custom_forward=self._forward is not forward_with_cache,
                sliding_window=cfg.sliding_window,
            )
            if mesh is not None:
                from thunder_tpu.serving.mesh import place_params as _pp

                speculative.draft_params = _pp(speculative.draft_params, mesh, None)
            self.draft_pool = PagedKVPool(
                speculative.draft_cfg, num_blocks=num_blocks,
                block_size=block_size, dtype=dtype,
                # the draft arena may quantize independently of the target
                # (SpecConfig.draft_kv_dtype; None inherits kv_dtype)
                kv_dtype=(speculative.draft_kv_dtype
                          if speculative.draft_kv_dtype is not None else kv_dtype),
                mesh=mesh,
            )
        else:
            self.draft_pool = None
        self.scheduler = Scheduler(
            self.pool,
            max_batch=max_batch,
            max_queue=max_queue,
            clock=clock,
            batch_buckets=batch_buckets,
            block_buckets=block_buckets,
            prefill_buckets=prefill_buckets,
            sliding_window=cfg.sliding_window,
            prefill_chunk=prefill_chunk,
            # a speculative round's draft scan writes up to K slots past the
            # last committed token — admission must reserve that overshoot;
            # a multi-step decode visit likewise writes up to N-1 slots past
            # the first token of the visit before the host sees any of them
            reserve_extra_tokens=(speculative.K if speculative is not None
                                  else self.n_decode_steps - 1),
            decode_horizon=self.n_decode_steps,
        )
        if getattr(cfg, "learned_pos_embedding", False):
            # wpe has block_size rows and dynamic_slice clamps silently past
            # them: cap the bucket sets so no program's dense capacity can
            # reach beyond the learned table
            sch = self.scheduler
            blk = tuple(
                b for b in sch.block_buckets
                if self.pool.capacity_tokens(b) <= cfg.block_size
            )
            assert blk, (
                f"block_size(cfg)={cfg.block_size} admits no pool bucket at "
                f"pool block_size={block_size} with learned position embeddings"
            )
            sch.block_buckets = blk
            sch.prefill_buckets = tuple(
                t for t in sch.prefill_buckets if t <= cfg.block_size
            ) or (cfg.block_size,)
            # a block-aligned resume point near block_size would push the
            # padded prefill window past the wpe table (dynamic_slice clamps
            # the start — real tokens would read shifted embeddings), so
            # suffix prefill is off the table for learned-pos models; that
            # rules out chunked prefill too (every chunk past the first is a
            # suffix resume)
            self.prefix_sharing = False
            sch.prefill_chunk = None
        self._table_widths = self._table_width_buckets()
        # chunked prefill resolves its kernel/gather path INDEPENDENTLY of
        # decode (stats()["attn"]["kinds"] reports both): the paged chunk
        # writer lands whole (L, ng, bs, hs) block slabs built from the
        # chunk's fresh K/V alone, so every chunk boundary must fall on a
        # block edge — the chunk width and every prefill bucket must be
        # multiples of the pool block size (the FINAL piece runs the
        # ``prefill`` kind and may stay ragged).  Sliding-window models
        # keep the gather chunk (the multi-query kernel has no windowed
        # keep-mask), and speculative engines keep ``spec_prefill_chunk``
        # (it writes the draft arena too).  Resolution happens ONCE here,
        # so the program-identity story is unchanged: the paged chunk kind
        # REPLACES the gather chunk kind 1:1 per engine and the
        # bucket_bound formula in stats() is untouched.
        sch = self.scheduler
        if self.attn != "paged":
            chunk_why = (self._attn_fallback_reason
                         if self._attn_requested == "auto"
                         else "attn='gather' requested")
        elif self.spec is not None:
            chunk_why = "speculative prefill writes the draft arena (gather chunk)"
        elif cfg.sliding_window is not None:
            chunk_why = "sliding-window keep-mask is decode-only"
        elif sch.prefill_chunk is not None and sch.prefill_chunk % block_size:
            chunk_why = (f"prefill_chunk={sch.prefill_chunk} not a multiple "
                         f"of block_size={block_size}")
        elif any(t % block_size for t in sch.prefill_buckets):
            chunk_why = (f"prefill_buckets={tuple(sch.prefill_buckets)} not "
                         f"all multiples of block_size={block_size}")
        else:
            chunk_why = None
        self.attn_chunk = "paged" if chunk_why is None else "gather"
        self._attn_chunk_fallback_reason = chunk_why
        # per-kind [kernel, fallback] step counters beside the decode-only
        # aggregates (attn_kernel_steps/attn_fallback_steps keep their
        # pre-existing decode semantics)
        self._attn_steps = {"decode": [0, 0], "prefill_chunk": [0, 0]}
        # fault tolerance: the chaos plan (None = unarmed — one `is None`
        # check per fault point, compiled programs byte-identical either
        # way), the retry/backoff policy, and the harvest watchdog on the
        # scheduler's (injectable) clock
        self._faults = resolve_fault_plan(fault_plan)
        self._retry = retry if retry is not None else RetryPolicy()
        self.watchdog_timeout_s = watchdog_timeout_s
        self._retry_streak = 0                             # consecutive transient faults
        self.recoveries = 0
        # telemetry: a StepLogger, a path for one, or None
        self._owns_telemetry = isinstance(telemetry, (str, bytes)) or hasattr(telemetry, "__fspath__")
        if self._owns_telemetry:
            from thunder_tpu.observability.telemetry import StepLogger

            telemetry = StepLogger(telemetry, meta={
                "kind": "serving", "block_size": block_size, "num_blocks": num_blocks,
                "max_batch": max_batch, "model": getattr(cfg, "name", "?"),
            })
        self.telemetry = telemetry
        self._handles: dict[int, RequestHandle] = {}
        # dp replication: which engine lane this is (None = solo); the
        # router stamps it into stats/flight/spans so every artifact of a
        # replicated fleet names its lane
        self.replica_id = replica_id
        self._prefix_index = PrefixIndex(self.pool.block_size)
        # stateful serving: resident-session table (parked prefix blocks),
        # priority gate (admission policy + preemption), and the
        # constrained-decoding knob.  All three are host policy/data —
        # only `constraints` touches program identity (one extra mask
        # argument), and it collapses to None on the off-path so default
        # engines share cached programs byte-identically.
        from thunder_tpu.serving.priority import resolve_priorities
        from thunder_tpu.serving.sessions import resolve_sessions

        self._sessions = resolve_sessions(sessions, self.pool, self._prefix_index)
        if self._sessions is not None and not self.prefix_sharing:
            raise ValueError(
                "sessions= requires prefix_sharing: session re-attach rides "
                "the shared-prefix admission path")
        self._priorities = resolve_priorities(priorities)
        self._constraints = bool(constraints)
        if self._constraints and speculative is not None:
            raise ValueError(
                "constraints= with speculative= is unsupported: the verify "
                "lane has no mask argument (use the plain decode lane)")
        # logit width every constraint mask must match (lm_head output)
        self._vocab = int(getattr(cfg, "padded_vocab_size", None)
                          or getattr(cfg, "vocab_size"))
        self._mask_ones: dict[tuple, np.ndarray] = {}
        self._hit_owner: int | None = None  # owner rid of the last live prefix hit
        self.preempted = 0
        self._programs: dict[tuple, Callable] = {}
        self._closed = False
        # drive-loop accounting (mirrored into the registry as it changes)
        self.decode_steps = 0
        self.prefill_runs = 0
        self.chunk_runs = 0
        self.step_calls = 0
        self.tokens_generated = 0
        self._occupancy_sum = 0
        self.compile_counts = {"prefill": 0, "prefill_chunk": 0,
                               "prefill_chunk_paged": 0, "decode": 0,
                               "decode_paged": 0, "decode_multi": 0,
                               "decode_multi_paged": 0, "spec_prefill": 0,
                               "spec_prefill_chunk": 0, "draft_decode": 0,
                               "verify": 0, "verify_paged": 0}
        # host-visit amortization accounting: one host_visit per decode-lane
        # harvest (a visit serves up to n_decode_steps tokens per row)
        self.host_visits = 0
        self.decode_lane_tokens = 0
        # async lanes: the in-flight futures table — one deferred decode
        # record plus any deferred prefill-piece records, harvested at the
        # top of the next step (the only place the host blocks)
        self._inflight_decode: dict | None = None
        self._inflight_prefill: list[dict] = []
        self._stall_s_sum = 0.0
        self._overlap_frac_sum = 0.0
        self._overlap_obs = 0
        # chained decode inputs: while the batch and tables are unchanged,
        # each decode step consumes the previous step's device outputs
        # directly (no host->device transfer); see _decode_dispatch
        self._decode_state: dict | None = None
        # the speculative lane's chained round inputs (toks=y, pos+n_emit)
        # plus its acceptance accounting; see serving.speculative
        self._spec_state: dict | None = None
        self.spec_rounds = 0
        self.spec_draft_tokens = 0
        self.spec_accepted_tokens = 0
        self._spec_accept_hist = (
            np.zeros(speculative.K + 1, dtype=np.int64)
            if speculative is not None else None
        )
        # per-step metric handles resolved once (registry().reset() zeroes
        # values but keeps objects, so these survive observability resets)
        reg0 = registry()
        self._m_steps_decode = reg0.counter("serving.steps.decode")
        self._m_occupancy = reg0.histogram("serving.batch_occupancy")
        self._m_tokens = reg0.counter("serving.tokens")
        self._m_queue_depth = reg0.gauge("serving.queue_depth")
        self._m_running = reg0.gauge("serving.running")
        self._m_pool_util = reg0.gauge("serving.pool.utilization")
        self._m_pool_free = reg0.gauge("serving.pool.free_blocks")
        self._m_pool_low_water = reg0.gauge("serving.pool.free_blocks_low_water")
        self._m_attn_kernel = reg0.counter("serving.attn.kernel_steps")
        self._m_attn_fallback = reg0.counter("serving.attn.fallback_steps")
        self._m_host_visits = reg0.counter("serving.decode.host_visits")
        self._m_pool_occ = reg0.gauge("serving.pool.occupancy_frac")
        if speculative is not None:
            self._m_spec_rounds = reg0.counter("serving.spec.rounds")
            self._m_spec_accepted = reg0.counter("serving.spec.accepted_tokens")
            self._m_spec_accept_len = reg0.histogram("serving.spec.accept_len")
        if self.async_step:
            self._m_stall = reg0.histogram("serving.decode.stall_s")
            self._m_overlap = reg0.gauge("serving.step.overlap_frac")
        self._compile_log: list[dict] = []               # per-bucket compile causes
        # serving-plane observability (all off by default; the off path is
        # one `is None` check per touch point — measured by bench.py tracing)
        if trace is None:
            trace = serving_trace_env_enabled()
        self._tracer = RequestTracer() if trace else None
        self._slo = resolve_slo(slo)
        # goodput ledger (ISSUE 18): host-side classification of every
        # dispatched device token-position; never enters _static_key, so
        # goodput=True compiles zero additional programs
        self._goodput = resolve_goodput(goodput)
        if flight_recorder is None:
            flight_recorder = flight_recorder_env_enabled()
        if isinstance(flight_recorder, FlightRecorder):
            flight_recorder.state_provider = self._flight_state
            self._flight = flight_recorder
        else:
            self._flight = (
                FlightRecorder(state_provider=self._flight_state)
                if flight_recorder else None
            )
        if mesh is not None:
            # serving.mesh.* gauges: static facts land at construction; the
            # decode collective count follows once a decode program exists
            reg = registry()
            reg.gauge("serving.mesh.devices").set(int(mesh.devices.size))
            for a in mesh.axis_names:
                reg.gauge(f"serving.mesh.axis.{a}").set(int(mesh.shape[a]))
            reg.gauge("serving.mesh.arena_shard_bytes").set(self.pool.per_shard_bytes())

    #
    # public API
    #

    def submit(
        self,
        prompt,
        *,
        max_new_tokens: int,
        deadline: float | None = None,
        key=None,
        stream_cb: Callable[[int], Any] | None = None,
        adapter_id: str | None = None,
        session_id: str | None = None,
        priority: str | None = None,
        constraint=None,
    ) -> RequestHandle:
        """Enqueues one request; returns immediately with a handle.

        ``deadline`` is seconds from now; past it the request finishes with
        reason ``"deadline"`` wherever it is.  ``key`` seeds the request's
        private sampling chain (default ``PRNGKey(0)``, like ``generate``).
        ``stream_cb`` receives each generated token id, in order, as soon as
        the host sees it.  ``adapter_id`` routes the request through a LoRA
        adapter registered in the engine's ``lora=`` registry (resolved to
        its slot here, at admission time — an unknown id raises KeyError
        immediately, never a silent base fallback).  Raises
        :class:`AdmissionError` when the wait queue is full or the request
        can never fit the pool.

        ``session_id`` (needs ``sessions=``) parks the finished turn's
        prefix blocks so the next turn re-attaches them; ``priority``
        (``"high"``/``"normal"``/``"low"``, needs ``priorities=``) orders
        the queue, feeds the SLO admission gate, and marks preemption
        victims; ``constraint`` (a :class:`serving.constrain.Constraint`,
        needs ``constraints=True``) masks every sampled token through the
        request's host-side automaton."""
        if self._closed:
            raise RuntimeError("engine is shut down")
        if key is None:
            key = jax.random.PRNGKey(0)
        adapter_slot = 0
        if adapter_id is not None:
            if self._registry is None:
                raise ValueError(
                    f"adapter_id={adapter_id!r} requires an engine built with "
                    f"lora=AdapterRegistry(...)"
                )
            adapter_slot = self._registry.slot(adapter_id)
        if session_id is not None and self._sessions is None:
            raise ValueError(
                f"session_id={session_id!r} requires an engine built with "
                f"sessions= (e.g. sessions=True)")
        from thunder_tpu.serving.priority import priority_level

        if priority is not None and self._priorities is None:
            raise ValueError(
                f"priority={priority!r} requires an engine built with "
                f"priorities= (e.g. priorities=True)")
        priority_cls, level = priority_level(priority)
        if constraint is not None:
            if not self._constraints:
                raise ValueError(
                    "constraint= requires an engine built with constraints=True")
            if int(constraint.vocab_size) != self._vocab:
                raise ValueError(
                    f"constraint.vocab_size={constraint.vocab_size} != model "
                    f"logit width {self._vocab}")
            # multi-step decode needs exact masks N draws ahead; fail at
            # submit, not mid-scan (ConstraintLookaheadError propagates)
            if self.n_decode_steps > 1:
                constraint.masks(self.n_decode_steps)
        reg = registry()
        try:
            req = self.scheduler.submit(
                prompt, max_new_tokens, key=key, deadline_s=deadline, stream_cb=stream_cb,
                adapter_id=adapter_id, adapter_slot=adapter_slot,
                session_id=session_id, priority=level,
                priority_class=priority_cls, constraint=constraint,
            )
        except AdmissionError:
            reg.counter("serving.requests.rejected").inc()
            raise
        reg.counter("serving.requests.submitted").inc()
        reg.gauge("serving.queue_depth").set(len(self.scheduler.queue))
        if self._tracer is not None:
            self._tracer.register_request(req.rid)
            self._tracer.begin(req.rid, "queued",
                               prompt_tokens=req.prompt_len,
                               max_new_tokens=req.max_new_tokens)
        if self._flight is not None:
            self._flight.record("submit", rid=req.rid,
                                prompt_tokens=req.prompt_len,
                                max_new_tokens=req.max_new_tokens,
                                queue_depth=len(self.scheduler.queue))
        handle = RequestHandle(self, req)
        self._handles[req.rid] = handle
        return handle

    def step(self) -> bool:
        """One event-loop iteration.  Async (default): harvest the in-flight
        decode/prefill futures from step *k−1* (the one host block — the
        idle backoff of every drive loop is this wait on the futures table,
        never a busy poll), expire deadlines, dispatch decode for batch *k*,
        then admit + dispatch prefill pieces while the device computes.
        Sync (``async_step=False``): the original expire → admit+prefill →
        one blocking decode.  Returns whether any work happened.  When a
        flight recorder is armed, any exception out of the step auto-dumps
        the flight record before propagating; when tracing is on, the step
        lands as an ``engine.step`` span."""
        if self._closed:
            raise RuntimeError("engine is shut down")
        self.step_calls += 1
        tr = self._tracer
        if tr is not None:
            tr.engine_begin("engine.step",
                            queued=len(self.scheduler.queue),
                            running=len(self.scheduler.running))
        try:
            worked = self._step_async() if self.async_step else self._step_inner()
            self._retry_streak = 0                         # a clean step resets the budget
        except Exception as e:
            # blast-radius containment: classified faults are absorbed —
            # quarantine / retry / recover — and the loop keeps serving;
            # anything unclassified keeps the crash-dump-and-raise contract
            try:
                handled = self._absorb_fault(e)
            except Exception as e2:
                if self._flight is not None:
                    self._flight.crash_dump(e2)
                if tr is not None:
                    tr.engine_end("engine.step", error=type(e2).__name__)
                raise
            if not handled:
                if self._flight is not None:
                    self._flight.crash_dump(e)
                if tr is not None:
                    tr.engine_end("engine.step", error=type(e).__name__)
                raise
            worked = True
        if tr is not None:
            tr.engine_end("engine.step", worked=worked)
        return worked

    def _step_inner(self) -> bool:
        """The synchronous scheduler iteration (``async_step=False``):
        byte-identical to the pre-async engine."""
        worked = False
        for req in self.scheduler.deadline_expired():
            self._finish(req, FINISH_DEADLINE)
            worked = True
        while self._try_admit():
            worked = True
        for r in list(self.scheduler.running):
            if not r.generated and r.state == "running":
                # a request stranded without token 0 (its admission prefill
                # was absorbed as a fault, or recovery reset it): re-prefill
                # before the decode batch consumes generated[-1]
                self._prefill_harvest(self._prefill_dispatch(r))
                self._release_retired()
                self._sample_occupancy()
                worked = True
        if self.scheduler.running:
            self._decode_once()
            worked = True
        self._update_gauges()
        return worked

    def _step_async(self) -> bool:
        """One event-loop turn.  Phase order is the overlap contract:

        1. **harvest** — materialize the previous step's in-flight decode
           tokens and prefill pieces (stream callbacks, finishes, window
           expiry land here, one device-latency late but in order);
        2. expire deadlines (a request finished here is skipped by any
           in-flight record that still names it);
        3. **decode dispatch** for the decode-ready batch — the device
           starts on step *k* while the host continues;
        4. admissions + chunked-prefill advancement — all host/dispatch
           work that overlaps the device's decode.
        """
        worked = self._harvest()
        for req in self.scheduler.deadline_expired():
            self._finish(req, FINISH_DEADLINE)
            worked = True
        if self.scheduler.decode_ready():
            self._decode_once()
            worked = True
        while self._try_admit():
            worked = True
        if self._advance_prefills():
            worked = True
        self._update_gauges()
        return worked

    def _harvest(self) -> bool:
        """Materializes every in-flight future (decode first: it was
        dispatched before the prefill pieces, so the device finishes it
        first).  This is where the host blocks — drive loops calling
        ``step()`` back off *inside* this wait instead of busy-polling."""
        wd = self.watchdog_timeout_s
        if wd is not None:
            # the watchdog: an in-flight record that aged past the timeout
            # on the engine clock without being harvested is a hung step —
            # convert the silent stall into the recovery path
            now = self.scheduler.clock()
            inflight = list(self._inflight_prefill)
            if self._inflight_decode is not None:
                inflight.append(self._inflight_decode)
            for wrec in inflight:
                age = now - wrec["t_clock"]
                if age > wd:
                    rids = ([r.rid for r in wrec["running"]]
                            if wrec["kind"] == "decode" else [wrec["req"].rid])
                    raise WatchdogTimeout(FP_HARVEST, rids, age_s=age)
        worked = False
        rec, self._inflight_decode = self._inflight_decode, None
        if rec is not None:
            self._decode_harvest(rec)
            worked = True
        pending, self._inflight_prefill = self._inflight_prefill, []
        for prec in pending:
            self._prefill_harvest(prec)
            worked = True
        if worked:
            # every record above materialized at least one output of its
            # program, so all of last step's donated-arena consumers have
            # completed — dropping the parked handles is free now (doing it
            # at dispatch would block the host for the whole device step)
            self._release_retired()
            self._sample_occupancy()
        return worked

    def _release_retired(self) -> None:
        """Drops the parked donated-arena handles of every pool the engine
        owns (target always; the draft arena too under speculative
        serving — both are donated by the same harvested round)."""
        self.pool.release_retired()
        if self.draft_pool is not None:
            self.draft_pool.release_retired()

    def _advance_prefills(self) -> bool:
        """The prefill lane: dispatches the next chunk for every running
        request whose prompt is not yet resident and has no piece already
        in flight — at most one piece per request per step, so chunks
        interleave 1:1 with decode dispatches."""
        worked = False
        inflight = {rec["req"].rid for rec in self._inflight_prefill}
        for r in list(self.scheduler.running):
            if r.pos < r.prompt_len and r.rid not in inflight:
                self._inflight_prefill.append(self._prefill_dispatch(r))
                worked = True
        return worked

    def run(self, requests: Sequence, *, max_new_tokens: int | None = None) -> list[RequestResult]:
        """Convenience driver: submits every request (stepping through
        transient queue-full rejections) and drives to completion.  Each
        request is a prompt array or a dict of :meth:`submit` kwargs."""
        handles = []
        for r in requests:
            kw = dict(r) if isinstance(r, dict) else {"prompt": r}
            if "max_new_tokens" not in kw:
                if max_new_tokens is None:
                    raise ValueError("max_new_tokens missing (argument or per-request)")
                kw["max_new_tokens"] = max_new_tokens
            prompt = kw.pop("prompt")
            # transient queue-full backpressure is not a rejection: make room
            # by stepping instead of bouncing off submit() (which counts every
            # AdmissionError it raises in serving.requests.rejected)
            while len(self.scheduler.queue) >= self.scheduler.max_queue:
                if not self.step():
                    raise AdmissionError(
                        f"wait queue full ({self.scheduler.max_queue}) and the "
                        "engine cannot make progress"
                    )
            handles.append(self.submit(prompt, **kw))
        self.drain()
        return [h.result(drive=False) for h in handles]

    def drain(self) -> None:
        """Steps until every submitted request has finished.  Never a busy
        poll: when every request is blocked on device work, the next
        ``step()`` backs off *inside* the harvest of the in-flight futures
        table (a bounded number of ``step()`` calls per token, asserted by
        regression test).  A stall (work remains but no step can progress)
        raises :class:`EngineStalledError` carrying the flight-recorder
        state snapshot."""
        while self.scheduler.queue or self.scheduler.running:
            if not self.step():
                raise EngineStalledError(
                    "engine stalled during drain", self._flight_state()
                )

    def evict(self, handle: RequestHandle) -> None:
        """Administratively removes a queued/running request (finish reason
        ``"evicted"``); its blocks return to the pool immediately."""
        if not handle.done():
            self._finish(handle._req, FINISH_EVICTED)

    def shutdown(self, *, drain: bool = True) -> None:
        """Graceful stop: optionally drains, discards whatever is still in
        flight, evicts whatever remains, closes owned telemetry, and
        rejects further submits.  The in-flight discard matters on the
        non-drain path: an async decode/chunk future still on the device —
        and the donated-arena handles parked for it — must be dropped
        before the engine closes, or they leak past shutdown."""
        if self._closed:
            return
        if drain:
            self.drain()
        self._discard_inflight()
        for req in (*self.scheduler.running, *self.scheduler.queue):
            self._finish(req, FINISH_EVICTED)
        if self._sessions is not None:
            self._sessions.clear()
        self._closed = True
        if self._owns_telemetry and self.telemetry is not None:
            self.telemetry.close()

    def __enter__(self) -> "ServingEngine":
        return self

    def __exit__(self, *exc) -> None:
        self.shutdown(drain=exc == (None, None, None))

    def mesh_stats(self) -> dict | None:
        """Mesh-serving facts (``None`` on a single-device engine): mesh
        shape, per-shard arena bytes, and — once the first decode step has
        run its program census — the collective count of one compiled
        decode program."""
        if self.mesh is None:
            return None
        return {
            "devices": int(self.mesh.devices.size),
            "axes": {a: int(self.mesh.shape[a]) for a in self.mesh.axis_names},
            "arena_spec": str(self.pool.arena_sharding.spec),
            "arena_shard_bytes": self.pool.per_shard_bytes(),
            "arena_total_bytes": int(self.pool.k_arena.nbytes) * 2,
            "collectives_decode": self._mesh_collectives,  # None until censused
        }

    def stats(self) -> dict:
        """Host-side engine statistics (registry-independent)."""
        occ = (self._occupancy_sum / self.decode_steps) if self.decode_steps else 0.0
        mesh = self.mesh_stats()
        sch = self.scheduler
        # program kinds a bucket may instantiate: decode per batch bucket
        # (doubled under speculative serving: each round runs draft_decode
        # AND verify at the same bucket), prefill per prefill bucket, plus
        # the chunk kind when chunking is on — or once recovery has
        # replayed through the chunk programs
        kinds = len(sch.batch_buckets) * (
            2 if self.spec is not None else 1
        ) + len(sch.prefill_buckets) * (
            2 if (sch.prefill_chunk is not None or self.chunk_runs > 0) else 1
        )
        n = self._overlap_obs
        return {
            **({"replica": self.replica_id} if self.replica_id is not None else {}),
            **({"mesh": mesh} if mesh is not None else {}),
            **({"lora": self._registry.state_snapshot()} if self._registry is not None else {}),
            "queue_depth": len(sch.queue),
            "running": len(sch.running),
            "pool_free_blocks": self.pool.num_free,
            "pool_free_blocks_low_water": self.pool.free_blocks_low_water,
            "pool_utilization": self.pool.utilization(),
            "kv_dtype": str(self.pool.kv_dtype),
            "arena_bytes": self.pool.arena_bytes(),
            "async_step": self.async_step,
            "prefill_chunk": sch.prefill_chunk,
            "decode_steps": self.decode_steps,
            "decode_steps_per_visit": self.n_decode_steps,
            "host_visits": self.host_visits,
            "tokens_per_host_visit": (
                self.decode_lane_tokens / self.host_visits
                if self.host_visits else None
            ),
            "prefill_runs": self.prefill_runs,
            "chunk_runs": self.chunk_runs,
            "step_calls": self.step_calls,
            "tokens_generated": self.tokens_generated,
            "mean_batch_occupancy": occ,
            "decode_stall_s_mean": (self._stall_s_sum / n) if n else None,
            "overlap_frac_mean": (self._overlap_frac_sum / n) if n else None,
            "compile_counts": dict(self.compile_counts),
            "attn": {
                "mode": self.attn,
                "requested": self._attn_requested,
                "fallback_reason": self._attn_fallback_reason,
                "kernel_steps": self.attn_kernel_steps,
                "fallback_steps": self.attn_fallback_steps,
                # per-kind resolution: decode and chunk-prefill resolve
                # independently (the chunk kernel needs block-aligned
                # widths and no sliding window), so a single top-level
                # mode/reason can't tell the whole story
                "kinds": {
                    "decode": {
                        "mode": self.attn,
                        "fallback_reason": self._attn_fallback_reason,
                        "kernel_steps": self._attn_steps["decode"][0],
                        "fallback_steps": self._attn_steps["decode"][1],
                    },
                    "prefill_chunk": {
                        "mode": self.attn_chunk,
                        "fallback_reason": self._attn_chunk_fallback_reason,
                        "kernel_steps": self._attn_steps["prefill_chunk"][0],
                        "fallback_steps": self._attn_steps["prefill_chunk"][1],
                    },
                },
            },
            "bucket_bound": kinds * len(self._table_widths),
            "prefix_lookups": self._prefix_lookups,
            "prefix_hits": self._prefix_hits,
            "recoveries": self.recoveries,
            "faults": self._faults.snapshot() if self._faults is not None else None,
            **({"spec": self._spec_stats()} if self.spec is not None else {}),
            **({"sessions": self._sessions.snapshot()}
               if self._sessions is not None else {}),
            **({"priority": {**self._priorities.snapshot(),
                             "preempted": self.preempted}}
               if self._priorities is not None else {}),
            **({"constrained": True} if self._constraints else {}),
            **({"goodput": self._goodput.snapshot()}
               if self._goodput is not None else {}),
            "pool_occupancy": self.pool.occupancy_snapshot(),
        }

    def _spec_stats(self) -> dict:
        """Speculative-lane acceptance accounting: the histogram counts
        rounds by tokens emitted (1..K+1); acceptance_rate is accepted
        drafts / drafted tokens; tokens_per_round is the mean emission —
        the solo ``speculative_generate.last_tokens_per_round`` analogue."""
        hist = self._spec_accept_hist
        rounds = int(hist.sum())
        drafted = self.spec_draft_tokens
        return {
            "K": self.spec.K,
            "rounds": self.spec_rounds,
            "draft_tokens": drafted,
            "accepted_tokens": self.spec_accepted_tokens,
            "acceptance_rate": (self.spec_accepted_tokens / drafted) if drafted else None,
            "accept_len_hist": {i + 1: int(hist[i]) for i in range(len(hist))},
            "tokens_per_round": (
                sum((i + 1) * int(hist[i]) for i in range(len(hist))) / rounds
            ) if rounds else None,
        }

    def slo_report(self) -> dict:
        """Burn rates against the configured SLO targets (``slo=`` at
        construction; see :mod:`thunder_tpu.observability.slo`).  Without a
        configured SLO the report is ``{"enabled": False}`` — the engine
        carries no monitor and no per-request classification cost."""
        if self._slo is None:
            return {"enabled": False}
        return self._slo.report()

    def goodput_report(self) -> dict:
        """Full goodput-ledger report (``goodput=`` at construction; see
        :mod:`thunder_tpu.observability.goodput`): token-goodput fraction
        plus per-cause and per-program-kind breakdowns with device-time
        attribution.  ``{"enabled": False}`` when the ledger is off."""
        if self._goodput is None:
            return {"enabled": False}
        rep = self._goodput.report()
        if self.replica_id is not None:
            rep["replica"] = self.replica_id
        return rep

    def _flight_state(self) -> dict:
        """State snapshot the flight recorder embeds in every dump."""
        lookups = self._prefix_lookups
        dec = self._inflight_decode
        return {
            **({"replica": self.replica_id} if self.replica_id is not None else {}),
            "engine": self.stats(),                      # includes "mesh" when SPMD
            "scheduler": self.scheduler.state_snapshot(),
            "pool": self.pool.state_snapshot(),
            # what each lane was doing: the in-flight futures plus every
            # partially-prefilled request (a crash mid-overlap is
            # undiagnosable without knowing what was still on the device)
            "lanes": {
                "async_step": self.async_step,
                "decode_inflight": (
                    {"step": dec["step"], "bucket": dec["bucket"],
                     "steps": dec.get("multi", 1),
                     "rids": [r.rid for r in dec["running"]]}
                    if dec is not None else None
                ),
                "prefill_inflight": [
                    {"rid": rec["req"].rid, "kind": rec["kind"]}
                    for rec in self._inflight_prefill
                ],
                "prefilling": [
                    {"rid": r.rid, "pos": r.pos, "prompt_tokens": r.prompt_len}
                    for r in self.scheduler.running if r.pos < r.prompt_len
                ],
                "speculative": (
                    {"K": self.spec.K,
                     "chained": self._spec_state is not None,
                     "rounds": self.spec_rounds,
                     "acceptance_rate": self._spec_stats()["acceptance_rate"]}
                    if self.spec is not None else None
                ),
                "goodput": (self._goodput.brief()
                            if self._goodput is not None else None),
            },
            "prefix_share_hit_rate": (self._prefix_hits / lookups) if lookups else None,
            "compiles": list(self._compile_log),         # per-bucket compile causes
            "slo": self.slo_report(),
        }

    #
    # admission + prefill
    #

    def _table_width_buckets(self) -> tuple[int, ...]:
        """Every table width a compiled program may use: the scheduler's
        block buckets, shifted off any width whose gathered capacity equals
        ``sliding_window`` (which ``forward_with_cache`` would interpret as
        the ring layout — the pool always uses the plain slot-=-position
        layout; the window lives in the keep-mask), then extended so a
        shared-prefix or chunked-prefill resume point plus prefill-bucket
        padding past the largest block bucket still rounds up into the set.
        ``stats()``'s ``bucket_bound`` counts these widths, so :meth:`_nbb`
        may never produce one outside them."""
        sch, bs = self.scheduler, self.pool.block_size
        W = self.cfg.sliding_window
        chunk = sch.prefill_chunk

        def dodge(b: int) -> int:
            return b + 1 if W is not None and self.pool.capacity_tokens(b) == W else b

        widths = {dodge(b) for b in sch.block_buckets}
        # widest dense window a prefill piece can touch: the largest
        # block-aligned resume point (shared prefix OR an earlier chunk)
        # plus a padded prefill bucket.  Without chunking, prompts are
        # capped by the prefill buckets; with it, only by the admission
        # hard cap on blocks — but every piece is at most one chunk wide.
        cap_tokens = self.pool.capacity_tokens(
            min(self.pool.num_usable, sch.block_buckets[-1])
        )
        max_prompt = cap_tokens if chunk is not None else min(sch.prefill_buckets[-1], cap_tokens)
        resumes = self.prefix_sharing or chunk is not None
        max_resume = ((max_prompt - 1) // bs) * bs if resumes else 0
        piece = chunk if chunk is not None else pick_bucket(max_prompt, sch.prefill_buckets)
        need = -(-(max_resume + piece) // bs)
        # re-prefill recovery replays prompt + emitted tokens through the
        # chunk programs on ANY engine (chunked or not): its resume points
        # reach to one token short of the full reservation capacity, and
        # its pieces are the widest block-aligned prefill bucket — those
        # widths must be in the set too, or a recovery would mint a table
        # width bucket_bound never counted
        aligned = [t for t in sch.prefill_buckets if t % bs == 0]
        replay_piece = max(aligned) if aligned else sch.prefill_buckets[-1]
        replay_resume = ((cap_tokens - 1) // bs) * bs
        need = max(need, -(-(replay_resume + replay_piece) // bs))
        b = max(widths)
        while b < need:
            b *= 2
            widths.add(dodge(b))
        return tuple(sorted(widths))

    def _nbb(self, min_blocks: int) -> int:
        """Table-width bucket for ``min_blocks``, from the precomputed
        width set (see :meth:`_table_width_buckets`)."""
        return pick_bucket(min_blocks, self._table_widths)

    def _try_admit(self) -> bool:
        sch = self.scheduler
        if not sch.queue:
            return False
        head = sch.queue[0]
        gate = self._priorities
        if gate is not None and not gate.admit_ok(head.priority_class, self._slo):
            # SLO burn defers this class; more urgent arrivals jump the
            # queue (priority insertion), so holding the head is safe
            return False
        # a preempted victim re-admitting skips prefix sharing: its replay
        # rewrites from position 0, so leased shared blocks would be
        # co-owned write targets
        resume = bool(head.generated)
        shared = [] if resume else self._find_shared_prefix(head)
        req = sch.next_admittable(shared_blocks=len(shared))
        if req is None:
            return (gate is not None and self._maybe_preempt(head))
        if (shared and self._sessions is not None
                and self._hit_owner is not None and self._hit_owner < 0):
            self._sessions.note_reattach(self._hit_owner)
            entry = self._sessions.owner_entry(self._hit_owner)
            if entry is not None and entry.full_pos:
                # the parked turn had written full_pos cache slots; the
                # prompt positions below that watermark and past the shared
                # blocks are recomputation of the truncated tail
                req.replay_until = max(
                    req.replay_until, min(entry.full_pos, req.prompt_len))
                req.replay_cause = "replay_session_tail"
        n_needed = sch.blocks_needed(req)
        table = self.pool.share(shared) + self.pool.alloc(n_needed - len(shared))
        sch.admit(req, table, len(shared))
        if gate is not None:
            registry().counter(
                f"serving.priority.{req.priority_class}.admitted").inc()
        if self._tracer is not None:
            self._tracer.end(req.rid, "queued",
                             queue_s=req.admit_t - req.submit_t)
        if self._flight is not None:
            self._flight.record("admit", rid=req.rid, blocks=n_needed,
                                shared_blocks=len(shared),
                                pool_free=self.pool.num_free,
                                resume=resume)
        if resume:
            self._resume_replay(req)
        else:
            self._prefill(req)
        return True

    def _maybe_preempt(self, head: Request) -> bool:
        """Evict-and-resume: checkpoint the least-urgent running request so
        a strictly more urgent head can be funded.  The checkpoint is free
        — prompt, generated tokens and the PRNG key chain are host state
        that only advances at harvest — so preemption is unregister +
        release + re-queue; re-admission replays through the sampling-free
        ``prefill_chunk`` pieces (:meth:`_resume_replay`), bit-identical
        to an undisturbed run.  Unsupported beside the speculative lane
        (its harvest has no preemption epoch guard)."""
        if self.spec is not None:
            return False
        victim = self._priorities.pick_victim(self.scheduler.running, head.priority)
        if victim is None:
            return False
        self._unregister_prefix(victim)
        self.scheduler.preempt(victim)     # frees blocks, bumps preemptions
        self._decode_state = None
        self.preempted += 1
        registry().counter(
            f"serving.priority.{victim.priority_class}.preempted").inc()
        if self._tracer is not None:
            self._tracer.instant(victim.rid, "preempted",
                                 for_rid=head.rid,
                                 generated=len(victim.generated))
            self._tracer.begin(victim.rid, "queued",
                               preemptions=victim.preemptions)
        if self._flight is not None:
            self._flight.record("preempt", rid=victim.rid, for_rid=head.rid,
                                generated=len(victim.generated),
                                pool_free=self.pool.num_free)
        return True

    def _resume_replay(self, req: Request) -> None:
        """Re-admission path for a preempted request that already holds
        generated tokens: rebuild its KV through the ``prefill_chunk``
        replay (bucket-wide pieces, no sampling, no key split) and rejoin
        the decode lane at the identical position/key chain."""
        tr = self._tracer
        if tr is not None:
            tr.begin(req.rid, "resume", lane="prefill",
                     generated=len(req.generated))
        self._replay_request(req, cause="replay_preemption")
        self._register_prefix(req, upto=req.pos)
        if tr is not None:
            tr.end(req.rid, "resume", pos=req.pos)

    def _find_shared_prefix(self, req: Request) -> list[int]:
        """Longest block-aligned prompt prefix already resident in a live
        request's blocks (the last prompt token always re-prefills, so the
        share is capped one token short of the full prompt).  The index
        machinery itself lives in :class:`~thunder_tpu.serving.kv_pool.
        PrefixIndex` so the dp router can probe residency without touching
        engine internals."""
        if not self.prefix_sharing:
            return []
        self._hit_owner = None
        return self._prefix_index.find(req.prompt, self._prefix_alive)

    def _prefix_alive(self, hit: tuple[int, tuple[int, ...]]) -> bool:
        """A registered prefix is shareable only while its owner is still
        running AND every snapshot block id is still the live table entry
        (window expiry sinks leading entries without finishing the owner).
        Negative owner rids are parked sessions — their liveness is the
        session table's (the entry exists and still owns those blocks)."""
        rid, blocks = hit
        if rid < 0:
            ok = self._sessions is not None and self._sessions.alive(rid, blocks)
            if ok:
                self._hit_owner = rid
            return ok
        owner = next((r for r in self.scheduler.running if r.rid == rid), None)
        if owner is None or len(owner.block_table) < len(blocks):
            return False
        ok = all(t == b != SINK_BLOCK for t, b in zip(owner.block_table, blocks))
        if ok:
            self._hit_owner = rid
        return ok

    def _register_prefix(self, req: Request, upto: int | None = None) -> None:
        """Registers ``req``'s block-aligned prompt prefixes.  ``upto``
        bounds registration to tokens already *written* (a chunked prefill
        registers after each piece; a sharer's later-dispatched program is
        ordered behind the writes on the device stream, so it never gathers
        an unwritten block)."""
        if not self.prefix_sharing:
            return
        self._prefix_index.register(
            req.rid, req.prompt, req.block_table, self._prefix_alive, upto=upto)

    def _unregister_prefix(self, req: Request) -> None:
        self._prefix_index.unregister(req.rid)

    def probe_prefix(self, prompt) -> int:
        """Longest resident shared-prefix length (tokens) for ``prompt``,
        without counting a lookup or mutating the index — the dp router's
        affinity probe."""
        if not self.prefix_sharing:
            return 0
        return self._prefix_index.probe(prompt, self._prefix_alive)

    @property
    def _prefix_lookups(self) -> int:
        return self._prefix_index.lookups

    @property
    def _prefix_hits(self) -> int:
        return self._prefix_index.hits

    def _prefill(self, req: Request) -> None:
        """Admission-time prefill entry.  Sync: dispatch the whole prompt
        and materialize inline (the original path).  Async: dispatch the
        first piece (a chunk when the prompt exceeds ``prefill_chunk``,
        else the whole remainder) and defer the harvest to the next step."""
        rec = self._prefill_dispatch(req)
        if self.async_step:
            self._inflight_prefill.append(rec)
        else:
            self._prefill_harvest(rec)
            self._release_retired()         # token materialized: consumer done
            self._sample_occupancy()

    def _chunk_kind(self) -> str:
        """The non-speculative chunk program kind this engine dispatches —
        resolved once at construction (``self.attn_chunk``), so raggedness
        never changes program identity mid-flight."""
        return ("prefill_chunk_paged" if self.attn_chunk == "paged"
                else "prefill_chunk")

    def _note_chunk_attn_step(self) -> None:
        """Per-kind attn step accounting for one chunk dispatch (the decode
        aggregates keep their decode-only semantics)."""
        st = self._attn_steps["prefill_chunk"]
        if self.attn_chunk == "paged":
            st[0] += 1
        else:
            st[1] += 1
            if self._attn_requested != "gather":
                # the user asked for kernels (paged or auto) but the chunk
                # kind resolved gather: that is a fallback step
                self._m_attn_fallback.inc()

    def _prefill_dispatch(self, req: Request) -> dict:
        """Dispatches the next prefill piece for ``req`` and returns its
        in-flight record.  A piece is either a full ``prefill`` (samples
        token 0, splits the request key exactly like solo ``generate()``)
        or an intermediate ``prefill_chunk`` (writes KV only — no sampling,
        no key split, so the final piece's draw stays bit-identical to the
        unchunked prefill)."""
        self._fault_point(FP_PREFILL, (req.rid,))
        sch, pool = self.scheduler, self.pool
        bs = pool.block_size
        pos = req.pos                                      # block-aligned resume point
        remainder = req.prompt_len - pos
        chunk = sch.prefill_chunk
        final = chunk is None or remainder <= chunk
        n_real = remainder if final else chunk
        first = pos == req.n_shared_blocks * bs            # the admission piece
        Tb = sch.prefill_bucket(n_real)
        nbb = self._nbb(max(len(req.block_table), -(-(pos + Tb) // bs)))
        toks = np.zeros(Tb, dtype=np.int32)
        toks[:n_real] = req.prompt[pos:pos + n_real]
        # gather the whole table; scatter back only the freshly written
        # block range — everything else (shared prefix, earlier chunks,
        # bucket padding) sinks (chunk granularity, see kv_pool.chunk_tables)
        table, dest = chunk_tables(req.block_table, pos, Tb, nbb, bs)
        if self.spec is not None:
            kind = "spec_prefill" if final else "spec_prefill_chunk"
        else:
            kind = "prefill" if final else self._chunk_kind()
        prog, compiled = self._program(kind, Tb, nbb)
        req.prefill_compiled = req.prefill_compiled or compiled
        # the dispatch phase is named by its dominant cost: a fresh program
        # pays the XLA compile here, a cached one only dispatches
        name = ("prefill.chunk" if not final
                else "prefill.compile" if compiled else "prefill.dispatch")
        tr = self._tracer
        if tr is not None:
            if first:
                tr.begin(req.rid, "prefill", compile=compiled, bucket=[Tb, nbb],
                         shared_blocks=req.n_shared_blocks, lane="prefill",
                         chunked=not final)
            tr.begin(req.rid, name, lane="prefill")
        darenas = None
        if final and self.spec is not None:
            tok, arenas, darenas, key, qerr = prog(
                self.params, self.spec.draft_params,
                jnp.asarray(toks)[None], jnp.int32(pos), jnp.int32(n_real),
                pool.arenas, self.draft_pool.arenas,
                jnp.asarray(table), jnp.asarray(dest), jnp.asarray(req.key),
                self._lora_arenas(), jnp.asarray([req.adapter_slot], dtype=jnp.int32),
            )
            rec = {"kind": "prefill", "req": req, "tok": tok, "key": key,
                   "qerr": qerr, "compiled": compiled, "span": name,
                   "epoch": req.preemptions, "t_clock": sch.clock()}
        elif final:
            args = (
                self.params, jnp.asarray(toks)[None], jnp.int32(pos), jnp.int32(n_real),
                pool.arenas, jnp.asarray(table), jnp.asarray(dest),
                jnp.asarray(req.key),
                self._lora_arenas(), jnp.asarray([req.adapter_slot], dtype=jnp.int32),
            )
            if self._constraints:
                # the final piece samples token 0: it must respect the
                # request's automaton exactly like every decode draw
                args += (jnp.asarray(req.constraint.mask()[None])
                         if req.constraint is not None
                         else self._ones_mask((1, self._vocab)),)
            tok, arenas, key, qerr = prog(*args)
            rec = {"kind": "prefill", "req": req, "tok": tok, "key": key,
                   "qerr": qerr, "compiled": compiled, "span": name,
                   "epoch": req.preemptions, "t_clock": sch.clock()}
        elif self.spec is not None:
            arenas, darenas, qerr = prog(
                self.params, self.spec.draft_params,
                jnp.asarray(toks)[None], jnp.int32(pos),
                pool.arenas, self.draft_pool.arenas,
                jnp.asarray(table), jnp.asarray(dest),
                self._lora_arenas(), jnp.asarray([req.adapter_slot], dtype=jnp.int32),
            )
            rec = {"kind": "chunk", "req": req, "qerr": qerr,
                   "compiled": compiled, "span": name,
                   "t_clock": sch.clock()}
        else:
            arenas, qerr = prog(
                self.params, jnp.asarray(toks)[None], jnp.int32(pos),
                pool.arenas, jnp.asarray(table), jnp.asarray(dest),
                self._lora_arenas(), jnp.asarray([req.adapter_slot], dtype=jnp.int32),
            )
            rec = {"kind": "chunk", "req": req, "qerr": qerr,
                   "compiled": compiled, "span": name,
                   "t_clock": sch.clock()}
        # a fault here is past the point of no return: the program call
        # above consumed the donated arenas, so absorb routes to recovery
        self._fault_point(FP_SCATTER, (req.rid,))
        pool.set_arenas(arenas)
        if darenas is not None:
            self.draft_pool.set_arenas(darenas)
        req.pos = pos + n_real                             # written (device-ordered)
        self._register_prefix(req, upto=req.pos)
        if req.replay_until > pos:
            # recompute bookkeeping (host ints, replay paths only): these
            # positions were already dispatched once before the replay
            rn = min(req.replay_until, pos + n_real) - pos
            req.tokens_recomputed += rn
            if (req.replay_cause
                    and req.replay_cause not in req.recompute_causes):
                req.recompute_causes.append(req.replay_cause)
        if self._goodput is not None:
            rec["pkind"] = kind
            rec["t_disp"] = time.perf_counter()
            rec["goodput"] = self._account_prefill(req, kind, pos, n_real, Tb)
        reg = registry()
        if final:
            self.prefill_runs += 1
            reg.counter("serving.steps.prefill").inc()
        else:
            self.chunk_runs += 1
            reg.counter("serving.steps.prefill_chunk").inc()
            if self.spec is None:
                self._note_chunk_attn_step()
        if compiled:
            # cold-compile TTFT outliers must be distinguishable from queue
            # delay: count prefill RUNS that paid a compile (vs
            # serving.compiles.prefill, which counts program builds)
            reg.counter("serving.prefill.compiles").inc()
        if first and req.n_shared_blocks:
            reg.counter("serving.prefix.shared_blocks").inc(req.n_shared_blocks)
        if self._flight is not None:
            self._flight.record("prefill" if final else "prefill_chunk",
                                rid=req.rid, compiled=compiled,
                                bucket=[Tb, nbb], pos=pos,
                                shared_blocks=req.n_shared_blocks,
                                **({} if final else {"attn": self.attn_chunk}))
        return rec

    def _prefill_harvest(self, rec: dict) -> None:
        """Materializes one prefill-piece record: chunks only settle the
        measured quantization error; the final piece delivers token 0
        (TTFT stamps here — token availability, not dispatch)."""
        req, pool = rec["req"], self.pool
        self._fault_point(FP_HARVEST, (req.rid,))
        gp = self._goodput
        if gp is not None and "t_disp" in rec:
            gp.note_device_s(rec["pkind"], time.perf_counter() - rec["t_disp"])
        tr = self._tracer
        if rec["kind"] == "chunk":
            # the scalar fetch doubles as the fence on the chunk execution
            # (release_retired relies on every harvested record having
            # materialized an output of its program)
            qerr = float(np.asarray(rec["qerr"]))
            if pool.quantized_kv:
                registry().gauge("serving.kv_quant.rel_err").set(qerr)
            if tr is not None:
                tr.end(req.rid, rec["span"], lane="prefill")
            return
        if tr is not None:
            tr.end(req.rid, rec["span"])
            tr.begin(req.rid, "prefill.host")
        if req.state != "running" or req.preemptions != rec.get(
                "epoch", req.preemptions):
            # finished (deadline/evict) or preempted-and-resumed while the
            # piece was in flight: the sampled token was never promised (a
            # resumed request re-draws it against its rebuilt KV) — drop
            # it, close the span
            if tr is not None:
                tr.end(req.rid, "prefill.host")
                tr.end(req.rid, "prefill", aborted=True)
            return
        req.key = np.asarray(rec["key"])
        tok0 = int(np.asarray(rec["tok"])[0])              # blocks until the device delivers
        req.first_token_t = self.scheduler.clock()         # TTFT = token availability, not dispatch
        if tr is not None:
            tr.end(req.rid, "prefill.host")
            tr.end(req.rid, "prefill", compile=req.prefill_compiled)
        self.tokens_generated += 1                         # prefill samples token 0
        if gp is not None:
            gp.commit_tokens(1)                            # token 0 streams below
        reg = registry()
        reg.counter("serving.tokens").inc()
        if pool.quantized_kv:
            # measured quantization error of THIS prefill's written blocks
            # (sum|dq-x|/sum|x| over non-sink destinations)
            reg.gauge("serving.kv_quant.rel_err").set(float(np.asarray(rec["qerr"])))
        self._emit_token(req, tok0)

    #
    # goodput / occupancy accounting helpers
    #

    def _sample_occupancy(self) -> None:
        """One ``(free, shared, leased)`` sample into the pool's bounded
        occupancy ring per harvest, mirrored into the
        ``serving.pool.occupancy_frac`` gauge."""
        self.pool.sample_occupancy()
        self._m_pool_occ.set(self.pool.utilization())

    @staticmethod
    def _sunk_positions(block_table, pos: int, n: int, bs: int) -> int:
        """How many of the real positions ``[pos, pos + n)`` route their
        KV write to the sink block (window-expired table entries — the
        replayed work is recomputed but never attended)."""
        if n <= 0:
            return 0
        sunk = 0
        for bi in range(pos // bs, -(-(pos + n) // bs)):
            b = block_table[bi] if bi < len(block_table) else SINK_BLOCK
            if b == SINK_BLOCK:
                sunk += min(pos + n, (bi + 1) * bs) - max(pos, bi * bs)
        return sunk

    def _account_prefill(self, req: Request, kind: str, pos: int,
                         n_real: int, Tb: int) -> dict:
        """Classify one prefill-family dispatch (1 row x Tb positions):
        bucket padding, sink-routed (window-expired) slots, recompute
        below the request's replay watermark, and fresh committed KV
        work.  Returns the ledger's compact tag dict."""
        bs = self.pool.block_size
        sunk = self._sunk_positions(req.block_table, pos, n_real, bs)
        replay_n = min(max(req.replay_until - pos, 0), n_real)
        win = min(sunk, replay_n)          # sunk slots inside the watermark
        extra_sunk = sunk - win            # defensive: sunk fresh writes
        cause_n = replay_n - win
        waste = {}
        if Tb > n_real:
            waste["pad_prefill"] = Tb - n_real
        if sunk:
            waste["replay_window"] = sunk
        if cause_n:
            cause = req.replay_cause or "replay_recovery"
            waste[cause] = waste.get(cause, 0) + cause_n
        return self._goodput.account(
            kind, 1, Tb, committed=n_real - replay_n - extra_sunk, **waste)

    #
    # decode
    #

    def _decode_once(self) -> None:
        """One decode-lane turn: dispatch the bucketed decode program for
        the decode-ready batch; sync harvests inline, async parks the
        record in the in-flight table for the next step's harvest."""
        if self.spec is not None:
            from thunder_tpu.serving.speculative import spec_decode_dispatch

            rec = spec_decode_dispatch(self)
        else:
            rec = self._decode_dispatch()
        if self.async_step:
            self._inflight_decode = rec
        else:
            self._decode_harvest(rec)
            self._release_retired()         # tokens materialized: consumer done
            self._sample_occupancy()

    def _decode_dispatch(self) -> dict:
        sch, pool = self.scheduler, self.pool
        running = (sch.decode_ready() if self.async_step
                   else list(sch.running))                 # FIFO admission order
        self._fault_point(FP_DECODE, tuple(r.rid for r in running))
        Bb, _nbb_raw = sch.decode_bucket(running)
        nbb = self._nbb(_nbb_raw)
        bs = pool.block_size
        sig = (tuple(r.rid for r in running), Bb, nbb)
        N = self.n_decode_steps
        st = self._decode_state
        if st is not None and st["sig"] == sig:
            # steady state: the batch composition and tables are unchanged
            # since the last step, so this step's inputs ARE the previous
            # step's device outputs (toks=nxt, keys=new_keys, pos=pos+N)
            # plus the cached tables/slots — zero host->device transfers
            toks_d, pos_d = st["toks"], st["pos"]
            tables_d, keys_d, slots_d = st["tables"], st["keys"], st["slots"]
            host_pos = st["host_pos"] + N
            stop_d = st.get("stop")
        else:
            toks = np.zeros(Bb, dtype=np.int32)
            host_pos = np.zeros(Bb, dtype=np.int32)
            tables = np.full((Bb, nbb), SINK_BLOCK, dtype=np.int32)
            keys = np.zeros((Bb, *np.shape(running[0].key)),
                            dtype=np.asarray(running[0].key).dtype)
            slots = np.zeros(Bb, dtype=np.int32)           # padding rows: base slot
            # multi-step stopping: the last position a row may write before
            # FINISH_LENGTH (see _build_decode_multi); -1 parks padding rows
            # dead from step 0
            stop = np.full(Bb, -1, dtype=np.int32)
            for i, r in enumerate(running):
                wpos = r.prompt_len + len(r.generated) - 1  # slot this step writes
                toks[i] = r.generated[-1]
                host_pos[i] = wpos
                tables[i, : len(r.block_table)] = r.block_table
                keys[i] = r.key
                slots[i] = r.adapter_slot
                stop[i] = r.prompt_len + r.max_new_tokens - 2
            # commit once; the chained steps reuse these device buffers
            toks_d, pos_d = jnp.asarray(toks), jnp.asarray(host_pos)
            tables_d, keys_d = jnp.asarray(tables), jnp.asarray(keys)
            slots_d = jnp.asarray(slots)
            stop_d = jnp.asarray(stop) if N > 1 else None
        # constrained decoding: the per-row token masks are fresh host data
        # every dispatch (the automata advanced at the last harvest) — an
        # argument beside the chained device state, never part of it
        cmask_d = None
        if self._constraints:
            shape = ((N, Bb, self._vocab) if N > 1 else (Bb, self._vocab))
            if any(r.constraint is not None for r in running):
                m = np.ones(shape, dtype=bool)
                for i, r in enumerate(running):
                    if r.constraint is not None:
                        if N > 1:
                            m[:, i, :] = r.constraint.masks(N)
                        else:
                            m[i] = r.constraint.mask()
                cmask_d = jnp.asarray(m)
            else:
                cmask_d = self._ones_mask(shape)
        if N > 1:
            kind = "decode_multi_paged" if self.attn == "paged" else "decode_multi"
        else:
            kind = "decode_paged" if self.attn == "paged" else "decode"
        prog, compiled = self._program(kind, Bb, nbb)
        lora_arenas = self._lora_arenas()
        if self.mesh is not None and self._mesh_collectives is None:
            # census BEFORE the call: the arenas are donated by it
            ex = (self.params, toks_d, pos_d, tables_d, pool.arenas,
                  keys_d, lora_arenas, slots_d)
            if N > 1:
                ex = ex + (stop_d,)
            if cmask_d is not None:
                ex = ex + (cmask_d,)
            self._mesh_collectives = self._collective_census(
                (kind, Bb, nbb), prog, ex,
            )
        if self.attn == "paged":
            self.attn_kernel_steps += 1
            self._attn_steps["decode"][0] += 1
            self._m_attn_kernel.inc()
        elif self._attn_requested == "auto":
            # auto resolved to gather: every decode step is a fallback step
            self.attn_fallback_steps += 1
            self._attn_steps["decode"][1] += 1
            self._m_attn_fallback.inc()
        if self._goodput is not None and self.attn == "paged":
            # ragged-decode visibility: the compiled grid spans Bb x nbb
            # blocks per step but the ragged clamp streams only each row's
            # live range — per-row ceil(pos / bs) clamped to [1, nbb]
            # (padding rows collapse to one block, the sink); host ints
            # only, the dispatch itself is untouched
            hp = np.asarray(host_pos, dtype=np.int64)[:, None] + np.arange(N)
            real = int(np.minimum(np.maximum(-(-hp // bs), 1), nbb).sum())
            self._goodput.note_blocks(kind, Bb * nbb * N, real)
        tr = self._tracer
        if tr is not None:
            for r in running:
                tr.begin(r.rid, "decode", step=self.decode_steps,
                         compile=compiled, bucket=[Bb, nbb], lane="decode",
                         attn=self.attn,
                         **({"steps": N} if N > 1 else {}))
        call_args = (self.params, toks_d, pos_d, tables_d, pool.arenas,
                     keys_d, lora_arenas, slots_d)
        if N > 1:
            call_args = call_args + (stop_d,)
        if cmask_d is not None:
            call_args = call_args + (cmask_d,)
        if N > 1:
            ys_tok, ys_emit, toks_f, keys_f, pos_f, arenas = prog(*call_args)
            nxt, new_keys, new_pos = toks_f, keys_f, pos_f
        else:
            nxt, new_keys, new_pos, arenas = prog(*call_args)
        # past the point of no return: the call consumed the donated arenas
        self._fault_point(FP_SCATTER, tuple(r.rid for r in running))
        pool.set_arenas(arenas)
        self._decode_state = {
            "sig": sig, "toks": nxt, "pos": new_pos, "tables": tables_d,
            "keys": new_keys, "slots": slots_d, "host_pos": host_pos,
            **({"stop": stop_d} if N > 1 else {}),
        }
        rec = {"kind": "decode", "running": running, "nxt": nxt,
               "new_keys": new_keys, "pos": host_pos, "bucket": [Bb, nbb],
               "pkind": kind, "compiled": compiled, "step": self.decode_steps,
               "epochs": [r.preemptions for r in running],
               "t_disp": time.perf_counter(), "t_clock": sch.clock()}
        if N > 1:
            rec.update(multi=N, nxt=ys_tok, emit=ys_emit, new_keys=keys_f)
        self.decode_steps += 1
        self._occupancy_sum += len(running)
        self._m_steps_decode.inc()
        self._m_occupancy.observe(len(running))
        return rec

    def _decode_harvest(self, rec: dict) -> None:
        if rec.get("spec"):
            from thunder_tpu.serving.speculative import spec_decode_harvest

            return spec_decode_harvest(self, rec)
        if rec.get("multi"):
            return self._decode_harvest_multi(rec)
        sch = self.scheduler
        running = rec["running"]
        self._fault_point(FP_HARVEST, tuple(r.rid for r in running))
        t0 = time.perf_counter()
        nxt = np.asarray(rec["nxt"])                       # the host block
        new_keys = np.asarray(rec["new_keys"])
        if self.async_step:
            # overlap accounting: host work since dispatch vs the residual
            # device wait the materialization just paid
            stall = time.perf_counter() - t0
            overlapped = t0 - rec["t_disp"]
            frac = overlapped / (overlapped + stall) if (overlapped + stall) > 0 else 0.0
            self._stall_s_sum += stall
            self._overlap_frac_sum += frac
            self._overlap_obs += 1
            self._m_stall.observe(stall)
            self._m_overlap.set(frac)
        epochs = rec.get("epochs")
        gp, gtag = self._goodput, None
        if gp is not None:
            # exact pre-emit classification of this visit's Bb x 1 slots:
            # every non-skipped row streams exactly one token
            Bb = rec["bucket"][0]
            n_stale = n_dead = live = 0
            for i, r in enumerate(running):
                if epochs is not None and r.preemptions != epochs[i]:
                    n_stale += 1                           # preempted: chain re-derives it
                elif r.state != "running":
                    n_dead += 1                            # finished while in flight
                else:
                    live += 1
            waste = {}
            if Bb > len(running):
                waste["pad_row"] = Bb - len(running)
            if n_stale:
                waste["replay_preemption"] = n_stale
            if n_dead:
                waste["dead_scan_row"] = n_dead
            gtag = gp.account(rec["pkind"], Bb, 1, committed=live, **waste)
            gp.note_device_s(rec["pkind"],
                             time.perf_counter() - rec["t_disp"])
        tr = self._tracer
        if tr is not None:                                 # tokens host-visible
            for r in running:
                tr.end(r.rid, "decode",
                       **({"goodput": gtag} if gtag is not None else {}))
        if self._flight is not None:
            self._flight.record("decode", step=rec["step"],
                                batch=len(running), bucket=rec["bucket"],
                                compiled=rec["compiled"],
                                rids=[r.rid for r in running],
                                **({"goodput": gtag}
                                   if gtag is not None else {}))
        pos = rec["pos"]
        emitted = 0
        invalidate = False
        for i, r in enumerate(running):
            if r.state != "running" or (
                    epochs is not None and r.preemptions != epochs[i]):
                # finished mid-flight (token never promised), or preempted
                # and already resumed: the resumed chain re-derives this
                # token against its rebuilt KV — applying the stale record
                # would advance the key twice
                invalidate = True
                continue
            r.key = new_keys[i]
            r.pos = int(pos[i]) + 1
            released = sch.expire_window_blocks(r)
            if released:
                # every registered prefix of r starts at its (just-sunk)
                # leading blocks — scrub before anyone can share them; the
                # cached device tables are stale too
                invalidate = True
                self._unregister_prefix(r)
                if self._flight is not None:
                    self._flight.record("window_expire", rid=r.rid,
                                        released=released)
            emitted += 1
            self._emit_token(r, int(nxt[i]))
            if r.state != "running":
                invalidate = True                          # finished at this token
        self.tokens_generated += emitted
        self.decode_lane_tokens += emitted
        self.host_visits += 1
        self._m_host_visits.inc()
        if emitted:
            self._m_tokens.inc(emitted)
        if gp is not None:
            gp.commit_tokens(emitted)
        if invalidate:
            # the chained decode inputs assumed an unchanged batch/tables;
            # the next dispatch rebuilds from host state
            self._decode_state = None

    def _decode_harvest_multi(self, rec: dict) -> None:
        """Harvest one multi-step visit: up to N tokens per row.

        ``rec["nxt"]`` is the (N, Bb) token matrix and ``rec["emit"]`` the
        (N, Bb) liveness mask from the scan's stacked outputs.  The emitted
        prefix of each column is exactly the tokens the 1-step engine would
        have served: the in-program ``done`` predicate (pos >= stop, or
        token == eos) coincides bit-for-bit with ``_emit_token``'s
        FINISH_LENGTH / FINISH_EOS conditions, so a column with k < N
        emitted tokens finished at its k-th token and the remaining
        iterations keep-masked their KV writes to the sink block."""
        sch = self.scheduler
        running = rec["running"]
        N = rec["multi"]
        self._fault_point(FP_HARVEST, tuple(r.rid for r in running))
        t0 = time.perf_counter()
        nxt = np.asarray(rec["nxt"])                       # (N, Bb) host block
        emit = np.asarray(rec["emit"])                     # (N, Bb) bool
        new_keys = np.asarray(rec["new_keys"])
        if self.async_step:
            stall = time.perf_counter() - t0
            overlapped = t0 - rec["t_disp"]
            frac = overlapped / (overlapped + stall) if (overlapped + stall) > 0 else 0.0
            self._stall_s_sum += stall
            self._overlap_frac_sum += frac
            self._overlap_obs += 1
            self._m_stall.observe(stall)
            self._m_overlap.set(frac)
        tr = self._tracer
        harvested = [int(emit[:, i].sum()) for i in range(len(running))]
        epochs = rec.get("epochs")
        gp, gtag = self._goodput, None
        if gp is not None:
            # exact pre-emit classification of the Bb x N scan slots: the
            # in-program done predicate coincides with _emit_token's finish
            # conditions, so a live row streams min(k, budget, eos-cut)
            # tokens and its remaining iterations were dead scan rows
            Bb = rec["bucket"][0]
            committed = n_stale = n_dead = 0
            for i, r in enumerate(running):
                if epochs is not None and r.preemptions != epochs[i]:
                    n_stale += N
                elif r.state != "running":
                    n_dead += N
                else:
                    streamed = min(harvested[i],
                                   r.max_new_tokens - len(r.generated))
                    if self.eos_id is not None:
                        for s in range(streamed):
                            if int(nxt[s, i]) == self.eos_id:
                                streamed = s + 1
                                break
                    committed += streamed
                    n_dead += N - streamed
            waste = {}
            if Bb > len(running):
                waste["pad_row"] = (Bb - len(running)) * N
            if n_stale:
                waste["replay_preemption"] = n_stale
            if n_dead:
                waste["dead_scan_row"] = n_dead
            gtag = gp.account(rec["pkind"], Bb, N, committed=committed,
                              **waste)
            gp.note_device_s(rec["pkind"],
                             time.perf_counter() - rec["t_disp"])
        if tr is not None:                                 # tokens host-visible
            # one span per request per HOST VISIT (not N phantom per-token
            # spans): tagged with how many of the N steps actually emitted
            for i, r in enumerate(running):
                tr.end(r.rid, "decode", harvested=harvested[i],
                       **({"goodput": gtag} if gtag is not None else {}))
        if self._flight is not None:
            self._flight.record("decode", step=rec["step"],
                                batch=len(running), bucket=rec["bucket"],
                                compiled=rec["compiled"], steps=N,
                                harvested=harvested,
                                rids=[r.rid for r in running],
                                **({"goodput": gtag}
                                   if gtag is not None else {}))
        pos = rec["pos"]
        emitted = 0
        invalidate = False
        for i, r in enumerate(running):
            if r.state != "running" or (
                    epochs is not None and r.preemptions != epochs[i]):
                # finished mid-flight (tokens never promised) or preempted
                # and resumed (the resumed chain re-derives these tokens)
                invalidate = True
                continue
            k = harvested[i]
            r.key = new_keys[i]
            r.pos = int(pos[i]) + k
            released = sch.expire_window_blocks(r)
            if released:
                invalidate = True
                self._unregister_prefix(r)
                if self._flight is not None:
                    self._flight.record("window_expire", rid=r.rid,
                                        released=released)
            for s in range(k):
                emitted += 1
                self._emit_token(r, int(nxt[s, i]))
                if r.state != "running":
                    invalidate = True                      # finished at this token
                    break
            if k < N:
                # the row went dead in-program; the chained device state no
                # longer matches this row's host state
                invalidate = True
        self.tokens_generated += emitted
        self.decode_lane_tokens += emitted
        self.host_visits += 1
        self._m_host_visits.inc()
        if emitted:
            self._m_tokens.inc(emitted)
        if gp is not None:
            gp.commit_tokens(emitted)
        if invalidate:
            self._decode_state = None

    #
    # finishing / results
    #

    def _emit_token(self, req: Request, tok: int) -> None:
        req.generated.append(tok)
        if req.constraint is not None:
            # the automaton advances exactly where the key chain does (at
            # harvest), so replay/resume never re-advances it
            req.constraint.advance(tok)
        if req.stream_cb is not None:
            req.stream_cb(tok)
        if self.eos_id is not None and tok == self.eos_id:
            self._finish(req, FINISH_EOS)
        elif len(req.generated) >= req.max_new_tokens:
            self._finish(req, FINISH_LENGTH)

    def _finish(self, req: Request, reason: str) -> None:
        never_admitted = req.admit_t is None
        if self._sessions is not None and req.session_id is not None:
            if reason in (FINISH_LENGTH, FINISH_EOS) and req.state == "running":
                # park the turn's block-aligned written prefix BEFORE the
                # scheduler frees the request's own references; the table
                # takes share() refs of its own, so the blocks stay leased
                self._park_session(req)
            else:
                # an abnormal turn (deadline/evicted/error) breaks the
                # deterministic continuation contract: release the session
                self._sessions.close(req.session_id)
        self._unregister_prefix(req)                       # before blocks free
        self.scheduler.finish(req, reason)
        reg = registry()
        reg.counter("serving.requests.completed").inc()
        reg.counter(f"serving.finish.{reason}").inc()
        res = self._result(req)
        if self._tracer is not None:
            if never_admitted:                             # died in the queue
                self._tracer.end(req.rid, "queued", finish_reason=reason)
            self._tracer.instant(
                req.rid, "finish", reason=reason,
                new_tokens=len(req.generated),
                **({"session_id": req.session_id} if req.session_id else {}),
                **({"priority": req.priority_class}
                   if self._priorities is not None else {}),
                **({"constrained": True} if req.constraint is not None else {}),
                **({"error": req.error_cause.get("type")}
                   if req.error_cause else {}),
            )
        if self._flight is not None:
            self._flight.record("finish", rid=req.rid, reason=reason,
                                new_tokens=len(req.generated))
        if self._slo is not None:
            self._slo.observe(res)
        if res.ttft_s is not None:
            reg.histogram("serving.ttft_s").observe(res.ttft_s)
        if res.tpot_s is not None:
            reg.histogram("serving.tpot_s").observe(res.tpot_s)
        if res.tokens_per_sec is not None:
            reg.histogram("serving.tokens_per_sec").observe(res.tokens_per_sec)
        if req.adapter_id is not None:
            # per-tenant accounting: which adapter consumed the tokens and
            # what latency its requests saw
            reg.counter(f"serving.tenant.{req.adapter_id}.tokens").inc(len(req.generated))
            reg.counter(f"serving.tenant.{req.adapter_id}.requests").inc()
            if res.ttft_s is not None:
                reg.histogram(f"serving.tenant.{req.adapter_id}.ttft_s").observe(res.ttft_s)
            if res.e2e_s is not None:
                reg.histogram(f"serving.tenant.{req.adapter_id}.e2e_s").observe(res.e2e_s)
        if self.telemetry is not None:
            self.telemetry.log_request(
                rid=req.rid,
                prompt_tokens=req.prompt_len,
                new_tokens=len(req.generated),
                finish_reason=reason,
                ttft_s=res.ttft_s,
                tpot_s=res.tpot_s,
                tokens_per_sec=res.tokens_per_sec,
                queue_s=res.queue_s,
                e2e_s=res.e2e_s,
                prefill_compiled=req.prefill_compiled,
                shared_prefix_blocks=req.n_shared_blocks,
                session_id=req.session_id,
                priority=(req.priority_class
                          if self._priorities is not None else None),
                constrained=(True if req.constraint is not None else None),
                preemptions=(req.preemptions or None),
                error=req.error_cause,
                tokens_recomputed=(req.tokens_recomputed or None),
                recompute_causes=(list(req.recompute_causes)
                                  if req.recompute_causes else None),
            )

    def _park_session(self, req: Request) -> None:
        """Park the finished turn's written block-aligned prefix.

        The resident KV covers positions ``[0, req.pos)`` of the full
        served sequence (prompt + generated; the last emitted token's KV
        is never written — it was sampled, not forwarded).  Sliding-window
        expiry may have sunk leading blocks, which truncates the parkable
        prefix to nothing (the park helper stops at the first sink)."""
        full = np.concatenate(
            [np.asarray(req.prompt, dtype=np.int64),
             np.asarray(req.generated, dtype=np.int64)])
        bs = self.pool.block_size
        nblk = min(req.pos // bs, len(req.block_table))
        entry = self._sessions.park(
            req.session_id, full[:nblk * bs], req.block_table[:nblk],
            adapter_slot=req.adapter_slot, full_pos=req.pos)
        if self._flight is not None:
            self._flight.record(
                "session_park", rid=req.rid, session_id=req.session_id,
                blocks=(len(entry.blocks) if entry is not None else 0),
                resident_blocks=self._sessions.resident_blocks)

    def close_session(self, session_id: str) -> int:
        """Release a session's parked blocks; returns how many were freed
        (0 when the session is unknown — closing twice is a no-op)."""
        if self._sessions is None:
            return 0
        freed = self._sessions.close(session_id)
        if freed and self._flight is not None:
            self._flight.record("session_close", session_id=session_id,
                                blocks=freed)
        return freed

    def session_resident(self, session_id: str) -> bool:
        """Does this engine's table hold the session's blocks?  (The dp
        router's session-affinity probe.)"""
        return self._sessions is not None and self._sessions.resident(session_id)

    def _ones_mask(self, shape: tuple) -> jnp.ndarray:
        """Cached device-resident all-``True`` constraint mask — the
        no-op mask unconstrained rows ride through a constrained program
        (``where(True, logits, -inf)`` is the identity, bit-exactly)."""
        m = self._mask_ones.get(shape)
        if m is None:
            m = jnp.ones(shape, dtype=bool)
            self._mask_ones[shape] = m
        return m

    def _result(self, req: Request) -> RequestResult:
        n = len(req.generated)
        ttft = (req.first_token_t - req.submit_t) if req.first_token_t is not None else None
        tpot = None
        tps = None
        if req.first_token_t is not None and req.finish_t is not None and n > 1:
            span = max(req.finish_t - req.first_token_t, 0.0)
            tpot = span / (n - 1)
        if req.finish_t is not None and n and (req.finish_t - req.submit_t) > 0:
            tps = n / (req.finish_t - req.submit_t)
        return RequestResult(
            rid=req.rid,
            prompt=req.prompt,
            new_tokens=tuple(req.generated),
            finish_reason=req.finish_reason or "?",
            ttft_s=ttft,
            tpot_s=tpot,
            tokens_per_sec=tps,
            queue_s=(req.admit_t - req.submit_t) if req.admit_t is not None else None,
            e2e_s=(req.finish_t - req.submit_t) if req.finish_t is not None else None,
            shared_prefix_blocks=req.n_shared_blocks,
            prefill_compiled=req.prefill_compiled,
            error=req.error_cause,
            tokens_recomputed=req.tokens_recomputed,
            recompute_causes=tuple(req.recompute_causes),
        )

    def _update_gauges(self) -> None:
        self._m_queue_depth.set(len(self.scheduler.queue))
        self._m_running.set(len(self.scheduler.running))
        self._m_pool_util.set(self.pool.utilization())
        self._m_pool_free.set(self.pool.num_free)
        # the post-mortem capacity floor: how close the pool ever came to
        # exhaustion (also in the flight-recorder pool snapshot)
        self._m_pool_low_water.set(self.pool.free_blocks_low_water)

    #
    # fault containment + re-prefill recovery
    #

    def _fault_point(self, point: str, rids: Sequence[int] = ()) -> None:
        """One injectable fault point (unarmed engines pay one ``is None``
        test — the compiled programs never see the plan)."""
        if self._faults is not None:
            self._faults.check(point, rids)

    def _absorb_fault(self, exc: Exception) -> bool:
        """Blast-radius containment for one classified step exception.
        Returns False for anything the recovery layer must not absorb
        (programming errors keep the crash-dump-and-raise contract).

        - **request** class: quarantine the offending rids (finish with
          ``"error"`` + structured cause, blocks freed, prefix scrubbed)
          and keep serving; a harvest/scatter fault additionally recovers
          (the step's tokens / donated arenas are already lost);
        - **transient** class: bounded retry with exponential backoff on
          the policy's injectable sleep; a *donated* failure (scatter /
          harvest) may have consumed its inputs, so it routes through
          recovery instead of re-submitting stale handles; retry
          exhaustion escalates to recovery;
        - **engine** class (OOM / hang / watchdog): straight to recovery.
        """
        cls = classify_fault(exc)
        if cls is None:
            return False
        cause = fault_cause(exc)
        point = cause.get("point")
        reg = registry()
        reg.counter("serving.faults.observed").inc()
        if self._flight is not None:
            self._flight.record("fault", fault_class=cls, cause=cause,
                                rids=cause.get("rids", []))
        lossy = point in (FP_HARVEST, FP_SCATTER)
        if cls == CLASS_REQUEST:
            for rid in cause.get("rids", ()):
                self._quarantine(rid, cause)
            if lossy:
                self._recover(cause)
        elif cls == CLASS_TRANSIENT:
            self._retry_streak += 1
            if self._retry_streak > self._retry.max_retries:
                self._retry_streak = 0
                self._recover(cause)
            else:
                reg.counter("serving.faults.retries").inc()
                self._retry.sleep(self._retry.backoff(self._retry_streak))
                if lossy:
                    self._recover(cause)
        else:
            self._recover(cause)
        return True

    def _quarantine(self, rid: int, cause: dict) -> None:
        """Finishes one poisoned request with ``finish_reason="error"`` and
        the structured cause; its blocks free and its prefix-index entries
        scrub through the normal ``_finish`` path, so the rest of the batch
        keeps serving."""
        req = next((r for r in (*self.scheduler.running, *self.scheduler.queue)
                    if r.rid == rid), None)
        if req is None or req.state == "finished":
            return
        req.error_cause = cause
        registry().counter("serving.faults.quarantined").inc()
        if self._flight is not None:
            self._flight.record("quarantine", rid=rid, cause=cause)
        self._finish(req, FINISH_ERROR)

    def recover(self) -> None:
        """Rebuilds the KV arenas and re-prefills every running request
        from its prompt + already-emitted tokens (the engine triggers this
        automatically on engine-class faults and retry exhaustion; it is
        public for operational use — e.g. after an external device reset).

        The recovery guarantee: a request's PRNG key advances only when a
        token is harvested, so the KV arena is *soft state* — replaying the
        already-known tokens through the sampling-free chunked-prefill
        program rebuilds exactly the cache an uninterrupted run would hold,
        and every subsequent draw is bit-identical."""
        self._recover({"type": "manual", "point": None, "kind": None,
                       "rids": [], "injected": False,
                       "message": "engine.recover()"})

    def _recover(self, cause: dict) -> None:
        reg = registry()
        t0 = time.perf_counter()
        tr = self._tracer
        if tr is not None:
            tr.engine_begin("engine.recover", cause=cause.get("type"))
        if self._flight is not None:
            self._flight.record("recover", cause=cause,
                                rids=[r.rid for r in self.scheduler.running])
        attempts = 0
        while True:
            try:
                self._recover_once()
                break
            except Exception as e:
                ecls = classify_fault(e)
                if ecls is None:
                    if tr is not None:
                        tr.engine_end("engine.recover", error=type(e).__name__)
                    raise
                if ecls == CLASS_REQUEST:
                    # a poison request resurfaced during its own replay:
                    # quarantining it IS progress, so it never consumes
                    # the bounded retry budget
                    ecause = fault_cause(e)
                    for rid in ecause.get("rids", ()):
                        self._quarantine(rid, ecause)
                    continue
                attempts += 1
                if attempts > self._retry.max_retries:
                    if tr is not None:
                        tr.engine_end("engine.recover", error="RecoveryError")
                    raise RecoveryError(
                        f"re-prefill recovery failed {attempts} times "
                        f"(last: {type(e).__name__}: {e})"
                    ) from e
                self._retry.sleep(self._retry.backoff(attempts))
        self.recoveries += 1
        self._retry_streak = 0
        dt = time.perf_counter() - t0
        reg.counter("serving.faults.recoveries").inc()
        reg.histogram("serving.recovery.duration_s").observe(dt)
        if self._flight is not None:
            self._flight.record("recovered", duration_s=dt,
                                rids=[r.rid for r in self.scheduler.running])
        if tr is not None:
            tr.engine_end("engine.recover", duration_s=dt)

    def _recover_once(self) -> None:
        """One recovery attempt: drop in-flight work, rebuild fresh zeroed
        arenas (allocator state — tables, refcounts, prefix sharing — is
        host-side and survives untouched), then replay every surviving
        request's known tokens back into its own blocks.  Requests still
        waiting on token 0 reset to pos=0 and re-run the normal prefill
        path (their key was never split, so token 0 is unchanged); shared-
        prefix blocks are rewritten by every co-owner with bit-identical
        content (the forward pass is deterministic)."""
        self._discard_inflight(cause="replay_recovery")
        self.pool.rebuild_arenas()
        if self.draft_pool is not None:
            # the draft arena is soft state too: the replay below rebuilds
            # it bit-identically (every attended slot holds the draft K/V
            # of the emitted token at that position)
            self.draft_pool.rebuild_arenas()
        if self._sessions is not None:
            # parked session KV is soft state like everything else in the
            # arenas: each entry records the exact tokens its blocks hold,
            # so the chunk replay rebuilds them bit-identically and turn
            # k+1 re-attaches as if the fault never happened.  Sessions
            # replay first: running sharers then overwrite any co-owned
            # block with identical content (deterministic forward).
            for entry in self._sessions.entries():
                self._replay_seq(entry.tokens, list(entry.blocks),
                                 entry.adapter_slot, len(entry.tokens),
                                 cause="replay_recovery")
        for req in list(self.scheduler.running):
            if req.pos and not req.generated:
                # token-0 requests re-run the normal prefill path from 0:
                # the positions their admission prefill already wrote are
                # recomputation chargeable to the recovery
                req.replay_until = max(req.replay_until, req.pos)
                req.replay_cause = "replay_recovery"
            req.pos = 0
            if req.generated:
                self._replay_request(req, cause="replay_recovery")
        if not self.async_step:
            # the sync loop has no prefill lane; re-prefill token-0
            # requests inline so the next decode batch has a history row
            # for every running request
            for req in list(self.scheduler.running):
                if req.state == "running" and not req.generated:
                    self._prefill_harvest(self._prefill_dispatch(req))
                    self._release_retired()

    def _replay_request(self, req: Request, *,
                        cause: str = "replay_recovery") -> None:
        """Replays ``req``'s known sequence (prompt + all but the last
        emitted token) into its blocks through the sampling-free
        ``prefill_chunk`` program.  After the replay the written KV covers
        exactly ``[0, prompt_len + n - 1)`` — the state an uninterrupted
        run holds before its next decode step — and the key chain is
        untouched, so the next draw is bit-identical.  Window-expired
        (sunk) table entries route their writes to the sink exactly like
        live padding; the keep-mask already excludes those positions."""
        n = len(req.generated)
        seq = np.concatenate([
            req.prompt, np.asarray(req.generated[:n - 1], dtype=np.int32),
        ])
        self._replay_seq(seq, req.block_table, req.adapter_slot,
                         req.prompt_len + n - 1, req=req, cause=cause)

    def _replay_seq(self, seq, block_table, adapter_slot: int,
                    target: int, *, req: Request | None = None,
                    cause: str = "replay_recovery") -> None:
        """The chunk-replay engine under :meth:`_replay_request` and the
        resident-session recovery replay: writes KV for ``seq[:target]``
        into ``block_table`` through the sampling-free ``prefill_chunk``
        programs, one fenced bucket-wide piece at a time."""
        sch, pool = self.scheduler, self.pool
        bs = pool.block_size
        seq = np.asarray(seq, dtype=np.int32)
        aligned = [t for t in sch.prefill_buckets if t % bs == 0]
        piece = max(aligned) if aligned else sch.prefill_buckets[-1]
        if getattr(self.cfg, "learned_pos_embedding", False):
            # suffix resume is off the table for learned-pos models (the
            # wpe dynamic_slice clamps past its rows); their capacity is
            # capped at cfg.block_size, so one piece from 0 always fits
            piece = max(piece, target)
        pos = 0
        while pos < target:
            t_disp = time.perf_counter() if self._goodput is not None else 0.0
            n_real = min(target - pos, piece)
            Tb = sch.prefill_bucket(n_real)
            nbb = self._nbb(max(len(block_table), -(-(pos + Tb) // bs)))
            toks = np.zeros(Tb, dtype=np.int32)
            toks[:n_real] = seq[pos:pos + n_real]
            table, dest = chunk_tables(block_table, pos, Tb, nbb, bs)
            if self.spec is not None:
                # the draft forward is deterministic, so the replay rebuilds
                # the draft arena bit-identically alongside the target's
                prog, _compiled = self._program("spec_prefill_chunk", Tb, nbb)
                arenas, darenas, qerr = prog(
                    self.params, self.spec.draft_params,
                    jnp.asarray(toks)[None], jnp.int32(pos),
                    pool.arenas, self.draft_pool.arenas,
                    jnp.asarray(table), jnp.asarray(dest),
                    self._lora_arenas(),
                    jnp.asarray([adapter_slot], dtype=jnp.int32),
                )
                self.draft_pool.set_arenas(darenas)
            else:
                prog, _compiled = self._program(self._chunk_kind(), Tb, nbb)
                arenas, qerr = prog(
                    self.params, jnp.asarray(toks)[None], jnp.int32(pos),
                    pool.arenas, jnp.asarray(table), jnp.asarray(dest),
                    self._lora_arenas(),
                    jnp.asarray([adapter_slot], dtype=jnp.int32),
                )
                self._note_chunk_attn_step()
            pool.set_arenas(arenas)
            if req is not None:
                # every real position of a replay piece is recomputation
                req.tokens_recomputed += n_real
                if cause not in req.recompute_causes:
                    req.recompute_causes.append(cause)
            gp = self._goodput
            if gp is not None:
                # replay pieces never stream: real positions are the given
                # replay cause, except sink-routed (window-expired) slots
                kind = ("spec_prefill_chunk" if self.spec is not None
                        else self._chunk_kind())
                sunk = self._sunk_positions(block_table, pos, n_real, bs)
                waste = {}
                if Tb > n_real:
                    waste["pad_prefill"] = Tb - n_real
                if sunk:
                    waste["replay_window"] = sunk
                if n_real > sunk:
                    waste[cause] = waste.get(cause, 0) + (n_real - sunk)
                gp.account(kind, 1, Tb, committed=0, **waste)
            pos = pos + n_real
            if req is not None:
                req.pos = pos
            float(np.asarray(qerr))        # fence this piece before the next
            if gp is not None:
                gp.note_device_s(
                    "spec_prefill_chunk" if self.spec is not None
                    else self._chunk_kind(), time.perf_counter() - t_disp)
            self._release_retired()
            self.chunk_runs += 1
            registry().counter("serving.steps.prefill_chunk").inc()

    def _discard_inflight(self, cause: str = "dead_scan_row") -> None:
        """Drops every in-flight future record (their tokens were never
        promised) plus the parked donated-arena handles: recovery and
        ``shutdown()`` must not leak futures or retired handles past the
        engine's life.  The derefs may block briefly until the consuming
        executions finish — this is the slow path, correctness over
        overlap.  ``cause`` classifies the discarded decode dispatch's
        device slots in the goodput ledger (``replay_recovery`` from
        recovery; the ``dead_scan_row`` default from shutdown)."""
        rec, self._inflight_decode = self._inflight_decode, None
        tr = self._tracer
        if rec is not None and tr is not None:
            for r in rec["running"]:
                tr.end(r.rid, "decode", aborted=True)
        gp = self._goodput
        if gp is not None and rec is not None:
            # the dispatch ran on device but will never be harvested: every
            # slot is waste (prefill pieces were accounted at dispatch)
            Bb = rec["bucket"][0]
            if rec.get("spec"):
                K = self.spec.K
                gp.account("draft_decode", Bb, K, **{cause: Bb * K})
                vkind = rec.get("vkind", "verify")
                gp.account(vkind, Bb, K + 1, **{cause: Bb * (K + 1)})
            else:
                n = rec.get("multi", 1)
                gp.account(rec["pkind"], Bb, n, **{cause: Bb * n})
        pending, self._inflight_prefill = self._inflight_prefill, []
        if tr is not None:
            for prec in pending:
                tr.end(prec["req"].rid, prec["span"], aborted=True)
        self._decode_state = None
        self._spec_state = None
        self._release_retired()

    #
    # compiled bucket programs
    #

    def _lora_arenas(self) -> dict:
        """The registry's stacked factor arenas as a program argument
        ({} without a registry — an empty pytree, zero buffers).  Fetched
        per call so registrations/evictions land without recompiling."""
        return self._registry.arenas if self._registry is not None else {}

    def _static_key(self) -> tuple | None:
        """Global program-cache key for everything baked into a bucket
        program besides its bucket dims — or None (per-engine programs only)
        when a custom ``model_fn`` makes the closure unkeyable.  Mesh
        engines extend the key with the mesh fingerprint (axis layout +
        device ids), so programs compile once per (mesh, bucket) and a
        different device set never reuses a stale placement.  The LoRA
        component is the registry *geometry* only — adapter ids and factor
        values are program arguments, so a batch mixing tenants can never
        grow the program set."""
        if self._forward is not forward_with_cache:
            return None
        import dataclasses

        return (
            tuple(sorted(dataclasses.asdict(self.cfg).items())),
            self.pool.block_size, str(self.pool.dtype), str(self.pool.kv_dtype),
            self.temperature, self.quantized,
            self._registry.geometry if self._registry is not None else None,
            self._mesh_key,
            # the speculative component: K, the draft architecture, and the
            # draft arena's storage dtype are baked into every spec program
            # (draft params are arguments)
            (self.spec.K,
             str(self.draft_pool.kv_dtype),
             tuple(sorted(dataclasses.asdict(self.spec.draft_cfg).items())))
            if self.spec is not None else None,
            # the multi-step horizon: ONE knob joining the key, not
            # per-horizon buckets; N=1 collapses to None so a decode_steps=1
            # engine shares the module program cache with default engines
            self.n_decode_steps if self.n_decode_steps > 1 else None,
            # constrained decoding: one boolean knob — schemas/automata are
            # mask ARGUMENTS (the LoRA idiom), so program identity never
            # sees a grammar; off collapses to None for cache sharing
            "constrained" if self._constraints else None,
        )

    def _program(self, kind: str, a: int, b: int) -> tuple[Callable, bool]:
        """The bucket program for ``(kind, a, b)`` plus whether THIS lookup
        built it fresh — i.e. the imminent call pays the XLA compile (a
        cached program, per-engine or module-wide, was already traced and
        compiled by its first caller)."""
        key = (kind, a, b)
        prog = self._programs.get(key)
        if prog is not None:
            return prog, False
        static = self._static_key()
        gkey = (static, kind, a, b) if static is not None else None
        prog = _program_cache.get(gkey) if gkey is not None else None
        compiled = prog is None
        if compiled:
            if kind in ("spec_prefill", "spec_prefill_chunk", "draft_decode",
                        "verify", "verify_paged"):
                from thunder_tpu.serving import speculative as _spec_mod

                build = partial({
                    "spec_prefill": _spec_mod.build_spec_prefill,
                    "spec_prefill_chunk": _spec_mod.build_spec_prefill_chunk,
                    "draft_decode": _spec_mod.build_draft_decode,
                    "verify": _spec_mod.build_verify,
                    "verify_paged": _spec_mod.build_verify_paged,
                }[kind], self)
            else:
                build = {"prefill": self._build_prefill,
                         "prefill_chunk": self._build_prefill_chunk,
                         "prefill_chunk_paged": self._build_prefill_chunk_paged,
                         "decode": self._build_decode,
                         "decode_paged": self._build_decode_paged,
                         "decode_multi": self._build_decode_multi,
                         "decode_multi_paged": self._build_decode_multi_paged,
                         }[kind]
            prog = build(a, b)
            # a genuinely new program for this geometry: count the compile
            self.compile_counts[kind] += 1
            self._compile_log.append({"kind": kind, "bucket": [a, b],
                                      "cause": f"new {kind} geometry"})
            registry().counter(f"serving.compiles.{kind}").inc()
            if gkey is not None:
                # LRU-ish bound (the _generate_cache idiom).  64, not 32: a
                # multi-tenant deployment legitimately runs several static
                # configs at once (f32 + int8 pools, per-registry-geometry
                # LoRA variants), and evicting a live config's programs
                # re-pays its compiles on the next request
                if len(_program_cache) >= 64:
                    _program_cache.pop(next(iter(_program_cache)))
                _program_cache[gkey] = prog
        self._programs[key] = prog
        return prog, compiled

    def _jit_kwargs(self, kind: str) -> dict:
        """Extra ``jax.jit`` kwargs for a bucket program: empty single-
        device; explicit in/out shardings under a mesh (params as placed,
        arenas per the pool's NamedSharding, host arrays replicated) so the
        compiled program is pjit-partitioned with per-shard arena donation."""
        if self.mesh is None:
            return {}
        from thunder_tpu.serving.mesh import program_shardings

        if self.spec is not None:
            return program_shardings(
                kind, self.params, self.mesh, self.pool.arena_sharding,
                draft_params=self.spec.draft_params,
                draft_arena_sh=self.draft_pool.arena_sharding,
            )
        kw = program_shardings(kind, self.params, self.mesh, self.pool.arena_sharding)
        if self._constraints and kind in (
                "prefill", "decode", "decode_paged",
                "decode_multi", "decode_multi_paged"):
            # the trailing constraint-mask argument is replicated like every
            # other small host-built per-step array
            from jax.sharding import NamedSharding, PartitionSpec

            repl = NamedSharding(self.mesh, PartitionSpec())
            kw["in_shardings"] = (*kw["in_shardings"], repl)
        return kw

    def _collective_census(self, bucket_key: tuple, prog, example_args) -> dict:
        """Collective count of one compiled decode program (mesh mode):
        how many cross-device ops one token step costs.  The census is an
        extra AOT compile, so it is cached module-wide next to the program
        cache — one census per (mesh, static config, bucket) per process —
        and mirrored into the ``serving.mesh.collectives.decode`` gauge."""
        static = self._static_key()
        gkey = ("collectives", static, *bucket_key) if static is not None else None
        got = _collectives_cache.get(gkey) if gkey is not None else None
        if got is None:
            from thunder_tpu.serving.mesh import collective_counts

            got = collective_counts(prog, *example_args)
            if gkey is not None:
                _collectives_cache[gkey] = got
        registry().gauge("serving.mesh.collectives.decode").set(got.get("total", 0))
        return got

    def _fwd_kwargs(self, lora_arenas, slots) -> dict:
        """The forward kwargs one bucket step adds on top of the base call:
        weight quantization (``quantized=``, PR-era int8 matmuls) plus the
        per-request LoRA factors gathered by slot — called inside the jit
        trace, so the gather is part of the compiled step."""
        kw = {"quantized": self.quantized}
        if self._registry is not None:
            kw["lora"] = gather_adapter_slots(lora_arenas, slots)
            kw["lora_scaling"] = self._registry.scaling
        return kw

    def _build_prefill(self, Tb: int, nbb: int) -> Callable:
        cfg, fwd, temp = self.cfg, self._forward, self.temperature
        qkv = self.pool.quantized_kv
        cdtype = jnp.dtype(self.pool.dtype)
        cap = self.pool.capacity_tokens(nbb)
        cos_all, sin_all = build_rope_cache(cfg, cap)

        # Constrained engines pass one trailing ``(1, V)`` bool mask; plain
        # engines pass nothing, so the traced program (and its module-cache
        # entry) is byte-identical to a pre-constraints engine.
        @partial(jax.jit, donate_argnums=(4,), **self._jit_kwargs("prefill"))
        def prefill(params, toks, pos, n_real, arenas, table, dest, key, lora, slot,
                    *cmask):
            if qkv:
                kd, vd = gather_dense_q(
                    arenas["k"], arenas["v"], arenas["k_scale"], arenas["v_scale"],
                    table[None, :], cdtype,
                )
            else:
                kd, vd = gather_dense(arenas["k"], arenas["v"], table[None, :])
            logits, cache = fwd(
                params, toks, pos, {"k": kd, "v": vd}, cos_all, sin_all, cfg,
                **self._fwd_kwargs(lora, slot),
            )
            last = jax.lax.dynamic_index_in_dim(logits, n_real - 1, axis=1, keepdims=False)
            if cmask:
                last = jnp.where(cmask[0], last, -jnp.inf)
            key, sub = jax.random.split(key)
            tok = sample_token(last, temp, sub)            # (1,) — solo-prefill parity
            if qkv:
                k_arena, k_scale, k_err = scatter_blocks_q(
                    arenas["k"], arenas["k_scale"], cache["k"], dest)
                v_arena, v_scale, v_err = scatter_blocks_q(
                    arenas["v"], arenas["v_scale"], cache["v"], dest)
                arenas = {"k": k_arena, "v": v_arena,
                          "k_scale": k_scale, "v_scale": v_scale}
                qerr = 0.5 * (k_err + v_err)
            else:
                arenas = {"k": scatter_blocks(arenas["k"], cache["k"], dest),
                          "v": scatter_blocks(arenas["v"], cache["v"], dest)}
                qerr = jnp.float32(0.0)
            return tok, arenas, key, qerr

        return prefill

    def _build_prefill_chunk(self, Tb: int, nbb: int) -> Callable:
        """An intermediate chunked-prefill piece: writes the chunk's KV into
        the arenas and nothing else — no sampling, no key split (the final
        ``prefill`` piece does both, so the request's draw stays
        bit-identical to an unchunked prefill).  The logits head is traced
        but unused, so XLA dead-code-eliminates the lm_head matmul — a
        chunk is strictly cheaper than a same-width prefill."""
        cfg, fwd = self.cfg, self._forward
        qkv = self.pool.quantized_kv
        cdtype = jnp.dtype(self.pool.dtype)
        cap = self.pool.capacity_tokens(nbb)
        cos_all, sin_all = build_rope_cache(cfg, cap)

        @partial(jax.jit, donate_argnums=(3,), **self._jit_kwargs("prefill_chunk"))
        def prefill_chunk(params, toks, pos, arenas, table, dest, lora, slot):
            if qkv:
                kd, vd = gather_dense_q(
                    arenas["k"], arenas["v"], arenas["k_scale"], arenas["v_scale"],
                    table[None, :], cdtype,
                )
            else:
                kd, vd = gather_dense(arenas["k"], arenas["v"], table[None, :])
            _logits, cache = fwd(
                params, toks, pos, {"k": kd, "v": vd}, cos_all, sin_all, cfg,
                **self._fwd_kwargs(lora, slot),
            )
            if qkv:
                k_arena, k_scale, k_err = scatter_blocks_q(
                    arenas["k"], arenas["k_scale"], cache["k"], dest)
                v_arena, v_scale, v_err = scatter_blocks_q(
                    arenas["v"], arenas["v_scale"], cache["v"], dest)
                arenas = {"k": k_arena, "v": v_arena,
                          "k_scale": k_scale, "v_scale": v_scale}
                qerr = 0.5 * (k_err + v_err)
            else:
                arenas = {"k": scatter_blocks(arenas["k"], cache["k"], dest),
                          "v": scatter_blocks(arenas["v"], cache["v"], dest)}
                qerr = jnp.float32(0.0)
            return arenas, qerr

        return prefill_chunk

    def _build_prefill_chunk_paged(self, Tb: int, nbb: int) -> Callable:
        """The kernel twin of :meth:`_build_prefill_chunk`: same signature,
        same returns — but the chunk's attention runs the multi-query paged
        kernel straight off the arenas (earlier chunks' KV is read in block
        granules with the causal intra-chunk mask fused in-kernel) and the
        chunk's fresh K/V lands via the block-granule chunk writer, so the
        compiled program contains zero arena gather/scatter primitives (the
        purity census asserts this with the gather chunk program as positive
        control).  Quantized pools take the fused absmax quantize-on-write
        epilogue; LoRA deltas run the fused kernel when meshless.  Only
        built when the construction-time chunk resolution picked "paged"
        (block-aligned chunk widths, no sliding window)."""
        from thunder_tpu.serving.paged_attention import (
            forward_paged,
            write_fresh_kv_chunk,
        )

        cfg = self.cfg
        qkv = self.pool.quantized_kv
        cdtype = jnp.dtype(self.pool.dtype)
        kv_dtype = jnp.dtype(self.pool.kv_dtype) if qkv else None
        bs = self.pool.block_size
        cap = self.pool.capacity_tokens(nbb)
        cos_all, sin_all = build_rope_cache(cfg, cap)
        mesh = self.mesh

        @partial(jax.jit, donate_argnums=(3,),
                 **self._jit_kwargs("prefill_chunk_paged"))
        def prefill_chunk_paged(params, toks, pos, arenas, table, dest, lora,
                                slot):
            pv = jnp.reshape(pos, (1,)).astype(jnp.int32)   # (B=1,) vec pos
            _logits, fresh = forward_paged(
                params, toks, pv, arenas, table[None, :], cos_all, sin_all,
                cfg, cdtype=cdtype, mesh=mesh, lora_fused=True,
                **self._fwd_kwargs(lora, slot),
            )
            return write_fresh_kv_chunk(
                arenas, fresh, dest, pv, block_size=bs,
                kv_dtype=kv_dtype, mesh=mesh)

        return prefill_chunk_paged

    def _build_decode(self, Bb: int, nbb: int) -> Callable:
        cfg, fwd, temp = self.cfg, self._forward, self.temperature
        qkv = self.pool.quantized_kv
        cdtype = jnp.dtype(self.pool.dtype)
        bs = self.pool.block_size
        cap = self.pool.capacity_tokens(nbb)
        cos_all, sin_all = build_rope_cache(cfg, cap)

        # The scatter destination is DERIVED inside the program (block =
        # table[pos // bs], slot = pos % bs) and the program returns pos+1,
        # so a steady-state decode step consumes only its predecessor's
        # device outputs (toks=nxt, keys=new_keys, pos=new_pos) plus the
        # cached tables/slots — zero host->device transfers per step (the
        # engine's _decode_state chain).  Padding rows carry all-sink
        # tables, and out-of-range block indices clamp to the row's last
        # (sink) entry, so derived destinations stay sink-routed.
        # Constrained engines pass one trailing ``(Bb, V)`` bool mask
        # (all-True rows are a bit-exact no-op); plain engines pass nothing.
        @partial(jax.jit, donate_argnums=(4,), **self._jit_kwargs("decode"))
        def decode(params, toks, pos, tables, arenas, keys, lora, slots, *cmask):
            dest_block = jnp.take_along_axis(
                tables, (pos // bs)[:, None], axis=1)[:, 0]
            dest_slot = pos % bs
            if qkv:
                kd, vd = gather_dense_q(
                    arenas["k"], arenas["v"], arenas["k_scale"], arenas["v_scale"],
                    tables, cdtype,
                )
            else:
                kd, vd = gather_dense(arenas["k"], arenas["v"], tables)
            logits, cache = fwd(
                params, toks[:, None], pos, {"k": kd, "v": vd}, cos_all, sin_all, cfg,
                **self._fwd_kwargs(lora, slots),
            )
            sp = jax.vmap(jax.random.split)(keys)          # per-request key chains
            new_keys, subs = sp[:, 0], sp[:, 1]
            lg = logits[:, 0]
            if cmask:
                lg = jnp.where(cmask[0], lg, -jnp.inf)
            # (1, V) per row under vmap == the unbatched B=1 generate() draw
            nxt = jax.vmap(lambda l, k: sample_token(l[None], temp, k)[0])(
                lg, subs
            )
            kc = cache["k"].transpose(1, 0, 2, 3, 4)       # (B, L, ng, cap, hs)
            vc = cache["v"].transpose(1, 0, 2, 3, 4)
            pick = jax.vmap(
                lambda c, p: jax.lax.dynamic_index_in_dim(c, p, axis=2, keepdims=False)
            )
            if qkv:
                # the picked values are THIS step's freshly computed K/V (the
                # dense cache write at pos), so quantize-on-scatter sees exact
                # inputs — no requantization drift across steps
                k_arena, k_scale = scatter_token_q(
                    arenas["k"], arenas["k_scale"], pick(kc, pos), dest_block, dest_slot)
                v_arena, v_scale = scatter_token_q(
                    arenas["v"], arenas["v_scale"], pick(vc, pos), dest_block, dest_slot)
                arenas = {"k": k_arena, "v": v_arena,
                          "k_scale": k_scale, "v_scale": v_scale}
            else:
                arenas = {"k": scatter_token(arenas["k"], pick(kc, pos), dest_block, dest_slot),
                          "v": scatter_token(arenas["v"], pick(vc, pos), dest_block, dest_slot)}
            return nxt, new_keys, pos + 1, arenas

        return decode

    def _build_decode_paged(self, Bb: int, nbb: int) -> Callable:
        """The kernel twin of :meth:`_build_decode`: same signature, same
        sampling/key-chain math, same returns — but attention runs the
        Pallas paged kernel straight off the arenas (scalar-prefetch block
        tables, in-kernel keep-mask + dequant) and the fresh token lands via
        the aliased write kernel, so the compiled program contains zero
        gather/scatter primitives (tests assert this on the jaxpr) and no
        dense cache ever materializes."""
        from thunder_tpu.serving.paged_attention import forward_paged, write_fresh_kv

        cfg, temp = self.cfg, self.temperature
        qkv = self.pool.quantized_kv
        cdtype = jnp.dtype(self.pool.dtype)
        kv_dtype = jnp.dtype(self.pool.kv_dtype) if qkv else None
        bs = self.pool.block_size
        cap = self.pool.capacity_tokens(nbb)
        cos_all, sin_all = build_rope_cache(cfg, cap)
        mesh = self.mesh

        @partial(jax.jit, donate_argnums=(4,), **self._jit_kwargs("decode_paged"))
        def decode_paged(params, toks, pos, tables, arenas, keys, lora, slots,
                         *cmask):
            logits, fresh = forward_paged(
                params, toks[:, None], pos, arenas, tables, cos_all, sin_all,
                cfg, cdtype=cdtype, mesh=mesh, lora_fused=True,
                **self._fwd_kwargs(lora, slots),
            )
            sp = jax.vmap(jax.random.split)(keys)          # per-request key chains
            new_keys, subs = sp[:, 0], sp[:, 1]
            lg = logits[:, 0]
            if cmask:
                lg = jnp.where(cmask[0], lg, -jnp.inf)
            nxt = jax.vmap(lambda l, k: sample_token(l[None], temp, k)[0])(
                lg, subs
            )
            arenas = write_fresh_kv(arenas, fresh, tables, pos, block_size=bs,
                                    kv_dtype=kv_dtype, mesh=mesh)
            return nxt, new_keys, pos + 1, arenas

        return decode_paged

    def _build_decode_multi(self, Bb: int, nbb: int) -> Callable:
        """N decode steps per host visit: the single-step decode body
        wrapped in a ``lax.scan`` with in-program stopping.

        Per-row liveness: a row is live while ``pos <= stop`` and no EOS has
        been sampled (``stop = prompt_len + max_new_tokens - 2`` is the last
        position a row may write — exactly the position at which the
        single-step engine's :meth:`_emit_token` fires FINISH_LENGTH on the
        resulting token).  A dead row keep-masks its KV write to the sink
        block (:func:`dest_for_pos`), freezes ``pos`` and ``toks``, and
        stops splitting its PRNG key — so the per-request key chain advances
        exactly once per *emitted* token, preserving the harvest-time
        key-advance contract that makes fault-recovery replay bit-identical.
        Padding rows enter with ``stop = -1`` and are dead from step 0.

        Returns the scan's stacked ``(ys_tok, ys_emit)`` — the (N, Bb)
        token matrix and liveness mask the harvest reads — plus the final
        ``(toks, keys, pos)`` carry for the engine's ``_decode_state``
        device-to-device chain, and the donated arenas."""
        cfg, fwd, temp = self.cfg, self._forward, self.temperature
        qkv = self.pool.quantized_kv
        cdtype = jnp.dtype(self.pool.dtype)
        bs = self.pool.block_size
        cap = self.pool.capacity_tokens(nbb)
        cos_all, sin_all = build_rope_cache(cfg, cap)
        eos = self.eos_id
        N = self.n_decode_steps

        @partial(jax.jit, donate_argnums=(4,),
                 **self._jit_kwargs("decode_multi"))
        def decode_multi(params, toks, pos, tables, arenas, keys, lora, slots, stop,
                         *cmask):
            kw = self._fwd_kwargs(lora, slots)   # LoRA gather once per visit
            live0 = pos <= stop

            def body(carry, step_mask):
                toks, pos, keys, live, arenas = carry
                dest_block, dest_slot = dest_for_pos(
                    tables, pos, live, block_size=bs)
                if qkv:
                    kd, vd = gather_dense_q(
                        arenas["k"], arenas["v"],
                        arenas["k_scale"], arenas["v_scale"], tables, cdtype,
                    )
                else:
                    kd, vd = gather_dense(arenas["k"], arenas["v"], tables)
                logits, cache = fwd(
                    params, toks[:, None], pos, {"k": kd, "v": vd},
                    cos_all, sin_all, cfg, **kw,
                )
                sp = jax.vmap(jax.random.split)(keys)
                new_keys = jnp.where(live[:, None], sp[:, 0], keys)
                lg = logits[:, 0]
                if cmask:
                    lg = jnp.where(step_mask, lg, -jnp.inf)
                nxt = jax.vmap(lambda l, k: sample_token(l[None], temp, k)[0])(
                    lg, sp[:, 1]
                )
                kc = cache["k"].transpose(1, 0, 2, 3, 4)
                vc = cache["v"].transpose(1, 0, 2, 3, 4)
                pick = jax.vmap(
                    lambda c, p: jax.lax.dynamic_index_in_dim(
                        c, p, axis=2, keepdims=False)
                )
                if qkv:
                    k_arena, k_scale = scatter_token_q(
                        arenas["k"], arenas["k_scale"], pick(kc, pos),
                        dest_block, dest_slot)
                    v_arena, v_scale = scatter_token_q(
                        arenas["v"], arenas["v_scale"], pick(vc, pos),
                        dest_block, dest_slot)
                    new_arenas = {"k": k_arena, "v": v_arena,
                                  "k_scale": k_scale, "v_scale": v_scale}
                else:
                    new_arenas = {
                        "k": scatter_token(arenas["k"], pick(kc, pos),
                                           dest_block, dest_slot),
                        "v": scatter_token(arenas["v"], pick(vc, pos),
                                           dest_block, dest_slot)}
                done = pos >= stop
                if eos is not None:
                    done = done | (nxt == eos)
                toks_n = jnp.where(live, nxt, toks)
                pos_n = jnp.where(live, pos + 1, pos)
                live_n = live & ~done
                return (toks_n, pos_n, new_keys, live_n, new_arenas), (nxt, live)

            # the constraint masks are scan xs: one (Bb, V) slice per step,
            # computed host-side from the exact masks(N) lookahead
            (toks_f, pos_f, keys_f, _live_f, arenas), (ys_tok, ys_emit) = (
                jax.lax.scan(body, (toks, pos, keys, live0, arenas),
                             cmask[0] if cmask else None, length=N))
            return ys_tok, ys_emit, toks_f, keys_f, pos_f, arenas

        return decode_multi

    def _build_decode_multi_paged(self, Bb: int, nbb: int) -> Callable:
        """The kernel twin of :meth:`_build_decode_multi`: same scan, same
        liveness/key-chain math, but each iteration runs the Pallas paged
        kernel straight off the arenas and folds the fresh token K/V back
        in via the masked write kernel (live rows commit at ``pos``, dead
        rows keep-mask to the sink block) — so the compiled N-step program
        still contains zero arena gather/scatter primitives (the purity
        census asserts this with the gather program as positive control)."""
        from thunder_tpu.serving.paged_attention import (
            forward_paged,
            write_fresh_kv_live,
        )

        cfg, temp = self.cfg, self.temperature
        qkv = self.pool.quantized_kv
        cdtype = jnp.dtype(self.pool.dtype)
        kv_dtype = jnp.dtype(self.pool.kv_dtype) if qkv else None
        bs = self.pool.block_size
        cap = self.pool.capacity_tokens(nbb)
        cos_all, sin_all = build_rope_cache(cfg, cap)
        mesh = self.mesh
        eos = self.eos_id
        N = self.n_decode_steps

        @partial(jax.jit, donate_argnums=(4,),
                 **self._jit_kwargs("decode_multi_paged"))
        def decode_multi_paged(params, toks, pos, tables, arenas, keys, lora,
                               slots, stop, *cmask):
            kw = self._fwd_kwargs(lora, slots)   # LoRA gather once per visit
            live0 = pos <= stop

            def body(carry, step_mask):
                toks, pos, keys, live, arenas = carry
                logits, fresh = forward_paged(
                    params, toks[:, None], pos, arenas, tables,
                    cos_all, sin_all, cfg, cdtype=cdtype, mesh=mesh,
                    lora_fused=True, **kw,
                )
                sp = jax.vmap(jax.random.split)(keys)
                new_keys = jnp.where(live[:, None], sp[:, 0], keys)
                lg = logits[:, 0]
                if cmask:
                    lg = jnp.where(step_mask, lg, -jnp.inf)
                nxt = jax.vmap(lambda l, k: sample_token(l[None], temp, k)[0])(
                    lg, sp[:, 1]
                )
                new_arenas = write_fresh_kv_live(
                    arenas, fresh, tables, pos, live,
                    block_size=bs, kv_dtype=kv_dtype, mesh=mesh)
                done = pos >= stop
                if eos is not None:
                    done = done | (nxt == eos)
                toks_n = jnp.where(live, nxt, toks)
                pos_n = jnp.where(live, pos + 1, pos)
                live_n = live & ~done
                return (toks_n, pos_n, new_keys, live_n, new_arenas), (nxt, live)

            (toks_f, pos_f, keys_f, _live_f, arenas), (ys_tok, ys_emit) = (
                jax.lax.scan(body, (toks, pos, keys, live0, arenas),
                             cmask[0] if cmask else None, length=N))
            return ys_tok, ys_emit, toks_f, keys_f, pos_f, arenas

        return decode_multi_paged


def serve(model_fn, params, cfg, **kwargs) -> ServingEngine:
    """Builds a :class:`ServingEngine` over ``model_fn`` (``None`` → the
    in-tree ``models.generate.forward_with_cache``).  See
    :class:`ServingEngine` for the knobs; nothing about constructing an
    engine touches any other compiled program (strictly additive).

    Mesh serving: ``serve(None, params, cfg, mesh=mesh)`` makes the whole
    engine SPMD — params are placed once (``shardings=`` overrides the
    default llama TP×FSDP rules), the paged K/V arenas shard their heads
    dim over ``tp`` (:func:`thunder_tpu.distributed.kv_cache_spec`), and
    every bucket program compiles once per (mesh, bucket) with explicit
    shardings and per-shard arena donation.  Served tokens stay
    bit-identical to solo ``generate(..., mesh=mesh)`` on the same mesh.

    Multi-tenant serving: ``kv_dtype="int8"`` stores the KV block arenas
    quantized (~``hs*itemsize/(hs+4)``x the resident requests per arena
    byte, quantize-on-scatter / dequant-on-gather inside the bucket
    programs, measured error in the ``serving.kv_quant.rel_err`` gauge);
    ``lora=AdapterRegistry(...)`` lets ``submit(..., adapter_id=...)``
    route each request through a registered LoRA adapter — batches freely
    mix tenants, and the compiled-program set grows only with the registry
    *geometry* (rank, slots, targets), never with adapter ids.

    Paged-attention decode: ``attn="paged"`` runs decode through the Pallas
    flash-decoding kernel straight off the KV block arena (scalar-prefetch
    block tables, in-kernel keep-mask and int8/fp8 dequant, aliased
    in-place fresh-token write) — the compiled decode program contains zero
    gather/scatter primitives and no dense cache copy.  ``attn="auto"``
    (default) takes the kernel when structurally supported and Pallas is
    enabled (TPU, or ``THUNDER_TPU_PALLAS_INTERPRET=1`` for interpret mode
    on CPU), else falls back to the gather path, counting
    ``serving.attn.fallback_steps``; ``attn="gather"`` pins the dense
    gather/scatter pair.  Served tokens are bit-identical across all three.

    Multi-step decode: ``decode_steps=N`` runs N decode steps per host
    visit inside one compiled program (a ``lax.scan`` over the decode body
    with in-program EOS/length stopping and per-request liveness masks —
    finished rows keep-mask their KV writes to the sink block), serving up
    to N tokens per dispatch.  Tokens stay bit-identical to the 1-step
    engine across the whole matrix (greedy/temperature, int8/fp8 KV, LoRA,
    prefix sharing, chunked prefill, fault recovery); host visits per
    served token drop to ~1/N.  N joins the program static key as one knob
    (not per-horizon buckets), and ``decode_steps=1`` (default) is
    byte-identical to the pre-knob engine, sharing the module program
    cache.  The trade-off is loop-boundary scheduling: admissions,
    deadline expiry, window reclamation, and streaming all happen at visit
    boundaries, so N widens token-delivery granularity by up to N steps.
    Incompatible with ``speculative=`` (that lane already amortizes host
    visits over accepted tokens; construction raises with the reason).

    Async serving: ``async_step=True`` (default) runs ``step()`` as an
    event loop — decode for batch *k* is dispatched and the host admits,
    schedules, and streams batch *k−1*'s tokens before blocking
    (``serving.step.overlap_frac`` measures the win); ``prefill_chunk=N``
    additionally splits prompts longer than N into block-aligned chunks
    dispatched one per step between decodes, so a long prompt neither
    stalls running requests' TPOT nor hits the prompt-length admission cap.
    ``async_step=False`` keeps the original fully synchronous loop
    byte-identical; served tokens are bit-identical either way.

    Fault tolerance: a classified step exception no longer kills the
    engine — per-request anomalies quarantine just the offending request
    (``finish_reason="error"`` + structured cause, blocks freed, prefix
    index scrubbed), transient dispatch failures retry with exponential
    backoff (``retry=RetryPolicy(...)``), and engine-class faults (OOM,
    hangs caught by ``watchdog_timeout_s=...``, retry exhaustion) trigger
    **re-prefill recovery**: fresh arenas are rebuilt and every surviving
    request is replayed from its prompt + emitted tokens, after which the
    decode stream continues bit-identical to an uninterrupted run (the
    PRNG chain only advances at harvest, so the KV arena is soft state).
    ``fault_plan=FaultPlan(...)`` (or ``THUNDER_TPU_FAULT_PLAN`` JSON)
    injects deterministic seeded faults at the named fault points for
    chaos testing; ``fault_plan=None`` leaves every compiled program
    byte-identical — the plan lives purely on the host side.

    Speculative serving: ``speculative=SpecConfig(draft_params, draft_cfg,
    K=...)`` swaps each decode turn for a draft/verify round — a draft KV
    block arena rides beside the target arena (same block tables, same
    ``kv_dtype``/mesh treatment), K chained draft forwards propose tokens,
    one (K+1)-position target forward verifies them through the shared
    rejection rule (``models.speculative.accept_tokens``), and 1..K+1
    tokens emit per round.  PRNG keys advance only at harvest, so served
    tokens are bit-identical to solo ``speculative_generate()`` — greedy
    or sampled — and re-prefill recovery replays both arenas
    deterministically.  ``speculative=None`` (default) leaves every
    compiled program byte-identical to a spec-free engine.

    Data-parallel replication: a mesh with a ``dp`` axis (size > 1) — or
    an explicit ``replicas=N`` without a mesh — returns a
    :class:`~thunder_tpu.serving.router.ReplicatedEngine`: the device set
    splits into ``dp`` submeshes (each engine keeps every other axis, so
    ``(dp, tp)`` runs TP-sharded replicas), one async engine per replica
    with its own arena / lanes / program-cache entries, fronted by a
    single prefix-affinity router that keeps this exact API.  Faults stay
    replica-scoped; pass ``fault_plans=[...]`` (one entry per replica)
    instead of the solo ``fault_plan=``.  ``replicas=1`` / no-``dp``-axis
    returns a plain :class:`ServingEngine` whose compiled programs are
    byte-identical to today's (the module program cache is shared either
    way).  See :mod:`thunder_tpu.serving.router` for routing semantics
    and the multi-host (process-0) caveat."""
    replicas = kwargs.pop("replicas", None)
    fault_plans = kwargs.pop("fault_plans", None)
    mesh = kwargs.get("mesh")
    dp = 0
    if mesh is not None and "dp" in mesh.axis_names:
        dp = int(mesh.shape["dp"])
        if replicas is not None and replicas != dp:
            raise ValueError(
                f"replicas={replicas} conflicts with the mesh dp axis of "
                f"size {dp} — pass one or the other"
            )
    n = replicas if replicas is not None else dp
    if n is not None and n > 1:
        from thunder_tpu.serving.router import ReplicatedEngine

        if mesh is not None and dp == 0:
            raise ValueError(
                f"replicas={n} with a mesh requires a 'dp' axis to split "
                f"on (axes: {mesh.axis_names})"
            )
        return ReplicatedEngine(params, cfg, model_fn=model_fn, replicas=n,
                                fault_plans=fault_plans, **kwargs)
    if fault_plans is not None:
        raise ValueError(
            "fault_plans= is the per-replica form; a solo engine takes "
            "fault_plan="
        )
    return ServingEngine(params, cfg, model_fn=model_fn, **kwargs)
