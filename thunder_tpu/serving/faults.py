"""Deterministic fault injection + retry/recovery policy for the serving engine.

A production engine must treat device faults and poison requests as routine,
and the only way to *test* that is to make failures reproducible.  This
module is the chaos harness and the policy vocabulary the engine's recovery
layer (:mod:`serving.engine`) speaks:

- **Fault points** are the places the event loop touches the device:
  :data:`FP_PREFILL` / :data:`FP_DECODE` / :data:`FP_DRAFT` /
  :data:`FP_VERIFY` (program dispatch, before the call —
  host state is still consistent and the arenas are not yet donated),
  :data:`FP_SCATTER` (after the program call, before the returned arenas are
  installed — the donated inputs are already consumed, so a fault here can
  never be retried against stale handles), and :data:`FP_HARVEST` (the
  materialization of an in-flight record — a fault here loses the step's
  tokens for the whole batch).
- **Fault kinds** map to exception classes the engine classifies by blast
  radius: ``"fail"`` → :class:`TransientDispatchFault` (retryable),
  ``"nan"`` → :class:`RequestAnomalyFault` (per-request poison → quarantine),
  ``"oom"`` → :class:`DeviceOOMFault` (engine-wide → recovery), and
  ``"hang"`` → :class:`HarvestHangFault` (the injectable stand-in for a hung
  harvest; a *real* hang is converted to :class:`WatchdogTimeout` by the
  engine's ``watchdog_timeout_s`` clock check — both classify engine-wide).
- A :class:`FaultPlan` is **deterministic**: either an explicit list of
  :class:`FaultSpec` rows (fire at the ``at``-th arrival of a point,
  optionally only for a given rid) or a seeded random mode (``seed=``,
  ``rate=``, bounded by ``max_faults`` so any plan eventually allows
  progress — the differential-recovery guarantee is only testable for plans
  that exhaust).  Checks are pure host arithmetic; an unarmed engine holds
  ``None`` and pays one ``is None`` test per fault point, so the compiled
  programs are byte-identical with or without a plan (tested via the
  module program cache).

``tt.serve(..., fault_plan=...)`` accepts a plan/spec/dict/list, and
``THUNDER_TPU_FAULT_PLAN`` (JSON) arms engines from the environment —
chaos-test a deployment without touching its code.

Recovery's re-prefill replay (and the device work a fault strands in
flight) is attributed, not hidden: with ``goodput=True`` the engine
charges discarded in-flight dispatches and every replayed position to
the ``replay_recovery`` waste cause in the goodput ledger and bills the
affected :class:`RequestResult` (``tokens_recomputed`` /
``recompute_causes``) — the chaos soak's recovery cost is a number, not
a vibe.
"""
from __future__ import annotations

import json
import os
import time
from dataclasses import dataclass, field
from typing import Callable, Sequence

import numpy as np

from thunder_tpu.observability.metrics import registry

__all__ = [
    "FP_PREFILL",
    "FP_DECODE",
    "FP_DRAFT",
    "FP_VERIFY",
    "FP_HARVEST",
    "FP_SCATTER",
    "FP_TRAIN_STEP",
    "FP_CKPT_SAVE",
    "FAULT_POINTS",
    "FAULT_KINDS",
    "FaultSpec",
    "FaultPlan",
    "FaultError",
    "TransientDispatchFault",
    "RequestAnomalyFault",
    "DeviceOOMFault",
    "HarvestHangFault",
    "WatchdogTimeout",
    "RecoveryError",
    "RetryPolicy",
    "classify_fault",
    "resolve_fault_plan",
]

# named fault points — where the event loop touches the device.  The
# speculative lane adds two dispatch sites: FP_DRAFT before the draft
# program and FP_VERIFY between draft and verify — both pre-donation (the
# draft rerun is deterministic and ``_spec_state`` only advances at
# harvest), so they retry/quarantine/recover exactly like FP_DECODE.
FP_PREFILL = "prefill.dispatch"
FP_DECODE = "decode.dispatch"
FP_DRAFT = "draft.dispatch"
FP_VERIFY = "verify.dispatch"
FP_HARVEST = "harvest"
FP_SCATTER = "scatter"
# training-plane fault points (thunder_tpu.train.loop / train.checkpoint):
# FP_TRAIN_STEP fires before the train-step dispatch (params/opt state
# intact, so transient faults retry the same step) and FP_CKPT_SAVE inside
# the async checkpoint worker (a failed save surfaces as a harvest record,
# never into the step path)
FP_TRAIN_STEP = "train.step"
FP_CKPT_SAVE = "checkpoint.save"
FAULT_POINTS = (FP_PREFILL, FP_DECODE, FP_DRAFT, FP_VERIFY, FP_HARVEST, FP_SCATTER,
                FP_TRAIN_STEP, FP_CKPT_SAVE)

FAULT_KINDS = ("fail", "nan", "oom", "hang")

# blast-radius classes the engine's _absorb_fault switches on
CLASS_REQUEST = "request"      # poison request → quarantine, keep serving
CLASS_TRANSIENT = "transient"  # retryable dispatch failure → backoff + retry
CLASS_ENGINE = "engine"        # device-wide → rebuild arenas + re-prefill


class FaultError(RuntimeError):
    """Base of every injected (or watchdog-synthesized) serving fault.

    Carries the structured cause the quarantine/recovery machinery threads
    into ``RequestResult.error``, flight-recorder entries, and telemetry:
    ``point`` (which fault point raised), ``kind``, ``rids`` (the requests
    in flight at the point), and ``injected`` (False for watchdog/real)."""

    kind = "fail"

    def __init__(self, point: str, rids: Sequence[int] = (), *,
                 injected: bool = True, message: str | None = None):
        self.point = point
        self.rids = tuple(int(r) for r in rids)
        self.injected = injected
        super().__init__(
            message if message is not None else
            f"injected {self.kind!r} fault at {point} (rids={list(self.rids)})"
        )

    def cause(self) -> dict:
        """The structured cause dict (JSON-safe) this fault propagates."""
        return {
            "type": type(self).__name__,
            "point": self.point,
            "kind": self.kind,
            "rids": list(self.rids),
            "injected": self.injected,
            "message": str(self),
        }


class TransientDispatchFault(FaultError):
    """A dispatch failed in a way worth retrying (the injected analogue of
    a transient RPC error out of the runtime)."""

    kind = "fail"


class RequestAnomalyFault(FaultError):
    """A request poisoned its own step (the injected analogue of a NaN/Inf
    anomaly traced to one request's math) — quarantine it, keep the rest."""

    kind = "nan"


class DeviceOOMFault(FaultError):
    """Device memory exhausted mid-step: the arenas are suspect, so the only
    way forward is arena rebuild + re-prefill."""

    kind = "oom"


class HarvestHangFault(FaultError):
    """Injectable stand-in for a harvest that never completes.  A real hang
    cannot raise; the engine's watchdog (``watchdog_timeout_s``) converts it
    to :class:`WatchdogTimeout` — both land in the same recovery path."""

    kind = "hang"


class WatchdogTimeout(FaultError):
    """An in-flight record aged past ``watchdog_timeout_s`` on the engine
    clock without being harvested: treat the step as lost and recover."""

    kind = "hang"

    def __init__(self, point: str, rids: Sequence[int] = (), *,
                 age_s: float | None = None):
        self.age_s = age_s
        super().__init__(
            point, rids, injected=False,
            message=(f"watchdog: in-flight {point} record aged "
                     f"{age_s:.3f}s past the timeout (rids={[int(r) for r in rids]})"
                     if age_s is not None else
                     f"watchdog: in-flight {point} record timed out"),
        )


class RecoveryError(RuntimeError):
    """Re-prefill recovery could not complete within the retry budget; the
    engine is not serviceable (carries the last underlying fault as
    ``__cause__``)."""


_KIND_EXC = {
    "fail": TransientDispatchFault,
    "nan": RequestAnomalyFault,
    "oom": DeviceOOMFault,
    "hang": HarvestHangFault,
}

# message fragments that classify *real* runtime exceptions the same way
# injected ones are: transient RPC-ish failures retry, allocation failures
# force an arena rebuild (the strings are the jax/XLA status-code surface)
_TRANSIENT_MARKERS = ("DEADLINE_EXCEEDED", "UNAVAILABLE", "ABORTED")
_ENGINE_MARKERS = ("RESOURCE_EXHAUSTED", "out of memory", "Out of memory")


def classify_fault(exc: BaseException) -> str | None:
    """Blast-radius class of an exception out of ``step()``:
    ``"request"`` / ``"transient"`` / ``"engine"``, or ``None`` for
    anything the recovery layer must not absorb (programming errors keep
    the existing crash-dump-and-raise contract)."""
    if isinstance(exc, RequestAnomalyFault):
        return CLASS_REQUEST
    if isinstance(exc, TransientDispatchFault):
        return CLASS_TRANSIENT
    if isinstance(exc, (DeviceOOMFault, HarvestHangFault, WatchdogTimeout)):
        return CLASS_ENGINE
    # NOTE: a real AnomalyError (debug_anomalies mode) stays un-absorbed on
    # purpose — the user armed that check to crash with symbol attribution,
    # and silently recovering would defeat the debugging tool.
    msg = str(exc)
    if any(m in msg for m in _TRANSIENT_MARKERS):
        return CLASS_TRANSIENT
    if any(m in msg for m in _ENGINE_MARKERS):
        return CLASS_ENGINE
    return None


def fault_cause(exc: BaseException) -> dict:
    """Structured cause for any classified exception (FaultErrors carry
    their own; real exceptions get a best-effort envelope)."""
    if isinstance(exc, FaultError):
        return exc.cause()
    return {
        "type": type(exc).__name__,
        "point": None,
        "kind": classify_fault(exc),
        "rids": [],
        "injected": False,
        "message": str(exc),
    }


@dataclass(frozen=True)
class FaultSpec:
    """One deterministic fault: fire at the ``at``-th (1-based) arrival of
    ``point`` — counted over arrivals matching ``rid`` when set — for
    ``count`` consecutive arrivals.  ``kind`` picks the exception class."""

    point: str
    kind: str = "fail"
    at: int = 1
    rid: int | None = None
    count: int = 1

    def __post_init__(self):
        if self.point not in FAULT_POINTS:
            raise ValueError(f"unknown fault point {self.point!r}; expected one of {FAULT_POINTS}")
        if self.kind not in FAULT_KINDS:
            raise ValueError(f"unknown fault kind {self.kind!r}; expected one of {FAULT_KINDS}")
        if self.at < 1 or self.count < 1:
            raise ValueError(f"at/count must be >= 1, got at={self.at} count={self.count}")


@dataclass
class FaultPlan:
    """A deterministic schedule of injected faults.

    Two modes, composable: explicit ``specs`` fire by arrival count, and a
    seeded random mode (``seed`` + ``rate``) flips a biased coin per check —
    the same seed always yields the same fault sequence for the same
    workload.  ``max_faults`` bounds *total* injections (both modes), so any
    plan eventually stops interfering — the recovery guarantee ("drained
    tokens bit-identical to the fault-free run") is only meaningful for
    plans that allow progress."""

    specs: Sequence[FaultSpec] = ()
    seed: int | None = None
    rate: float = 0.0
    kinds: Sequence[str] = ("fail", "nan", "oom", "hang")
    max_faults: int = 8

    def __post_init__(self):
        self.specs = tuple(
            s if isinstance(s, FaultSpec) else FaultSpec(**s) for s in self.specs
        )
        for k in self.kinds:
            if k not in FAULT_KINDS:
                raise ValueError(f"unknown fault kind {k!r}; expected one of {FAULT_KINDS}")
        if not (0.0 <= float(self.rate) <= 1.0):
            raise ValueError(f"rate must be in [0, 1], got {self.rate}")
        self._rng = np.random.default_rng(self.seed) if self.seed is not None else None
        self._arrivals: dict = {}          # (point, rid-constraint) -> count
        self.injected = 0
        self.fired: list[dict] = []

    def _spec_matches(self, spec: FaultSpec, point: str, rids: Sequence[int]) -> bool:
        if spec.point != point:
            return False
        if spec.rid is not None and spec.rid not in rids:
            return False
        n = self._arrivals[(spec.point, spec.rid)]
        return spec.at <= n < spec.at + spec.count

    def check(self, point: str, rids: Sequence[int] = ()) -> None:
        """Called by the engine at each fault point; raises the scheduled
        fault (counted in ``serving.faults.injected``) or returns."""
        if self.injected >= self.max_faults:
            return
        rids = tuple(int(r) for r in rids)
        seen = set()
        for spec in self.specs:
            k = (spec.point, spec.rid)
            if k not in seen and (spec.rid is None or spec.rid in rids) and spec.point == point:
                self._arrivals[k] = self._arrivals.get(k, 0) + 1
                seen.add(k)
        for spec in self.specs:
            if self._spec_matches(spec, point, rids):
                # a rid-pinned anomaly blames exactly that request — the
                # quarantine blast radius is the poison request, never the
                # batch it happened to share a step with
                self._fire(spec.kind, point,
                           rids if spec.rid is None else (spec.rid,))
        if self._rng is not None and self.rate > 0.0:
            if float(self._rng.random()) < self.rate:
                kinds = [k for k in self.kinds
                         # a per-request anomaly needs a request to blame
                         if not (k == "nan" and not rids)]
                if kinds:
                    kind = kinds[int(self._rng.integers(len(kinds)))]
                    blame = ((rids[int(self._rng.integers(len(rids)))],)
                             if kind == "nan" else rids)
                    self._fire(kind, point, blame)

    def _fire(self, kind: str, point: str, rids: tuple[int, ...]):
        self.injected += 1
        exc = _KIND_EXC[kind](point, rids)
        self.fired.append(exc.cause())
        registry().counter("serving.faults.injected").inc()
        raise exc

    def snapshot(self) -> dict:
        """Plan state for ``engine.stats()`` / the flight recorder."""
        return {
            "injected": self.injected,
            "max_faults": self.max_faults,
            "seed": self.seed,
            "rate": self.rate,
            "specs": len(self.specs),
            "fired": list(self.fired),
        }


@dataclass
class RetryPolicy:
    """Bounded retry with exponential backoff on an injectable sleep.

    ``backoff(attempt)`` (1-based) returns ``backoff_s * multiplier**(n-1)``;
    the engine sleeps that between transient-fault retries and recovery
    attempts.  Tests inject ``sleep=`` to record delays without waiting."""

    max_retries: int = 3
    backoff_s: float = 0.05
    multiplier: float = 2.0
    sleep: Callable[[float], None] = field(default=time.sleep)

    def __post_init__(self):
        if self.max_retries < 0:
            raise ValueError(f"max_retries must be >= 0, got {self.max_retries}")
        if self.backoff_s < 0 or self.multiplier < 1.0:
            raise ValueError(
                f"backoff_s must be >= 0 and multiplier >= 1, got "
                f"backoff_s={self.backoff_s} multiplier={self.multiplier}"
            )

    def backoff(self, attempt: int) -> float:
        return self.backoff_s * self.multiplier ** (max(int(attempt), 1) - 1)


def resolve_fault_plan(plan) -> FaultPlan | None:
    """Engine-facing constructor: ``None`` → the ``THUNDER_TPU_FAULT_PLAN``
    env JSON (or no plan), ``False`` → force-off, a :class:`FaultPlan` /
    :class:`FaultSpec` / dict of plan kwargs / list of specs → armed."""
    if plan is None:
        raw = os.getenv("THUNDER_TPU_FAULT_PLAN", "").strip()
        if not raw:
            return None
        plan = json.loads(raw)
    if plan is False:
        return None
    if isinstance(plan, FaultPlan):
        return plan
    if isinstance(plan, FaultSpec):
        return FaultPlan(specs=(plan,))
    if isinstance(plan, dict):
        if "specs" in plan or "seed" in plan or "rate" in plan:
            return FaultPlan(**plan)
        return FaultPlan(specs=(FaultSpec(**plan),))
    if isinstance(plan, (list, tuple)):
        return FaultPlan(specs=tuple(
            s if isinstance(s, FaultSpec) else FaultSpec(**s) for s in plan
        ))
    raise TypeError(
        f"fault_plan= expects None/False/FaultPlan/FaultSpec/dict/list, "
        f"got {type(plan).__name__}"
    )
