"""Paged KV-cache pool: a preallocated block arena + free-list allocator.

vLLM's PagedAttention insight, recast for the XLA static-shape world: instead
of one contiguous per-request cache, all requests share one arena of
fixed-size **blocks** — ``(num_blocks, L, n_query_groups, block_size, hs)``
for K and V each (per-block geometry from
:func:`models.generate.kv_block_shape`, so a gather over a request's block
table reassembles exactly the dense :func:`models.generate.cache_shape`
layout that ``forward_with_cache`` already consumes).  Fragmentation is
bounded to one partial block per request, admission control becomes a free-
block count, and finished/expired requests return their blocks in O(blocks).

Design points:

- **Physical block 0 is a reserved garbage sink.**  Every compiled serving
  program is static-shape: padding rows in a bucketed batch and
  not-yet-reached table slots still need *some* valid physical index to
  read from / write to.  They all point at block 0, whose contents are never
  attended (the positional keep-mask excludes them), so no dynamic shapes
  and no masked scatters are ever needed.
- **Reference counting** enables prefix sharing: two requests with the same
  block-aligned prompt prefix map their leading table entries to the same
  physical blocks (``share``), and a block returns to the free list only
  when its last owner releases it.
- **Quantized block storage** (``kv_dtype="int8"`` or ``"fp8"``): the
  arenas store 1-byte values plus a float32 scale arena at per-block-slot,
  per-head granularity (:mod:`thunder_tpu.serving.quant`) —
  ~``hs*itemsize/(hs+4)``× the resident requests per arena byte, with
  quantize-on-scatter and dequant-on-gather inside the jitted programs.
- **Chunk scatter granularity**: a prefill piece (whole prompt, shared-
  prefix suffix, or one chunk of a chunked prefill) writes only the block
  range its tokens cover — :func:`chunk_tables` builds the sink-padded
  gather/scatter tables for any ``[pos, pos + n)`` token window, so the
  prefill and chunked-prefill lanes share one granularity rule.
- The pool owns only the *allocator* state (host-side, O(num_blocks) ints)
  and the arena arrays.  All array movement (gather/scatter) is pure
  jnp code in :mod:`thunder_tpu.serving.engine`'s jitted bucket programs,
  which donate the arenas so updates stay in place.
- Sliding-window models keep the plain positional layout (slot = position);
  the window shows up as the keep-mask band plus **early block release**:
  once every position in a block has slid out of the window, the scheduler
  frees it and the table entry falls back to the sink block.
"""
from __future__ import annotations

from collections import deque
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from thunder_tpu.models.generate import kv_block_shape
from thunder_tpu.serving.quant import is_quantized_kv, resolve_kv_dtype

__all__ = ["PoolExhaustedError", "ArenaMismatchError", "PagedKVPool",
           "PrefixIndex", "chunk_tables", "dest_for_pos",
           "OCCUPANCY_WINDOW"]

SINK_BLOCK = 0  # reserved physical block for padding/expired table entries

OCCUPANCY_WINDOW = 128  # samples retained in the occupancy timeline ring


class PoolExhaustedError(RuntimeError):
    """Raised by :meth:`PagedKVPool.alloc` when fewer free blocks remain
    than requested.  Admission control catches this to queue the request."""


class ArenaMismatchError(ValueError):
    """An arena (or arena write) does not match the pool's geometry
    (shape/dtype) or placement (sharding).  Caught at the swap/scatter, not
    steps later as garbage KV.

    Attributes: ``arena`` ("k" | "v" | "k_scale" | "v_scale" | "scatter"),
    ``field`` ("shape" | "dtype" | "sharding"), ``expected``, ``got``."""

    def __init__(self, arena: str, field: str, expected, got, *, msg: str | None = None):
        self.arena = arena
        self.field = field
        self.expected = expected
        self.got = got
        super().__init__(
            msg if msg is not None else (
                f"refusing to install {arena}-arena with mismatched {field}: "
                f"program returned {got!r}, pool expects {expected!r} — the "
                f"producing bucket program is writing a different arena "
                f"geometry/placement than this pool owns"
            )
        )


class PagedKVPool:
    """Block arena + free-list allocator + per-block reference counts.

    ``dtype`` is the **compute** dtype the model consumes (what
    ``gather_dense*`` hands ``forward_with_cache``); ``kv_dtype`` selects
    the **storage** dtype — ``None`` stores at ``dtype`` (full-width),
    ``"int8"`` stores quantized blocks plus float32 scale arenas of shape
    ``(num_blocks, L, n_query_groups, block_size)``.

    With ``mesh``, the arenas carry a ``NamedSharding`` splitting the
    KV-heads dim over ``axis`` (the shared ``distributed.kv_cache_spec``
    rule; the scale arenas keep the heads dim at axis 2 too, so ONE rule
    places all four arrays) — the *bytes* live sharded across the mesh
    while every allocator decision (free list, refcounts, prefix sharing)
    stays host-side and identical to the single-device pool."""

    def __init__(self, cfg, num_blocks: int, block_size: int, dtype=jnp.bfloat16,
                 *, kv_dtype=None, mesh=None, axis: str = "tp"):
        if num_blocks < 2:
            raise ValueError(f"num_blocks must be >= 2 (block 0 is the sink), got {num_blocks}")
        if block_size < 1:
            raise ValueError(f"block_size must be >= 1, got {block_size}")
        self.cfg = cfg
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        self.dtype = dtype                              # compute/dequant dtype
        self.kv_dtype = resolve_kv_dtype(kv_dtype, dtype)  # storage dtype
        self.quantized_kv = is_quantized_kv(self.kv_dtype, dtype)
        self.mesh = mesh
        shape = (self.num_blocks, *kv_block_shape(cfg, self.block_size))
        self._arena_shape = shape
        self._scale_shape = shape[:-1]                  # absmax over hs
        if mesh is not None:
            from thunder_tpu.serving.mesh import arena_sharding

            self.arena_sharding = arena_sharding(cfg, mesh, axis=axis)
            # shard-local allocation: no device ever materializes the full
            # arena (the whole point — a model/cache too big for one chip).
            # The spec (heads at axis 2) is a valid prefix for the rank-4
            # scale arenas too, so one sharding object places everything.
        else:
            self.arena_sharding = None

        # independent buffers (no copy traffic between K and V updates)
        self.k_arena = self._zeros(shape, self.kv_dtype)
        self.v_arena = self._zeros(shape, self.kv_dtype)
        if self.quantized_kv:
            self.k_scale = self._zeros(self._scale_shape, jnp.float32)
            self.v_scale = self._zeros(self._scale_shape, jnp.float32)
        else:
            self.k_scale = self.v_scale = None
        # outgoing donated arena handles, parked until their consumer
        # completes (see set_arenas/release_retired)
        self._retired: list = []
        # block 0 is permanently leased to the sink
        self._refcount = np.zeros(self.num_blocks, dtype=np.int32)
        self._refcount[SINK_BLOCK] = 1
        self._free: list[int] = list(range(self.num_blocks - 1, SINK_BLOCK, -1))  # pop() -> lowest id
        # capacity-exhaustion post-mortems need the floor, not the current
        # value: the low-water mark survives into the flight-recorder dump
        self._free_low_water = len(self._free)
        # occupancy timeline: bounded ring of (free, shared, leased) triples
        # sampled at each harvest — the low-water mark alone hides spikes
        self._occ_ring: deque = deque(maxlen=OCCUPANCY_WINDOW)

    #
    # allocator
    #

    @property
    def num_free(self) -> int:
        return len(self._free)

    @property
    def num_usable(self) -> int:
        """Allocatable blocks (arena minus the sink)."""
        return self.num_blocks - 1

    @property
    def free_blocks_low_water(self) -> int:
        """Fewest free blocks ever observed (capacity headroom floor)."""
        return self._free_low_water

    def utilization(self) -> float:
        """Fraction of usable blocks currently leased."""
        return 1.0 - self.num_free / self.num_usable

    def blocks_for_tokens(self, n_tokens: int) -> int:
        """Blocks needed to hold ``n_tokens`` cache slots."""
        return -(-max(int(n_tokens), 0) // self.block_size)

    def can_alloc(self, n: int) -> bool:
        return n <= self.num_free

    def alloc(self, n: int) -> list[int]:
        """Leases ``n`` blocks (refcount 1 each); raises
        :class:`PoolExhaustedError` without side effects when short."""
        if n > self.num_free:
            raise PoolExhaustedError(
                f"need {n} blocks, {self.num_free} free of {self.num_usable}"
            )
        out = [self._free.pop() for _ in range(n)]
        for b in out:
            self._refcount[b] = 1
        self._free_low_water = min(self._free_low_water, len(self._free))
        return out

    def share(self, blocks: Sequence[int]) -> list[int]:
        """Increments the refcount of already-leased ``blocks`` (prefix
        sharing: the new owner's table points at the same physical blocks).
        Returns the same ids for convenience."""
        for b in blocks:
            if b == SINK_BLOCK:
                continue
            if self._refcount[b] <= 0:
                raise ValueError(f"block {b} is not leased; cannot share")
            self._refcount[b] += 1
        return list(blocks)

    def free(self, blocks: Sequence[int]) -> int:
        """Releases one reference on each block; blocks whose count reaches
        zero return to the free list.  Returns how many became free."""
        released = 0
        for b in blocks:
            if b == SINK_BLOCK:
                continue
            if self._refcount[b] <= 0:
                raise ValueError(f"double free of block {b}")
            self._refcount[b] -= 1
            if self._refcount[b] == 0:
                self._free.append(b)
                released += 1
        return released

    def refcount(self, block: int) -> int:
        return int(self._refcount[block])

    def sample_occupancy(self) -> tuple[int, int, int]:
        """Append one ``(free, shared, leased)`` sample to the bounded
        occupancy ring (the engine calls this once per harvest) and
        return it.  O(num_blocks) numpy scan; ring stays O(1) memory."""
        counts = self._refcount[SINK_BLOCK + 1:]
        sample = (self.num_free, int((counts > 1).sum()),
                  int((counts > 0).sum()))
        self._occ_ring.append(sample)
        return sample

    def occupancy_timeline(self) -> list[tuple[int, int, int]]:
        """The retained ``(free, shared, leased)`` samples, oldest first
        (at most :data:`OCCUPANCY_WINDOW` — spikes between crashes stay
        visible, unlike the low-water scalar alone)."""
        return list(self._occ_ring)

    def occupancy_snapshot(self) -> dict:
        """Summary of the timeline for ``stats()``: sample count, window,
        the latest triple, and the peak leased-block count observed."""
        tl = self._occ_ring
        return {
            "window": OCCUPANCY_WINDOW,
            "samples": len(tl),
            "last": tl[-1] if tl else None,
            "peak_leased": max((s[2] for s in tl), default=0),
            "occupancy_frac": self.utilization(),
        }

    def state_snapshot(self) -> dict:
        """Allocator state for the flight recorder: occupancy plus the
        free-list/sharing breakdown (the paged-pool notion of
        fragmentation is how lease references spread over blocks)."""
        counts = self._refcount[SINK_BLOCK + 1:]
        snap = {
            "num_blocks": self.num_blocks,
            "block_size": self.block_size,
            "num_free": self.num_free,
            "free_blocks_low_water": self._free_low_water,
            "utilization": self.utilization(),
            "leased_blocks": int((counts > 0).sum()),
            "shared_blocks": int((counts > 1).sum()),
            "lease_refs": int(counts.sum()),
            "kv_dtype": str(self.kv_dtype),
            "arena_bytes": self.arena_bytes(),
            "occupancy_timeline": [list(s) for s in self._occ_ring],
        }
        if self.arena_sharding is not None:
            snap["arena_spec"] = str(self.arena_sharding.spec)
            snap["arena_shard_bytes"] = self.per_shard_bytes()
        return snap

    #
    # arena geometry helpers (pure; the jitted programs in engine.py close
    # over these shapes)
    #

    def capacity_tokens(self, n_blocks: int) -> int:
        return n_blocks * self.block_size

    def dense_shape(self, B: int, n_blocks: int) -> tuple[int, ...]:
        L, ng, bs, hs = kv_block_shape(self.cfg, self.block_size)
        return (L, B, ng, n_blocks * bs, hs)

    def block_bytes(self) -> int:
        """Bytes one block costs across all arenas (K+V data, plus the
        scale arenas on the quantized path) — the unit of byte-based
        admission/capacity accounting."""
        total = int(self.k_arena.nbytes) + int(self.v_arena.nbytes)
        if self.quantized_kv:
            total += int(self.k_scale.nbytes) + int(self.v_scale.nbytes)
        return total // self.num_blocks

    def arena_bytes(self) -> int:
        """Total bytes of every arena array this pool owns."""
        return self.block_bytes() * self.num_blocks

    def per_shard_bytes(self) -> int:
        """Bytes of ONE K arena on one device (what a chip's HBM must
        hold; ×2 for K+V).  Equals ``k_arena.nbytes`` unsharded."""
        from thunder_tpu.serving.mesh import per_shard_bytes

        return per_shard_bytes(self.k_arena)

    @property
    def arenas(self) -> dict:
        """The arena pytree a bucket program takes (and returns donated):
        ``{"k", "v"}`` plus ``{"k_scale", "v_scale"}`` on the int8 path."""
        out = {"k": self.k_arena, "v": self.v_arena}
        if self.quantized_kv:
            out["k_scale"] = self.k_scale
            out["v_scale"] = self.v_scale
        return out

    def _check_arena(self, name: str, new: jax.Array) -> None:
        scale = name.endswith("_scale")
        want_shape = self._scale_shape if scale else self._arena_shape
        want_dtype = jnp.dtype(jnp.float32) if scale else jnp.dtype(self.kv_dtype)
        if tuple(new.shape) != want_shape:
            raise ArenaMismatchError(name, "shape", want_shape, tuple(new.shape))
        if new.dtype != want_dtype:
            raise ArenaMismatchError(name, "dtype", want_dtype, new.dtype)
        if self.arena_sharding is not None:
            got = getattr(new, "sharding", None)
            ok = got is not None and (
                got == self.arena_sharding
                or self.arena_sharding.is_equivalent_to(got, new.ndim)
            )
            if not ok:
                raise ArenaMismatchError(name, "sharding", self.arena_sharding, got)

    def set_arenas(self, arenas: dict) -> None:
        """Installs the arena pytree a donated program returned (in-place
        update).  Validates geometry, dtype, and (mesh mode) sharding
        first: a buggy program's mismatched arena would otherwise surface
        steps later as garbage KV — :class:`ArenaMismatchError` names the
        offending arena at the swap instead."""
        expected = set(self.arenas)
        if set(arenas) != expected:
            raise ArenaMismatchError(
                "arenas", "shape", sorted(expected), sorted(arenas),
                msg=f"program returned arena keys {sorted(arenas)}, pool "
                    f"expects {sorted(expected)} (kv_dtype={self.kv_dtype})",
            )
        for name, arr in arenas.items():
            self._check_arena(name, arr)
        # park the outgoing handles instead of letting them die here:
        # dropping the LAST reference to a jax Array that was DONATED to a
        # still-running execution blocks the host until that execution
        # completes — measured ~the full device step, i.e. it silently
        # serializes the async engine's overlap.  The engine calls
        # release_retired() at harvest, when the consumer has finished and
        # the deref costs microseconds.
        self._retired.append((self.k_arena, self.v_arena,
                              self.k_scale, self.v_scale))
        self.k_arena = arenas["k"]
        self.v_arena = arenas["v"]
        if self.quantized_kv:
            self.k_scale = arenas["k_scale"]
            self.v_scale = arenas["v_scale"]

    def release_retired(self) -> None:
        """Drops the parked donated-arena handles (cheap once their
        consuming executions have completed — call after materializing any
        later output of the same device stream)."""
        self._retired.clear()

    def _zeros(self, shp: tuple, dt) -> jax.Array:
        """A zeroed arena buffer, shard-local under a mesh (no device ever
        materializes the full arena)."""
        if self.mesh is not None:
            return jax.jit(
                lambda: jnp.zeros(shp, dtype=dt), out_shardings=self.arena_sharding
            )()
        return jnp.zeros(shp, dtype=dt)

    def rebuild_arenas(self) -> None:
        """Replaces the device arenas with fresh zeroed buffers, dropping
        whatever the old handles held (re-prefill recovery: the KV content
        is soft state the engine rebuilds by replaying known tokens).
        Allocator state — block tables, refcounts, prefix sharing, the
        free list — is host-side and survives untouched; under a mesh the
        new buffers come up with the same shard-local placement."""
        self._retired.clear()
        self.k_arena = self._zeros(self._arena_shape, self.kv_dtype)
        self.v_arena = self._zeros(self._arena_shape, self.kv_dtype)
        if self.quantized_kv:
            self.k_scale = self._zeros(self._scale_shape, jnp.float32)
            self.v_scale = self._zeros(self._scale_shape, jnp.float32)

    def update_arenas(self, k_arena: jax.Array, v_arena: jax.Array,
                      k_scale: jax.Array | None = None,
                      v_scale: jax.Array | None = None) -> None:
        """Positional convenience over :meth:`set_arenas` (kept for the
        pre-quantization call sites and tests)."""
        arenas = {"k": k_arena, "v": v_arena}
        if k_scale is not None or v_scale is not None:
            arenas["k_scale"] = k_scale
            arenas["v_scale"] = v_scale
        self.set_arenas(arenas)


class PrefixIndex:
    """Block-aligned prompt-prefix → ``(owner rid, block ids)`` map — the
    prefix-sharing lookup structure one engine (one pool) owns.

    Liveness is delegated: every query takes an ``alive(hit) -> bool``
    callback (the engine checks that the owner is still running and every
    snapshot block id is still the live table entry), so the index itself
    stays a pure pool-side structure with no scheduler dependency — which
    is what lets the dp router read it from outside the engine.

    Two lookup flavors with different side-effect contracts:

    - :meth:`find` — the engine's admission-path lookup: counts into
      ``lookups``/``hits`` and scrubs stale entries as it walks (sharing a
      stale snapshot would lease dead block ids);
    - :meth:`probe` — the router's affinity query: **non-mutating** (no
      counter bumps, no scrubbing), because a routing decision must not
      perturb the engine's prefix-share hit-rate accounting or race its
      scrub with an admission happening on the same step.
    """

    def __init__(self, block_size: int):
        self.block_size = int(block_size)
        self._index: dict[tuple, tuple[int, tuple[int, ...]]] = {}
        self.lookups = 0
        self.hits = 0

    def __len__(self) -> int:
        return len(self._index)

    def find(self, prompt: np.ndarray, alive) -> list[int]:
        """Longest block-aligned prefix of ``prompt`` with a live owner:
        the shared block ids (the last prompt token always re-prefills, so
        the share is capped one token short of the full prompt), or ``[]``.
        Counts the lookup and deletes stale entries encountered."""
        self.lookups += 1
        bs = self.block_size
        max_share = ((int(prompt.shape[0]) - 1) // bs) * bs
        for k in range(max_share, 0, -bs):
            key = tuple(prompt[:k].tolist())
            hit = self._index.get(key)
            if hit is None:
                continue
            if alive(hit):
                self.hits += 1
                return list(hit[1])
            # stale snapshot (the owner's blocks were freed or sunk, e.g. by
            # sliding-window expiry): sharing it would lease dead block ids
            del self._index[key]
        return []

    def probe(self, prompt, alive) -> int:
        """Longest *alive* shared-prefix length in tokens (0 on miss),
        without touching counters or scrubbing — the router's read-only
        affinity question: "how much of this prompt is already resident
        here?"."""
        prompt = np.asarray(prompt).reshape(-1)
        bs = self.block_size
        max_share = ((int(prompt.shape[0]) - 1) // bs) * bs
        for k in range(max_share, 0, -bs):
            hit = self._index.get(tuple(prompt[:k].tolist()))
            if hit is not None and alive(hit):
                return k
        return 0

    def register(self, rid: int, prompt: np.ndarray, block_table,
                 alive, *, upto: int | None = None, full: bool = False) -> None:
        """Registers every block-aligned prefix of ``prompt`` (owner
        ``rid``).  ``upto`` bounds registration to tokens already written
        (a chunked prefill registers after each piece); live entries are
        never displaced — first writer wins while it stays alive.

        ``full=True`` lifts the one-token-short cap: a *running* request
        must keep its last prompt token for its own re-prefill, but a
        parked session sequence is complete and fully written, so every
        covered block is shareable (turn k+1's prompt is strictly longer,
        which is what the ``find`` cap already guarantees per-query)."""
        bs = self.block_size
        n = int(prompt.shape[0])
        limit = n if upto is None else min(upto, n)
        hi = (limit // bs) * bs if full else min(
            (limit // bs) * bs, ((n - 1) // bs) * bs)
        toks = prompt.tolist()
        for k in range(bs, hi + 1, bs):
            key = tuple(toks[:k])
            cur = self._index.get(key)
            if cur is None or not alive(cur):
                self._index[key] = (rid, tuple(block_table[: k // bs]))

    def unregister(self, rid: int) -> None:
        """Drops every entry owned by ``rid`` (called before its blocks
        free, so no later request can share just-released ids)."""
        if self._index:
            stale = [k for k, (r, _) in self._index.items() if r == rid]
            for k in stale:
                del self._index[k]


def chunk_tables(block_table, pos: int, n_tokens: int, nbb: int,
                 block_size: int) -> tuple[np.ndarray, np.ndarray]:
    """Host-side gather/scatter tables for one prefill piece at **chunk
    granularity**.

    A prefill piece (a whole prompt, a shared-prefix suffix, or one chunk
    of a chunked prefill) computes K/V for the ``n_tokens`` positions at
    ``[pos, pos + n_tokens)`` of a request holding ``block_table``.
    Returns ``(table, dest)`` int32 arrays of width ``nbb`` (the padded
    program table width):

    - ``table`` — the gather side: every leased block, sink-padded to
      ``nbb``, so the dense window the program reassembles covers the
      already-written prefix (earlier chunks / shared blocks);
    - ``dest`` — the scatter side: only the block range
      ``[pos // bs, ceil((pos + n_tokens) / bs))`` this piece writes;
      every other entry (shared prefix, earlier chunks, bucket padding
      beyond the leased table) routes to the sink block, so a piece never
      writes blocks another piece owns the write for.  ``n_tokens`` may be
      the *padded* bucket width: trailing padding that spills into leased
      future-decode blocks writes garbage that decode overwrites slot by
      slot before ever attending it (the same invariant padding always
      relied on).
    """
    bs = block_size
    table = np.full(nbb, SINK_BLOCK, dtype=np.int32)
    table[: len(block_table)] = block_table
    dest = np.full(nbb, SINK_BLOCK, dtype=np.int32)
    lo, hi = pos // bs, min(len(block_table), -(-(pos + n_tokens) // bs))
    dest[lo:hi] = block_table[lo:hi]
    return table, dest


def gather_dense(k_arena, v_arena, tables):
    """Reassembles dense caches from block tables.

    ``tables``: (B, nb) int32 physical-block ids (sink-padded).  Returns
    ``k, v`` of shape (L, B, ng, nb*bs, hs) — the :func:`cache_shape` layout
    ``forward_with_cache`` consumes.  Pure jnp; call inside jit."""
    def one(arena):
        g = jnp.take(arena, tables, axis=0)        # (B, nb, L, ng, bs, hs)
        g = g.transpose(2, 0, 3, 1, 4, 5)          # (L, B, ng, nb, bs, hs)
        L, B, ng, nb, bs, hs = g.shape
        return g.reshape(L, B, ng, nb * bs, hs)

    return one(k_arena), one(v_arena)


def dest_for_pos(tables, pos, live, *, block_size):
    """In-program scatter destination for a token write at ``pos``, with a
    per-row liveness keep-mask.

    ``tables``: (B, nb) int32 (sink-padded); ``pos``/``live``: (B,).  Live
    rows advance through their own table as ``pos`` crosses block
    boundaries (``tables[b, pos // bs]``, the in-program table walk the
    multi-step decode scan relies on — the full table is leased at
    admission, so every entry the walk can reach is owned); dead rows route
    to ``(SINK_BLOCK, 0)`` so a finished request's remaining scan
    iterations write only garbage the sink absorbs.  Pure jnp; call inside
    jit.  ``take_along_axis`` clamps an out-of-range block index to the
    row's last (sink-padded) entry, matching the single-step derivation."""
    blk = jnp.take_along_axis(tables, (pos // block_size)[:, None], axis=1)[:, 0]
    return (jnp.where(live, blk, SINK_BLOCK),
            jnp.where(live, pos % block_size, 0))


def scatter_token(arena, new_kv, dest_block, dest_slot):
    """Writes one token's K (or V) per batch row back into the arena.

    ``new_kv``: (B, L, ng, hs); ``dest_block``/``dest_slot``: (B,) int32
    (sink-routed for padding rows).  Pure jnp; call inside jit on a donated
    arena.  The source dtype must already match the arena (int8 arenas go
    through :func:`quant.scatter_token_q` instead)."""
    if jnp.dtype(new_kv.dtype) != jnp.dtype(arena.dtype):
        raise ArenaMismatchError(
            "scatter", "dtype", jnp.dtype(arena.dtype), jnp.dtype(new_kv.dtype),
            msg=f"scatter_token source dtype {jnp.dtype(new_kv.dtype)} != arena "
                f"dtype {jnp.dtype(arena.dtype)} — route int8 arenas through "
                f"quant.scatter_token_q; anything else is a silent truncation",
        )
    return arena.at[dest_block, :, :, dest_slot, :].set(new_kv)


def scatter_blocks(arena, dense, dest_table):
    """Writes a request's dense cache back into the arena block-by-block.

    ``dense``: (L, 1, ng, nb*bs, hs) (B=1 prefill layout); ``dest_table``:
    (nb,) int32 — entries equal to the sink absorb padding/garbage blocks.
    Duplicate sink entries are benign (last write wins into garbage).

    The source dtype must match the arena exactly: the pre-quantization
    code silently ``astype``'d here, which would truncate an f32 cache into
    a narrower arena without a trace — now any mismatch raises
    :class:`ArenaMismatchError` at trace time, and int8 arenas route
    through the explicit quantize path (:func:`quant.scatter_blocks_q`)."""
    if jnp.dtype(dense.dtype) != jnp.dtype(arena.dtype):
        raise ArenaMismatchError(
            "scatter", "dtype", jnp.dtype(arena.dtype), jnp.dtype(dense.dtype),
            msg=f"scatter_blocks source dtype {jnp.dtype(dense.dtype)} != arena "
                f"dtype {jnp.dtype(arena.dtype)} — route int8 arenas through "
                f"quant.scatter_blocks_q; anything else is a silent truncation",
        )
    L, B, ng, cap, hs = dense.shape
    bs = arena.shape[3]
    blocks = dense[:, 0].reshape(L, ng, cap // bs, bs, hs).transpose(2, 0, 1, 3, 4)
    return arena.at[dest_table].set(blocks)
