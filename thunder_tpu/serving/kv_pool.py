"""Paged KV-cache pool: a preallocated block arena + free-list allocator.

vLLM's PagedAttention insight, recast for the XLA static-shape world: instead
of one contiguous per-request cache, all requests share one arena of
fixed-size **blocks** — ``(num_blocks, L, n_query_groups, block_size, hs)``
for K and V each (per-block geometry from
:func:`models.generate.kv_block_shape`, so a gather over a request's block
table reassembles exactly the dense :func:`models.generate.cache_shape`
layout that ``forward_with_cache`` already consumes).  Fragmentation is
bounded to one partial block per request, admission control becomes a free-
block count, and finished/expired requests return their blocks in O(blocks).

Design points:

- **Physical block 0 is a reserved garbage sink.**  Every compiled serving
  program is static-shape: padding rows in a bucketed batch and
  not-yet-reached table slots still need *some* valid physical index to
  read from / write to.  They all point at block 0, whose contents are never
  attended (the positional keep-mask excludes them), so no dynamic shapes
  and no masked scatters are ever needed.
- **Reference counting** enables prefix sharing: two requests with the same
  block-aligned prompt prefix map their leading table entries to the same
  physical blocks (``share``), and a block returns to the free list only
  when its last owner releases it.
- The pool owns only the *allocator* state (host-side, O(num_blocks) ints)
  and the two arena arrays.  All array movement (gather/scatter) is pure
  jnp code in :mod:`thunder_tpu.serving.engine`'s jitted bucket programs,
  which donate the arenas so updates stay in place.
- Sliding-window models keep the plain positional layout (slot = position);
  the window shows up as the keep-mask band plus **early block release**:
  once every position in a block has slid out of the window, the scheduler
  frees it and the table entry falls back to the sink block.
"""
from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from thunder_tpu.models.generate import kv_block_shape

__all__ = ["PoolExhaustedError", "ArenaMismatchError", "PagedKVPool"]

SINK_BLOCK = 0  # reserved physical block for padding/expired table entries


class PoolExhaustedError(RuntimeError):
    """Raised by :meth:`PagedKVPool.alloc` when fewer free blocks remain
    than requested.  Admission control catches this to queue the request."""


class ArenaMismatchError(ValueError):
    """A program handed :meth:`PagedKVPool.update_arenas` an arena that
    does not match the pool's geometry (shape/dtype) or placement
    (sharding).  Caught at the swap, not steps later as garbage KV.

    Attributes: ``arena`` ("k" | "v"), ``field`` ("shape" | "dtype" |
    "sharding"), ``expected``, ``got``."""

    def __init__(self, arena: str, field: str, expected, got):
        self.arena = arena
        self.field = field
        self.expected = expected
        self.got = got
        super().__init__(
            f"refusing to install {arena}-arena with mismatched {field}: "
            f"program returned {got!r}, pool expects {expected!r} — the "
            f"producing bucket program is writing a different arena "
            f"geometry/placement than this pool owns"
        )


class PagedKVPool:
    """Block arena + free-list allocator + per-block reference counts.

    With ``mesh``, the arenas carry a ``NamedSharding`` splitting the
    KV-heads dim over ``axis`` (the shared ``distributed.kv_cache_spec``
    rule) — the *bytes* live sharded across the mesh while every allocator
    decision (free list, refcounts, prefix sharing) stays host-side and
    identical to the single-device pool."""

    def __init__(self, cfg, num_blocks: int, block_size: int, dtype=jnp.bfloat16,
                 *, mesh=None, axis: str = "tp"):
        if num_blocks < 2:
            raise ValueError(f"num_blocks must be >= 2 (block 0 is the sink), got {num_blocks}")
        if block_size < 1:
            raise ValueError(f"block_size must be >= 1, got {block_size}")
        self.cfg = cfg
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        self.dtype = dtype
        self.mesh = mesh
        shape = (self.num_blocks, *kv_block_shape(cfg, self.block_size))
        self._arena_shape = shape
        if mesh is not None:
            from thunder_tpu.serving.mesh import arena_sharding

            self.arena_sharding = arena_sharding(cfg, mesh, axis=axis)
            # shard-local allocation: no device ever materializes the full
            # arena (the whole point — a model/cache too big for one chip)
            zeros = jax.jit(
                lambda: jnp.zeros(shape, dtype=dtype), out_shardings=self.arena_sharding
            )
            self.k_arena = zeros()
            self.v_arena = zeros()
        else:
            self.arena_sharding = None
            # two independent buffers (no copy traffic between K and V updates)
            self.k_arena = jnp.zeros(shape, dtype=dtype)
            self.v_arena = jnp.zeros(shape, dtype=dtype)
        # block 0 is permanently leased to the sink
        self._refcount = np.zeros(self.num_blocks, dtype=np.int32)
        self._refcount[SINK_BLOCK] = 1
        self._free: list[int] = list(range(self.num_blocks - 1, SINK_BLOCK, -1))  # pop() -> lowest id

    #
    # allocator
    #

    @property
    def num_free(self) -> int:
        return len(self._free)

    @property
    def num_usable(self) -> int:
        """Allocatable blocks (arena minus the sink)."""
        return self.num_blocks - 1

    def utilization(self) -> float:
        """Fraction of usable blocks currently leased."""
        return 1.0 - self.num_free / self.num_usable

    def blocks_for_tokens(self, n_tokens: int) -> int:
        """Blocks needed to hold ``n_tokens`` cache slots."""
        return -(-max(int(n_tokens), 0) // self.block_size)

    def can_alloc(self, n: int) -> bool:
        return n <= self.num_free

    def alloc(self, n: int) -> list[int]:
        """Leases ``n`` blocks (refcount 1 each); raises
        :class:`PoolExhaustedError` without side effects when short."""
        if n > self.num_free:
            raise PoolExhaustedError(
                f"need {n} blocks, {self.num_free} free of {self.num_usable}"
            )
        out = [self._free.pop() for _ in range(n)]
        for b in out:
            self._refcount[b] = 1
        return out

    def share(self, blocks: Sequence[int]) -> list[int]:
        """Increments the refcount of already-leased ``blocks`` (prefix
        sharing: the new owner's table points at the same physical blocks).
        Returns the same ids for convenience."""
        for b in blocks:
            if b == SINK_BLOCK:
                continue
            if self._refcount[b] <= 0:
                raise ValueError(f"block {b} is not leased; cannot share")
            self._refcount[b] += 1
        return list(blocks)

    def free(self, blocks: Sequence[int]) -> int:
        """Releases one reference on each block; blocks whose count reaches
        zero return to the free list.  Returns how many became free."""
        released = 0
        for b in blocks:
            if b == SINK_BLOCK:
                continue
            if self._refcount[b] <= 0:
                raise ValueError(f"double free of block {b}")
            self._refcount[b] -= 1
            if self._refcount[b] == 0:
                self._free.append(b)
                released += 1
        return released

    def refcount(self, block: int) -> int:
        return int(self._refcount[block])

    def state_snapshot(self) -> dict:
        """Allocator state for the flight recorder: occupancy plus the
        free-list/sharing breakdown (the paged-pool notion of
        fragmentation is how lease references spread over blocks)."""
        counts = self._refcount[SINK_BLOCK + 1:]
        snap = {
            "num_blocks": self.num_blocks,
            "block_size": self.block_size,
            "num_free": self.num_free,
            "utilization": self.utilization(),
            "leased_blocks": int((counts > 0).sum()),
            "shared_blocks": int((counts > 1).sum()),
            "lease_refs": int(counts.sum()),
        }
        if self.arena_sharding is not None:
            snap["arena_spec"] = str(self.arena_sharding.spec)
            snap["arena_shard_bytes"] = self.per_shard_bytes()
        return snap

    #
    # arena geometry helpers (pure; the jitted programs in engine.py close
    # over these shapes)
    #

    def capacity_tokens(self, n_blocks: int) -> int:
        return n_blocks * self.block_size

    def dense_shape(self, B: int, n_blocks: int) -> tuple[int, ...]:
        L, ng, bs, hs = kv_block_shape(self.cfg, self.block_size)
        return (L, B, ng, n_blocks * bs, hs)

    def per_shard_bytes(self) -> int:
        """Bytes of ONE K arena on one device (what a chip's HBM must
        hold; ×2 for K+V).  Equals ``k_arena.nbytes`` unsharded."""
        from thunder_tpu.serving.mesh import per_shard_bytes

        return per_shard_bytes(self.k_arena)

    def _check_arena(self, name: str, new: jax.Array) -> None:
        if tuple(new.shape) != self._arena_shape:
            raise ArenaMismatchError(name, "shape", self._arena_shape, tuple(new.shape))
        if new.dtype != jnp.dtype(self.dtype):
            raise ArenaMismatchError(name, "dtype", jnp.dtype(self.dtype), new.dtype)
        if self.arena_sharding is not None:
            got = getattr(new, "sharding", None)
            ok = got is not None and (
                got == self.arena_sharding
                or self.arena_sharding.is_equivalent_to(got, new.ndim)
            )
            if not ok:
                raise ArenaMismatchError(name, "sharding", self.arena_sharding, got)

    def update_arenas(self, k_arena: jax.Array, v_arena: jax.Array) -> None:
        """Installs the arenas a donated program returned (in-place update).

        Validates geometry, dtype, and (mesh mode) sharding first: a buggy
        program's mismatched arena would otherwise surface steps later as
        garbage KV — :class:`ArenaMismatchError` names the offending arena
        at the swap instead."""
        self._check_arena("k", k_arena)
        self._check_arena("v", v_arena)
        self.k_arena = k_arena
        self.v_arena = v_arena


def gather_dense(k_arena, v_arena, tables):
    """Reassembles dense caches from block tables.

    ``tables``: (B, nb) int32 physical-block ids (sink-padded).  Returns
    ``k, v`` of shape (L, B, ng, nb*bs, hs) — the :func:`cache_shape` layout
    ``forward_with_cache`` consumes.  Pure jnp; call inside jit."""
    def one(arena):
        g = jnp.take(arena, tables, axis=0)        # (B, nb, L, ng, bs, hs)
        g = g.transpose(2, 0, 3, 1, 4, 5)          # (L, B, ng, nb, bs, hs)
        L, B, ng, nb, bs, hs = g.shape
        return g.reshape(L, B, ng, nb * bs, hs)

    return one(k_arena), one(v_arena)


def scatter_token(arena, new_kv, dest_block, dest_slot):
    """Writes one token's K (or V) per batch row back into the arena.

    ``new_kv``: (B, L, ng, hs); ``dest_block``/``dest_slot``: (B,) int32
    (sink-routed for padding rows).  Pure jnp; call inside jit on a donated
    arena."""
    return arena.at[dest_block, :, :, dest_slot, :].set(new_kv)


def scatter_blocks(arena, dense, dest_table):
    """Writes a request's dense cache back into the arena block-by-block.

    ``dense``: (L, 1, ng, nb*bs, hs) (B=1 prefill layout); ``dest_table``:
    (nb,) int32 — entries equal to the sink absorb padding/garbage blocks.
    Duplicate sink entries are benign (last write wins into garbage)."""
    L, B, ng, cap, hs = dense.shape
    bs = arena.shape[3]
    blocks = dense[:, 0].reshape(L, ng, cap // bs, bs, hs).transpose(2, 0, 1, 3, 4)
    return arena.at[dest_table].set(blocks.astype(arena.dtype))
