"""Quantized (int8 / fp8-e4m3) KV block storage for the paged serving pool.

Pool capacity is the admission-control bottleneck of the serving subsystem,
and capacity is bytes: every block stored at full ``dtype`` width caps how
many requests can be resident at once.  This module stores the K/V arenas
in a **1-byte storage dtype** — ``int8`` (symmetric absmax) or
``float8_e4m3fn`` (absmax-scaled to the e4m3 dynamic range; ``fp8``) — with
a float32 scale arena at **per-block-slot, per-head** granularity — one
symmetric absmax scale for each ``(block, layer, kv_group, slot)``
coordinate, i.e. an absmax over the ``head_size`` values of one token's K
(or V) for one head:

- ``quantize_kv``: symmetric absmax int8 over the last (``hs``) dim —
  deterministic per token, so a request's stored KV never depends on what
  else shares the batch (the serving bit-exactness contract survives);
- ``scatter_token_q`` / ``scatter_blocks_q``: quantize-on-scatter — the
  exact K/V computed by the step is quantized once at write time (decode
  picks the *freshly computed* values, never a dequantized round trip, so
  there is no requantization drift across steps);
- ``gather_dense_q``: dequant-on-gather back into the dense
  :func:`models.generate.cache_shape` layout ``forward_with_cache``
  consumes, in the pool's compute dtype.

Capacity math: a stored slot-head costs ``hs`` bytes (int8 or fp8) plus 4
bytes of scale instead of ``hs * itemsize`` — ``hs*4 / (hs+4)`` more blocks
per arena byte vs a float32 pool (3.2x at ``hs=16``, 3.76x at ``hs=64``;
``bench.py capacity`` gates the measured admitted-concurrency win).  int8
and fp8 cost identical bytes; they differ only in error shape.

Error model: absmax int8 keeps ~2 decimal digits; expect ~1e-2 relative
error on the stored KV (the ``serving.kv_quant.rel_err`` gauge reports the
measured value per prefill).  fp8 e4m3 has 3 mantissa bits (~3e-2 relative
per element) but a sign-magnitude float grid, so small-magnitude values
keep relative precision where int8's uniform grid loses them.  Greedy
tokens match the full-precision cache whenever logit margins exceed that
noise — the tiny-llama greedy differential tests assert exact argmax-token
parity for both storage dtypes.

In mesh mode the scale arenas shard by the same
``distributed.kv_cache_spec`` rule as the data arenas (heads dim at axis 2
in both layouts), so no new placement rule is introduced.
"""
from __future__ import annotations

import jax.numpy as jnp

from thunder_tpu.models.generate import kv_block_shape

__all__ = [
    "resolve_kv_dtype",
    "is_quantized_kv",
    "quantize_kv",
    "dequantize_kv",
    "gather_dense_q",
    "scatter_token_q",
    "scatter_blocks_q",
    "arena_block_bytes",
    "blocks_for_arena_bytes",
]

_SINK = 0  # kv_pool.SINK_BLOCK (not imported: kv_pool imports this module)

# fp8 storage is gated on the jax build actually shipping the dtype (the
# ml_dtypes extended-float set); older builds fall back to a clear error
_FP8_DTYPE = getattr(jnp, "float8_e4m3fn", None)
_FP8_ALIASES = ("fp8", "e4m3", "float8_e4m3fn")


def _qmax(storage) -> float:
    """Largest representable magnitude of a quantized storage dtype — the
    absmax scale divisor (127 for int8, 448 for fp8 e4m3)."""
    storage = jnp.dtype(storage)
    if storage == jnp.dtype(jnp.int8):
        return 127.0
    return float(jnp.finfo(storage).max)          # 448.0 for e4m3fn


def resolve_kv_dtype(kv_dtype, dtype):
    """Storage dtype of the block arenas: ``None`` keeps today's behavior
    (store at the compute ``dtype``); ``"int8"``/``jnp.int8`` selects the
    int8 quantized path; ``"fp8"``/``"e4m3"``/``jnp.float8_e4m3fn`` the
    fp8 one.  Any other storage dtype is rejected — silent float truncation
    is exactly what this module replaces."""
    if kv_dtype is None:
        return jnp.dtype(dtype)
    if isinstance(kv_dtype, str) and kv_dtype.lower() in _FP8_ALIASES:
        if _FP8_DTYPE is None:
            raise ValueError(
                "kv_dtype='fp8' requires a jax build with float8_e4m3fn "
                "(jax.numpy.float8_e4m3fn is missing here)"
            )
        return jnp.dtype(_FP8_DTYPE)
    kd = jnp.dtype(kv_dtype)
    if kd == jnp.dtype(jnp.int8):
        return kd
    if _FP8_DTYPE is not None and kd == jnp.dtype(_FP8_DTYPE):
        return kd
    if kd == jnp.dtype(dtype):
        return kd
    raise ValueError(
        f"unsupported kv_dtype {kv_dtype!r}: use None (store at the compute "
        f"dtype {jnp.dtype(dtype)}), 'int8', or 'fp8' (quantized block "
        f"storage)"
    )


def is_quantized_kv(kv_dtype, dtype) -> bool:
    """Whether a resolved storage dtype takes the quantize/scale-arena path
    (1-byte storage that is NOT the compute dtype itself)."""
    kd = jnp.dtype(kv_dtype)
    if kd == jnp.dtype(dtype):
        return False
    if kd == jnp.dtype(jnp.int8):
        return True
    return _FP8_DTYPE is not None and kd == jnp.dtype(_FP8_DTYPE)


def quantize_kv(x, storage=jnp.int8):
    """Symmetric absmax quantization over the last (``hs``) dim into
    ``storage`` (int8: round-and-clip to ±127; fp8 e4m3: scale the absmax
    onto ±448 and let the cast round).

    Returns ``(q, scale)`` with ``q`` in ``storage`` shaped like ``x`` and
    ``scale`` float32 shaped ``x.shape[:-1]``.  All-zero rows get scale 1.0
    (exact).  Deterministic per token either way, so a request's stored KV
    never depends on batch composition.  Pure jnp; call inside jit."""
    storage = jnp.dtype(storage)
    qmax = _qmax(storage)
    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=-1)
    scale = jnp.where(amax == 0.0, 1.0, amax / qmax)
    if storage == jnp.dtype(jnp.int8):
        q = jnp.clip(jnp.round(xf / scale[..., None]), -qmax, qmax).astype(storage)
    else:
        # the scaled max lands exactly on ±qmax (representable in e4m3);
        # the cast rounds everything else to the nearest fp8 grid point
        q = (xf / scale[..., None]).astype(storage)
    return q, scale


def dequantize_kv(q, scale, dtype=jnp.float32):
    """Inverse of :func:`quantize_kv` (up to rounding)."""
    return (q.astype(jnp.float32) * scale[..., None]).astype(dtype)


def gather_dense_q(k_arena, v_arena, k_scale, v_scale, tables, dtype):
    """Quantized twin of :func:`kv_pool.gather_dense`: reassembles dense
    caches from quantized (int8 or fp8) block arenas, dequantizing into
    ``dtype``.

    ``tables``: (B, nb) int32 physical-block ids (sink-padded).  Returns
    ``k, v`` of shape (L, B, ng, nb*bs, hs) — the layout
    ``forward_with_cache`` consumes.  Pure jnp; call inside jit."""

    def one(arena, scale):
        g = jnp.take(arena, tables, axis=0)        # (B, nb, L, ng, bs, hs) int8
        s = jnp.take(scale, tables, axis=0)        # (B, nb, L, ng, bs) f32
        x = g.astype(jnp.float32) * s[..., None]
        x = x.transpose(2, 0, 3, 1, 4, 5)          # (L, B, ng, nb, bs, hs)
        L, B, ng, nb, bs, hs = x.shape
        return x.reshape(L, B, ng, nb * bs, hs).astype(dtype)

    return one(k_arena, k_scale), one(v_arena, v_scale)


def scatter_token_q(arena, scale_arena, new_kv, dest_block, dest_slot):
    """Quantized twin of :func:`kv_pool.scatter_token`: quantizes one
    token's K (or V) per batch row and writes value + scale.

    ``new_kv``: (B, L, ng, hs) in compute dtype; ``dest_block``/``dest_slot``:
    (B,) int32 (sink-routed for padding rows).  The storage dtype comes from
    the arena itself (int8 or fp8).  Pure jnp; call inside jit on donated
    arenas."""
    q, s = quantize_kv(new_kv, arena.dtype)        # (B, L, ng, hs) / (B, L, ng)
    arena = arena.at[dest_block, :, :, dest_slot, :].set(q)
    scale_arena = scale_arena.at[dest_block, :, :, dest_slot].set(s)
    return arena, scale_arena


def scatter_blocks_q(arena, scale_arena, dense, dest_table):
    """Quantized twin of :func:`kv_pool.scatter_blocks`: quantizes a
    request's dense cache block-by-block and writes values + scales.

    ``dense``: (L, 1, ng, nb*bs, hs) float (B=1 prefill layout);
    ``dest_table``: (nb,) int32 — sink entries absorb padding.  Returns
    ``(arena, scale_arena, rel_err)`` where ``rel_err`` is the measured
    quantization error over the actually-written (non-sink) blocks:
    ``sum|dq - x| / sum|x|`` — the per-prefill value behind the
    ``serving.kv_quant.rel_err`` gauge."""
    if not jnp.issubdtype(dense.dtype, jnp.floating):
        from thunder_tpu.serving.kv_pool import ArenaMismatchError

        raise ArenaMismatchError(
            "scatter", "dtype", "floating source", jnp.dtype(dense.dtype),
            msg=f"scatter_blocks_q quantizes a float dense cache into a "
                f"quantized arena; got source dtype {jnp.dtype(dense.dtype)}",
        )
    L, B, ng, cap, hs = dense.shape
    bs = arena.shape[3]
    blocks = dense[:, 0].reshape(L, ng, cap // bs, bs, hs).transpose(2, 0, 1, 3, 4)
    q, s = quantize_kv(blocks, arena.dtype)        # (nb, L, ng, bs, hs) / (nb, L, ng, bs)
    dq = q.astype(jnp.float32) * s[..., None]
    xf = blocks.astype(jnp.float32)
    m = (dest_table != _SINK).astype(jnp.float32)[:, None, None, None, None]
    rel_err = jnp.sum(jnp.abs(dq - xf) * m) / (jnp.sum(jnp.abs(xf) * m) + 1e-30)
    arena = arena.at[dest_table].set(q)
    scale_arena = scale_arena.at[dest_table].set(s)
    return arena, scale_arena, rel_err


#
# capacity math (host-side; the admission-accounting-in-bytes helpers)
#


def arena_block_bytes(cfg, block_size: int, dtype, kv_dtype=None) -> int:
    """Bytes ONE pool block costs across both (K+V) arenas, including the
    scale arenas on the int8 path — the unit of byte-based capacity math
    (``bench.py capacity`` sizes equal-byte pools with this)."""
    L, ng, bs, hs = kv_block_shape(cfg, block_size)
    storage = resolve_kv_dtype(kv_dtype, dtype)
    per_side = L * ng * bs * hs * storage.itemsize
    if is_quantized_kv(storage, dtype):
        per_side += L * ng * bs * 4                # float32 scale per slot-head
    return 2 * per_side


def blocks_for_arena_bytes(cfg, block_size: int, budget_bytes: int, dtype,
                           kv_dtype=None) -> int:
    """Total blocks (sink included) an arena-byte budget affords — the
    equal-bytes pool sizing behind the capacity bench."""
    bb = arena_block_bytes(cfg, block_size, dtype, kv_dtype)
    return max(int(budget_bytes) // bb, 2)
