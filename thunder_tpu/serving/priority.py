"""Priority classes, SLO-feedback admission, and evict-and-resume preemption.

``serve(..., priorities=True)`` turns the strict-FIFO queue into a
class-ordered one: ``submit(..., priority="high"|"normal"|"low")`` tags
each request with a level (lower = more urgent); the scheduler inserts
by level with FIFO order preserved within a class.  Two mechanisms keep
the latency-critical class honest under load:

**SLO-feedback admission.**  The engine's SLO monitor already computes
windowed burn rates (``bad_fraction / error_budget``) per latency
dimension.  The :class:`PriorityGate` turns those into per-class
admission: when the worst burn rate crosses a class's limit, that class
(and everything less urgent) is *deferred* at the admission gate — the
requests stay queued, higher classes keep flowing, and admission resumes
as soon as the window recovers.  ``high`` has no limit: SLO pressure
never locks out the class the SLO protects.

**Evict-and-resume preemption.**  When the queue head is strictly more
urgent than a running request and the pool cannot fund it, the engine
checkpoints the victim *at its current position*: host state (prompt,
generated tokens, PRNG key chain) is already exact because keys only
advance at harvest, so the checkpoint is just "release the blocks and
re-queue".  On re-admission the victim's sequence is rebuilt through the
sampling-free ``prefill_chunk`` replay — bucket-wide pieces, never
token-by-token — and decode continues from the identical key chain, so a
preempted-then-resumed stream is bit-identical to an undisturbed run.
The replay is not free, and with ``goodput=True`` it is not invisible
either: the engine charges every replayed position to the
``replay_preemption`` waste cause in the goodput ledger and bills the
victim's :class:`RequestResult` (``tokens_recomputed`` /
``recompute_causes``), so preemption pressure shows up as attributed
device work, not silent throughput loss.

With ``priorities=None`` (default) nothing changes: every request takes
the same level, insertion degrades to append, the gate never runs, and
no programs differ — scheduling is host policy, invisible to program
identity.
"""

from __future__ import annotations

import dataclasses

__all__ = [
    "PRIORITY_LEVELS",
    "PRIORITY_HIGH",
    "PRIORITY_NORMAL",
    "PRIORITY_LOW",
    "PriorityConfig",
    "PriorityGate",
    "resolve_priorities",
]

# Lower level = more urgent; "normal" is the engine-wide default and the
# level every request carries when priorities are disabled.
PRIORITY_HIGH = "high"
PRIORITY_NORMAL = "normal"
PRIORITY_LOW = "low"
PRIORITY_LEVELS: dict[str, int] = {
    PRIORITY_HIGH: 0,
    PRIORITY_NORMAL: 1,
    PRIORITY_LOW: 2,
}


@dataclasses.dataclass
class PriorityConfig:
    """Knobs for the admission gate and preemption.

    ``burn_limits`` maps a class name to the burn-rate threshold above
    which the class is deferred at admission (a burn rate of 1.0 means
    the window is consuming its error budget exactly at the objective
    rate).  Classes without an entry are never deferred.  ``preempt``
    turns evict-and-resume on; ``max_preemptions`` bounds how many times
    one request may be victimized (after that it is left to finish, so a
    busy high class cannot starve a low request forever).
    """

    burn_limits: dict[str, float] = dataclasses.field(
        default_factory=lambda: {PRIORITY_LOW: 1.0, PRIORITY_NORMAL: 4.0})
    preempt: bool = True
    max_preemptions: int = 8

    def __post_init__(self):
        for cls, lim in self.burn_limits.items():
            if cls not in PRIORITY_LEVELS:
                raise ValueError(
                    f"unknown priority class {cls!r} in burn_limits "
                    f"(expected one of {sorted(PRIORITY_LEVELS)})")
            if lim < 0:
                raise ValueError(f"burn limit for {cls!r} must be >= 0")
        if self.max_preemptions < 0:
            raise ValueError("max_preemptions must be >= 0")


def resolve_priorities(spec) -> "PriorityGate | None":
    """``priorities=`` engine kwarg → a :class:`PriorityGate` (or None)."""
    if spec is None or spec is False:
        return None
    if spec is True:
        cfg = PriorityConfig()
    elif isinstance(spec, PriorityConfig):
        cfg = spec
    elif isinstance(spec, dict):
        cfg = PriorityConfig(**spec)
    else:
        raise TypeError(
            f"priorities= must be None, True, a dict, or PriorityConfig; "
            f"got {type(spec).__name__}")
    return PriorityGate(cfg)


def priority_level(priority: str | None) -> tuple[str, int]:
    """Normalize a ``submit(priority=)`` value to ``(class, level)``."""
    cls = PRIORITY_NORMAL if priority is None else str(priority)
    if cls not in PRIORITY_LEVELS:
        raise ValueError(
            f"priority must be one of {sorted(PRIORITY_LEVELS)}, got {cls!r}")
    return cls, PRIORITY_LEVELS[cls]


class PriorityGate:
    """Per-class admission policy fed by SLO burn rates."""

    def __init__(self, config: PriorityConfig | None = None):
        self.config = config or PriorityConfig()
        self.deferrals: dict[str, int] = {c: 0 for c in PRIORITY_LEVELS}

    def admit_ok(self, priority_class: str, slo_monitor) -> bool:
        """May a request of this class be admitted right now?

        Consults the worst burn rate across the monitor's dimensions;
        with no monitor (``slo=None``) the gate is inert and always
        admits.
        """
        limit = self.config.burn_limits.get(priority_class)
        if limit is None or slo_monitor is None:
            return True
        burns = (slo_monitor.burn_rate(dim) for dim in slo_monitor._dims)
        worst = max((b for b in burns if b is not None), default=0.0)
        if worst > limit:
            self.deferrals[priority_class] = self.deferrals.get(priority_class, 0) + 1
            return False
        return True

    def pick_victim(self, running, head_level: int):
        """Choose the request to preempt for a head at ``head_level``.

        The victim is the least-urgent running request (ties broken by
        most-recent admission — the cheapest checkpoint to redo), and
        must be *strictly* less urgent than the head; requests already
        preempted ``max_preemptions`` times are exempt.
        """
        if not self.config.preempt:
            return None
        candidates = [r for r in running
                      if r.priority > head_level
                      and r.preemptions < self.config.max_preemptions]
        if not candidates:
            return None
        return max(candidates, key=lambda r: (r.priority, r.admit_t or 0.0))

    def snapshot(self) -> dict:
        return {
            "preempt": self.config.preempt,
            "burn_limits": dict(self.config.burn_limits),
            "deferrals": dict(self.deferrals),
        }
