"""Multi-step decode benchmark: host visits per token at N ∈ {1, 4, 8}.

The claim under test is the reason ``decode_steps=N`` exists: host
dispatch is the last per-token cost in the serving plane (one Python
round-trip per decode step), so running N decode steps inside one
compiled ``lax.scan`` program amortizes the per-token launch overhead by
N — the XLA analog of CUDA-graph multi-token capture and of vLLM's
``--num-scheduler-steps``.  On CPU the tiny-model decode step is
dispatch-bound, which is exactly the regime the TPU serving loop lives in
(host step latency dominating a small-batch decode), so the measured
host-visit counts exercise the real mechanism: fewer round-trips per
served token.

Workload: the same ``n_req`` fixed-length greedy requests served at each
horizon N.  Same-length requests finish together, so every visit of the
measured window runs at full occupancy and the horizon's visit count is
deterministic: ``host_visits_per_token`` must land at ~1/N of the 1-step
engine's (the gate allows 10%: the first generated token comes from
prefill, and a final partial visit rounds up).  Token parity against the
N=1 engine is asserted in-bench request-by-request — the throughput
numbers are only comparable because the streams are bit-identical.

All engines are warmed first (bucket programs land in the module cache),
so the measured windows pay zero XLA compiles (asserted via
``prefill_compiled`` and the gate's cold-compile check), and every
horizon's decode-program count stays inside the engine's bucket bound (N
joins the static key as one knob, not per-horizon buckets).
"""
from __future__ import annotations

import time

import jax
import jax.numpy as jnp
import numpy as np

HORIZONS = (1, 4, 8)
SMOKE_HORIZONS = (1, 4)


def multistep_bench(on_tpu: bool = False, *, smoke: bool = False) -> dict:
    """Returns ``{"results": {...}}`` in the BENCH_MICRO artifact shape."""
    import thunder_tpu as tt
    from thunder_tpu.models import llama

    horizons = SMOKE_HORIZONS if smoke else HORIZONS
    if smoke:
        n_req, prompt_len, max_new, max_batch, block_size = 4, 8, 9, 4, 8
    else:
        n_req, prompt_len, max_new, max_batch, block_size = 8, 16, 33, 8, 8
    # max_new - 1 decode tokens per request: divisible by 4 AND 8, so every
    # horizon's final visit is full and the visit count is exactly
    # ceil((max_new - 1) / N) per request-cohort
    overrides = dict(n_embd=128, intermediate_size=344, n_layer=4)
    cfg = llama.Config.from_name("tiny-llama-debug", **overrides)
    params = llama.init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)

    rng = np.random.default_rng(0)
    prompts = [rng.integers(0, cfg.vocab_size, (prompt_len,)).astype(np.int32)
               for _ in range(n_req)]
    reqs = [{"prompt": p, "max_new_tokens": max_new} for p in prompts]
    per_req = -(-(prompt_len + max_new + max(horizons)) // block_size)
    num_blocks = n_req * per_req + per_req + 1

    def make_engine(N: int):
        return tt.serve(
            None, params, cfg,
            block_size=block_size, num_blocks=num_blocks,
            max_batch=max_batch, cache_dtype=jnp.float32,
            batch_buckets=(max_batch,), decode_steps=N,
        )

    def drive(N: int):
        eng = make_engine(N)
        t0 = time.perf_counter()
        results = eng.run([dict(r) for r in reqs])
        dt = time.perf_counter() - t0
        return eng, results, dt

    # warm every horizon: bucket programs land in the module cache, so the
    # measured engines pay zero XLA compiles
    for N in horizons:
        drive(N)

    measured = {N: drive(N) for N in horizons}

    ref_results = measured[horizons[0]][1]
    parity = all(
        np.array_equal(a.tokens, b.tokens)
        for N in horizons[1:]
        for a, b in zip(measured[N][1], ref_results)
    )
    cold = sum(
        1 for N in horizons for r in measured[N][1] if r.prefill_compiled
    )

    per_horizon = {}
    for N in horizons:
        eng, results, dt = measured[N]
        stats = eng.stats()
        n_tokens = sum(len(r.new_tokens) for r in results)
        decode_compiles = sum(
            stats["compile_counts"][k]
            for k in ("decode", "decode_paged", "decode_multi",
                      "decode_multi_paged")
        )
        per_horizon[str(N)] = {
            "decode_steps": N,
            "tokens_per_sec": round(n_tokens / dt, 1),
            "host_visits": stats["host_visits"],
            "decode_tokens": eng.decode_lane_tokens,       # prefill excluded
            "host_visits_per_token": round(stats["host_visits"] / n_tokens, 4),
            "tokens_per_host_visit": round(stats["tokens_per_host_visit"], 3),
            "decode_compiles": decode_compiles,
            "bucket_bound": stats["bucket_bound"],
        }

    return {
        "results": {
            "horizons": list(horizons),
            "per_horizon": per_horizon,
            "token_parity_exact": bool(parity),
            "cold_compile_prefills_measured": cold,
            "n_requests": n_req,
            "occupancy": n_req,
            "prompt_tokens": prompt_len,
            "max_new_tokens": max_new,
            "attn": measured[horizons[0]][0].stats()["attn"]["mode"],
            "config": f"tiny-llama n_embd={cfg.n_embd} n_layer={cfg.n_layer}",
            "smoke": smoke,
        }
    }
