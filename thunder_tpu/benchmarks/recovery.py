"""Fault-tolerance benchmark: re-prefill recovery vs a cold engine restart.

Three claims are measured and gated (``tools/bench_targets.
check_recovery_targets``):

1. **Faults-off overhead ≤ 1.05x** — an armed-but-silent FaultPlan (the
   worst case anyone pays in production: the per-point host check runs,
   nothing fires) must not slow serving vs the unarmed engine, and must add
   zero compiled programs (the plan lives outside the program-cache key).
   Interleaved best-of-N to keep the ratio honest on a noisy host.
2. **Injected-fault token parity** — a plan that actually fires (a
   transient dispatch failure *and* a device OOM mid-decode, exercising
   both the retry and the arena-rebuild paths) must drain tokens
   bit-identical to the fault-free run.  Asserted in-bench: a recovery
   latency from a diverging engine is meaningless.
3. **Recovery beats a cold restart** — ``engine.recover()`` replays the
   known tokens through the wide chunked-prefill program (few dispatches,
   whole chunks per step); a cold restart must re-decode the same history
   one token per step on a fresh engine.  The gated ``speedup_x`` is
   cold-restart wall time / recovery wall time at the same resume point.

Config note: tiny-llama at ``n_embd=128`` (the BENCH_SERVING.json width,
where CPU compute beats dispatch); everything is warmed first — including
one throwaway ``recover()`` — so the measured windows are compile-free.
"""
from __future__ import annotations

import time

import jax
import jax.numpy as jnp
import numpy as np


def recovery_bench(on_tpu: bool = False, *, smoke: bool = False) -> dict:
    """Returns ``{"results": {...}}`` in the BENCH_MICRO artifact shape."""
    import thunder_tpu as tt
    from thunder_tpu.models import llama
    from thunder_tpu.serving import FaultPlan, FaultSpec, RetryPolicy

    if smoke:
        n_req, prompt_len, max_new, reps = 2, 16, 8, 2
        resume_tokens = 4
    else:
        n_req, prompt_len, max_new, reps = 4, 48, 32, 4
        resume_tokens = 24
    overrides = dict(n_embd=128, intermediate_size=344)
    cfg = llama.Config.from_name("tiny-llama-debug", **overrides)
    params = llama.init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    rng = np.random.default_rng(0)
    prompts = [rng.integers(0, cfg.vocab_size, (prompt_len,)).astype(np.int32)
               for _ in range(n_req)]
    reqs = [{"prompt": p, "max_new_tokens": max_new} for p in prompts]
    block_size = 8
    per_req = -(-(prompt_len + max_new) // block_size) + 1
    num_blocks = n_req * per_req + 2

    def make_engine(fault_plan=None):
        return tt.serve(
            None, params, cfg, block_size=block_size, num_blocks=num_blocks,
            max_batch=n_req, cache_dtype=jnp.float32, fault_plan=fault_plan,
            retry=RetryPolicy(sleep=lambda s: None),
        )

    def drive(fault_plan=None):
        eng = make_engine(fault_plan)
        t0 = time.perf_counter()
        results = eng.run([dict(r) for r in reqs])
        return eng, results, time.perf_counter() - t0

    # a spec that can never fire: the armed engine pays the check, nothing else
    def silent_plan():
        return FaultPlan(specs=[FaultSpec(point="decode.dispatch", kind="oom",
                                          at=10_000_000)])

    # warm every program (and the recovery path itself: its replay uses the
    # widest chunk program, which plain serving may never compile)
    eng, ref_results, _ = drive()
    warm = make_engine()
    hw = [warm.submit(p, max_new_tokens=max_new) for p in prompts]
    while len(warm.scheduler.running) < n_req or any(
            len(r._req.generated) < resume_tokens for r in hw):
        warm.step()
    warm.recover()
    warm.drain()
    drive(silent_plan())

    # 1) faults-off overhead: unarmed vs armed-but-silent, interleaved best-of
    from thunder_tpu.serving.engine import _program_cache

    n_progs = len(_program_cache)
    off_best = armed_best = float("inf")
    for _ in range(reps):
        _, _, dt = drive()
        off_best = min(off_best, dt)
        _, _, dt = drive(silent_plan())
        armed_best = min(armed_best, dt)
    overhead_x = armed_best / off_best
    programs_added_when_armed = len(_program_cache) - n_progs

    # 2) injected-fault parity: retry path + recovery path in one drive
    faulty_plan = FaultPlan(specs=[
        FaultSpec(point="decode.dispatch", kind="fail", at=2),
        FaultSpec(point="harvest", kind="oom", at=5),
    ])
    eng_f, fault_results, _ = drive(faulty_plan)
    parity = all(np.array_equal(a.tokens, b.tokens)
                 for a, b in zip(fault_results, ref_results))
    pool_clean = (eng_f.pool.num_free == eng_f.pool.num_usable)

    # 3) recovery vs cold restart at the same resume point
    def to_resume_point():
        eng = make_engine()
        handles = [eng.submit(p, max_new_tokens=max_new) for p in prompts]
        while any(len(h._req.generated) < resume_tokens for h in handles):
            eng.step()
        return eng, handles

    recover_best = cold_best = float("inf")
    recover_parity = True
    for _ in range(reps):
        eng, handles = to_resume_point()
        t0 = time.perf_counter()
        eng.recover()
        recover_best = min(recover_best, time.perf_counter() - t0)
        eng.drain()
        recover_parity = recover_parity and all(
            np.array_equal(h.result(drive=False).tokens, r.tokens)
            for h, r in zip(handles, ref_results))

        # cold restart: a fresh engine must re-earn the same history —
        # prompts re-prefill, then every already-served token re-decodes
        # one step at a time before the stream is back where it was
        t0 = time.perf_counter()
        cold, cold_handles = to_resume_point()
        cold_best = min(cold_best, time.perf_counter() - t0)
        cold.drain()

    tokens_replayed = n_req * (prompt_len + resume_tokens - 1)

    return {
        "results": {
            "faults_off_overhead_x": round(overhead_x, 3),
            "programs_added_when_armed": programs_added_when_armed,
            "injected_fault_token_parity": bool(parity),
            "injected_fault_recoveries": eng_f.recoveries,
            "pool_clean_after_faulted_drain": bool(pool_clean),
            "recovery_s": round(recover_best, 6),
            "cold_restart_s": round(cold_best, 6),
            "speedup_x": round(cold_best / recover_best, 3),
            "recovered_token_parity": bool(recover_parity),
            "tokens_replayed": tokens_replayed,
            "resume_point_tokens": resume_tokens,
            "n_requests": n_req,
            "prompt_tokens": prompt_len,
            "max_new_tokens": max_new,
            "config": f"tiny-llama n_embd={cfg.n_embd} n_layer={cfg.n_layer}",
            "smoke": smoke,
        }
    }
