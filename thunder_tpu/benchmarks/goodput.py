"""Goodput-ledger benchmark: observation overhead, conservation, and the
zero-new-programs contract (ISSUE 18).

Four claims under test, one per acceptance bar of the goodput PR:

**Overhead.**  The ledger is pure host arithmetic over shapes the engine
already holds, so an engine serving with ``goodput=True`` must stay
within 1.05x of the identical ``goodput=False`` engine's wall time over
the same request load (min-of-reps on both sides, reps interleaved so
machine drift hits both engines equally).

**Conservation.**  On the measured engine itself, the ledger's aggregate
identity must hold exactly: ``committed + sum(waste) == positions`` as
integers, zero violations (the ledger runs strict, so any per-dispatch
violation would have raised mid-bench), and ``committed_tokens`` equal to
the tokens the requests actually streamed.

**Acceptance.**  On a speculative engine pair, the ledger's draft-kind
committed count must equal the engine's own ``spec_accepted_tokens``
integer exactly — the waste taxonomy reproduces the acceptance
accounting, it does not approximate it.

**Programs.**  After the ``goodput=False`` engine warms the module
program cache, building and driving the ``goodput=True`` engines must
add zero cache entries and compile nothing: observation never enters
program identity.
"""
from __future__ import annotations

import time

import numpy as np


def _drive(eng, prompts, n):
    hs = [eng.submit(p, max_new_tokens=n) for p in prompts]
    return [h.result() for h in hs]


def goodput_bench(on_tpu: bool = False, *, smoke: bool = False) -> dict:
    """Returns ``{"results": {...}}`` in the BENCH_MICRO artifact shape."""
    import jax
    import jax.numpy as jnp

    import thunder_tpu as tt
    from thunder_tpu.models import llama
    from thunder_tpu.serving import SpecConfig
    from thunder_tpu.serving.engine import _program_cache

    if smoke:
        reps, n_req, prompt_len, new_tokens = 2, 3, 12, 8
    else:
        reps, n_req, prompt_len, new_tokens = 8, 4, 24, 32
    overrides = dict(n_embd=128, intermediate_size=344, n_layer=4)
    cfg = llama.Config.from_name("tiny-llama-debug", **overrides)
    params = llama.init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    dcfg = llama.Config.from_name("tiny-llama-debug",
                                  **{**overrides, "n_layer": 1})
    dparams = llama.init_params(dcfg, jax.random.PRNGKey(9), dtype=jnp.float32)
    rng = np.random.default_rng(0)

    def prompts():
        return [rng.integers(0, cfg.vocab_size, (prompt_len,)).astype(np.int32)
                for _ in range(n_req)]

    def make_engine(**kw):
        base = dict(block_size=8, num_blocks=96, max_batch=4,
                    cache_dtype=jnp.float32, batch_buckets=(4,),
                    prefill_buckets=(32,))
        base.update(kw)
        return tt.serve(None, params, cfg, **base)

    #
    # 1+2+4. paired decode engines: overhead, conservation, program count
    #
    off = make_engine()
    _drive(off, prompts(), new_tokens)               # warm the module cache
    progs_before = len(_program_cache)
    on = make_engine(goodput=True)
    _drive(on, prompts(), new_tokens)                # warm (cache is shared)
    new_programs = len(_program_cache) - progs_before
    new_programs += sum(on.compile_counts.values())  # and none engine-local

    off_s, on_s = [], []
    streamed = 0
    tokens_before = on.stats()["goodput"]["committed_tokens"]
    for rep in range(reps):          # interleave, alternate order: drift-fair
        load = prompts()
        for eng in ((off, on) if rep % 2 == 0 else (on, off)):
            t0 = time.perf_counter()
            res = _drive(eng, load, new_tokens)
            (off_s if eng is off else on_s).append(time.perf_counter() - t0)
            if eng is on:
                streamed += sum(len(r.new_tokens) for r in res)
    snap = on.stats()["goodput"]
    conserved = (
        snap["violations"] == 0
        and snap["committed"] + sum(snap["waste"].values()) == snap["positions"]
        and snap["committed_tokens"] - tokens_before == streamed)
    off.shutdown()
    on.shutdown()

    #
    # 3. speculative pairs: the ledger's acceptance integers are the
    # engine's — a real draft/target pair exercises the rejection path
    # (near-zero acceptance at this vocab), a self-draft pair the
    # acceptance path (greedy: every drafted token accepted)
    #
    def spec_pair(dp_, dcfg_):
        nonlocal new_programs, conserved
        off_e = make_engine(num_blocks=128,
                            speculative=SpecConfig(dp_, dcfg_, K=2))
        _drive(off_e, prompts(), new_tokens)
        before = len(_program_cache)
        on_e = make_engine(num_blocks=128,
                           speculative=SpecConfig(dp_, dcfg_, K=2),
                           goodput=True)
        _drive(on_e, prompts(), new_tokens)
        new_programs += len(_program_cache) - before
        new_programs += sum(on_e.compile_counts.values())
        per = on_e.goodput_report()["per_kind"]
        acc = per["draft_decode"]["committed"]
        drafted = (per["draft_decode"]["positions"]
                   - per["draft_decode"]["waste"].get("pad_row", 0)
                   - per["draft_decode"]["waste"].get("dead_scan_row", 0))
        exact = (acc == on_e.spec_accepted_tokens
                 and drafted == on_e.spec_draft_tokens)
        s = on_e.stats()["goodput"]
        conserved = conserved and s["violations"] == 0 and (
            s["committed"] + sum(s["waste"].values()) == s["positions"])
        off_e.shutdown()
        on_e.shutdown()
        return acc, drafted, exact

    acc_r, drafted_r, exact_r = spec_pair(dparams, dcfg)
    acc_s, drafted_s, exact_s = spec_pair(params, cfg)
    ledger_accepted = acc_r + acc_s
    ledger_drafted = drafted_r + drafted_s
    spec_exact = exact_r and exact_s and acc_s > 0

    return {
        "results": {
            "off_ms": round(min(off_s) * 1e3, 3),
            "on_ms": round(min(on_s) * 1e3, 3),
            "overhead_ratio_x": round(min(on_s) / min(off_s), 4),
            "conservation_exact": bool(conserved),
            "goodput_frac": round(snap["goodput_frac"], 4),
            "token_goodput_frac": round(snap["token_goodput_frac"], 4),
            "waste": dict(snap["waste"]),
            "spec_acceptance_exact": bool(spec_exact),
            "spec_accepted_tokens": int(ledger_accepted),
            "spec_draft_tokens": int(ledger_drafted),
            "new_programs_with_goodput": int(new_programs),
            "reps": reps,
            "requests_per_rep": n_req,
            "new_tokens": new_tokens,
            "config": f"tiny-llama n_embd={cfg.n_embd} n_layer={cfg.n_layer}",
            "smoke": smoke,
        }
    }
