"""Reusable benchmark-class library (reference ``thunder/benchmarks/__init__.py:50-460``).

The reference ships ~25 benchmark classes sharing one contract — a
``Benchmark`` with a name, a ``make_batch`` (sample inputs), and an ``fn``
to time — plus harness functions that run any of them under any executor
and report wallclock stats.  The TPU-native analog here keeps that contract
but times with the tunnel-proof methodology (a real device→host fetch is
the only reliable fence over the axon tunnel; ``timing.time_fn``) and
compares the thunder_tpu pipeline against stock ``jax.jit`` instead of
torch eager.

Tiers (mirroring the reference's spread):
- per-op      — gelu, cross_entropy, rms_norm, sdpa, swiglu (``op_benchmarks``)
- per-block   — MLP, causal self-attention, full transformer block
  (``block_benchmarks``; reference LitGPTMLP/CSA/Block classes, :584-698)
- per-model   — the llama family train step (``model_benchmarks``)

Every class is importable and pytest-runnable (``tests/test_bench_targets.py``)
and drivable standalone via ``python bench.py blocks``.
"""
from __future__ import annotations

import dataclasses
import functools
from typing import Callable

import jax
import jax.numpy as jnp

from thunder_tpu.benchmarks.timing import time_fn

__all__ = [
    "Benchmark",
    "BenchmarkResult",
    "run_benchmark",
    "op_benchmarks",
    "block_benchmarks",
    "model_benchmarks",
    "ablation_benchmarks",
    "jax_gpt_loss",
    "all_benchmarks",
]


@dataclasses.dataclass
class Benchmark:
    """One timeable workload: ``fn(*make_batch())`` under the thunder_tpu
    jit, ``baseline_fn`` (same math, plain jax) under stock ``jax.jit``."""

    name: str
    fn: Callable  # thunder_tpu-level callable (ltorch ops)
    baseline_fn: Callable | None  # plain-jax same-math callable (None: reuse fn)
    make_batch: Callable[[], tuple]  # () -> args
    tier: str = "op"  # op | block | model
    prejitted: bool = False  # fns already compiled (tt.grad / jax.grad pairs)
    # executor-ablation axis (reference's executor-zoo benchmarks,
    # benchmarks/__init__.py:699-975): e.g. {"executors": ["xla", "jax"]}
    # benches the same workload with pallas kernels disabled
    jit_kwargs: dict = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class BenchmarkResult:
    name: str
    tier: str
    thunder_ms: float
    baseline_ms: float | None
    speedup: float | None  # baseline / thunder

    def row(self) -> dict:
        out = {"name": self.name, "tier": self.tier,
               "thunder_ms": round(self.thunder_ms, 4)}
        if self.baseline_ms is not None:
            out["jax_ms"] = round(self.baseline_ms, 4)
            out["speedup"] = round(self.speedup, 3) if self.speedup else None
        return out


def run_benchmark(b: Benchmark, *, reps: int = 3) -> BenchmarkResult:
    """Times ``b`` (thunder pipeline vs stock jax.jit), pairwise-interleaved
    per rep with per-side min — the tunneled backend drifts by whole
    percents between loops (measured r3), so each rep times both sides
    back-to-back and min() rides the drift out."""
    import thunder_tpu as tt

    args = b.make_batch()
    tfn = b.fn if b.prejitted else tt.jit(b.fn, **b.jit_kwargs)
    if b.baseline_fn is None:
        jfn = None
    else:
        jfn = b.baseline_fn if b.prejitted else jax.jit(b.baseline_fn)
    t_vals, j_vals = [], []
    for _ in range(reps):
        t = time_fn(tfn, *args)
        if t == t:
            t_vals.append(t)
        if jfn is not None:
            j = time_fn(jfn, *args)
            if j == j:
                j_vals.append(j)
    t_ms = min(t_vals) * 1e3 if t_vals else float("nan")
    j_ms = min(j_vals) * 1e3 if j_vals else None
    speedup = (j_ms / t_ms) if (j_ms and t_ms == t_ms and t_ms > 0) else None
    return BenchmarkResult(b.name, b.tier, t_ms, j_ms, speedup)


#
# Shape presets: "tpu" = the headline-scale shapes (v5e, bf16), "cpu" = toy
# dims for CI (the classes themselves are shape-agnostic)
#


def _shapes(on_tpu: bool) -> dict:
    if on_tpu:
        return dict(B=8, H=32, T=2048, hs=128, C=4096, V=32000, I=11008, dt=jnp.bfloat16)
    return dict(B=2, H=2, T=128, hs=32, C=128, V=512, I=344, dt=jnp.float32)


def op_benchmarks(on_tpu: bool) -> list[Benchmark]:
    """Per-op tier (reference targets.py:402-700 op benchmarks)."""
    import thunder_tpu.torch as ltorch

    s = _shapes(on_tpu)
    B, T, C, V, I, dt = s["B"], s["T"], s["C"], s["V"], s["I"], s["dt"]
    key = jax.random.PRNGKey(0)
    k = lambda i: jax.random.fold_in(key, i)
    N = B * T

    def batch_rows():
        return (jax.random.normal(k(0), (N, C), dtype=dt),)

    def batch_ce():
        return (jax.random.normal(k(1), (N, V), dtype=jnp.float32),
                jax.random.randint(k(2), (N,), 0, V))

    def batch_norm():
        return (jax.random.normal(k(0), (N, C), dtype=dt), jnp.ones((C,), dtype=dt))

    def batch_mlp():
        return (jax.random.normal(k(0), (N, C), dtype=dt),
                jax.random.normal(k(3), (I, C), dtype=dt) * 0.02,
                jax.random.normal(k(4), (I, C), dtype=dt) * 0.02,
                jax.random.normal(k(5), (C, I), dtype=dt) * 0.02)

    def plain_ce(l, t):
        lse = jax.nn.logsumexp(l, axis=-1)
        return (lse - jnp.take_along_axis(l, t[:, None], axis=1)[:, 0]).mean()

    def plain_rms(a, w):
        af = a.astype(jnp.float32)
        ms = jnp.mean(af * af, axis=-1, keepdims=True)
        return ((af * jax.lax.rsqrt(ms + 1e-5)) * w.astype(jnp.float32)).astype(a.dtype)

    return [
        Benchmark("gelu", lambda a: ltorch.gelu(a),
                  functools.partial(jax.nn.gelu, approximate=False), batch_rows),
        Benchmark("cross_entropy", lambda l, t: ltorch.cross_entropy(l, t), plain_ce, batch_ce),
        Benchmark("rms_norm", lambda a, w: ltorch.rms_norm(a, (C,), w), plain_rms, batch_norm),
        Benchmark("swiglu_mlp",
                  lambda x, a, b, c: ltorch.linear(ltorch.silu(ltorch.linear(x, a)) * ltorch.linear(x, b), c),
                  lambda x, a, b, c: (jax.nn.silu(x @ a.T) * (x @ b.T)) @ c.T, batch_mlp),
    ]


def block_benchmarks(on_tpu: bool) -> list[Benchmark]:
    """Per-block tier: MLP / causal self-attention / full transformer block
    through the framework vs the hand-written jax mirror (reference
    LitGPTMLP / LitGPTCSA / LitGPTBlock benchmark classes)."""
    from thunder_tpu.models import llama

    s = _shapes(on_tpu)
    B, dt = s["B"], s["dt"]
    if on_tpu:
        cfg = llama.Config.from_name("Llama-2-7b-hf", n_layer=1)
    else:
        cfg = llama.Config.from_name("tiny-llama-debug", n_layer=1)
    T = min(s["T"], cfg.block_size)
    key = jax.random.PRNGKey(0)
    params = llama.init_params(cfg, key, dtype=dt)
    bp = params["blocks"][0]
    cos, sin = llama.build_rope_cache(cfg, T, dtype=jnp.float32)
    x = jax.random.normal(jax.random.fold_in(key, 9), (B, T, cfg.n_embd), dtype=dt)

    # the hand-written jax mirrors (same math as models/llama, no tracing)
    def jax_rms(h, w):
        hf = h.astype(jnp.float32)
        ms = jnp.mean(hf * hf, axis=-1, keepdims=True)
        return ((hf * jax.lax.rsqrt(ms + cfg.norm_eps)) * w.astype(jnp.float32)).astype(h.dtype)

    def jax_rope(h, cos_, sin_):
        half = h.shape[-1] // 2
        rotated = jnp.concatenate([-h[..., half:], h[..., :half]], axis=-1)
        return (h * cos_ + rotated * sin_).astype(h.dtype)

    def jax_csa(ap, h):
        Bl, Tl, Cl = h.shape
        hs, nh, ng = cfg.head_size, cfg.n_head, cfg.n_query_groups
        q = (h @ ap["wq"].T).reshape(Bl, Tl, nh, hs).transpose(0, 2, 1, 3)
        kk = (h @ ap["wk"].T).reshape(Bl, Tl, ng, hs).transpose(0, 2, 1, 3)
        v = (h @ ap["wv"].T).reshape(Bl, Tl, ng, hs).transpose(0, 2, 1, 3)
        q, kk = jax_rope(q, cos, sin), jax_rope(kk, cos, sin)
        if ng != nh:
            kk = jnp.repeat(kk, nh // ng, axis=1)
            v = jnp.repeat(v, nh // ng, axis=1)
        sres = (q @ kk.transpose(0, 1, 3, 2)).astype(jnp.float32) / (hs ** 0.5)
        mask = jnp.tril(jnp.ones((Tl, Tl), dtype=bool))
        sres = jnp.where(mask, sres, -jnp.inf)
        y = (jax.nn.softmax(sres, axis=-1).astype(q.dtype) @ v)
        y = y.transpose(0, 2, 1, 3).reshape(Bl, Tl, nh * hs)
        return y @ ap["wo"].T

    def jax_mlp(mp, h):
        return (jax.nn.silu(h @ mp["fc_1"].T) * (h @ mp["fc_2"].T)) @ mp["proj"].T

    def jax_block(bp_, h):
        a = h + jax_csa(bp_["attn"], jax_rms(h, bp_["norm_1"]))
        return a + jax_mlp(bp_["mlp"], jax_rms(a, bp_["norm_2"]))

    # cos/sin travel as explicit args: the thunder jit proxies ARGUMENTS —
    # a closed-over concrete jax array inside ltorch ops is "not number-like"
    benches = [
        Benchmark("block_mlp", lambda mp, h: llama.mlp(mp, h, cfg),
                  jax_mlp, lambda: (bp["mlp"], x), tier="block"),
        Benchmark("block_csa",
                  lambda ap, h, c, s: llama.attention(ap, h, c, s, cfg),
                  lambda ap, h, c, s: jax_csa(ap, h), lambda: (bp["attn"], x, cos, sin),
                  tier="block"),
        Benchmark("transformer_block",
                  lambda bp_, h, c, s: llama.block_forward(bp_, h, c, s, cfg),
                  lambda bp_, h, c, s: jax_block(bp_, h), lambda: (bp, x, cos, sin),
                  tier="block"),
    ]

    # fwd+bwd tier (the reference benchmarks backward too): grads of a
    # scalarized block loss wrt the block params, framework VJP vs jax.grad
    import thunder_tpu as tt
    import thunder_tpu.torch as ltorch

    def t_block_loss(bp_, h, c, s):
        out = llama.block_forward(bp_, h, c, s, cfg)
        return ltorch.sum(out * out)

    def j_block_loss(bp_, h, c, s):
        out = jax_block(bp_, h)
        return jnp.sum((out * out).astype(jnp.float32))

    benches.append(Benchmark(
        "transformer_block_grad",
        tt.grad(t_block_loss, argnums=0),
        jax.jit(jax.grad(j_block_loss, argnums=0)),
        lambda: (bp, x, cos, sin), tier="block", prejitted=True,
    ))
    return benches


def jax_gpt_loss(cfg):
    """A config-parameterized PLAIN-JAX mirror of ``models/llama.gpt_loss``
    (same math, no tracing pipeline) so every model family benches against a
    stock ``jax.jit`` baseline — the reference benches LitGPT models against
    torch eager/compile the same way.  Handles every config switch the model
    zoo uses: RMS/layer norm, partial rope, GQA, sliding window, the four
    MLP classes (incl. dense MoE), parallel residual, learned positions,
    scaled/tied embeddings, and the -100-ignore CE."""

    def norm(h, w, b=None):
        hf = h.astype(jnp.float32)
        if cfg.norm_class == "RMSNorm":
            out = hf * jax.lax.rsqrt(jnp.mean(hf * hf, -1, keepdims=True) + cfg.norm_eps)
            out = out * w.astype(jnp.float32)
        else:
            mu = jnp.mean(hf, -1, keepdims=True)
            var = jnp.mean((hf - mu) ** 2, -1, keepdims=True)
            out = (hf - mu) * jax.lax.rsqrt(var + cfg.norm_eps) * w.astype(jnp.float32)
            if b is not None:
                out = out + b.astype(jnp.float32)
        return out.astype(h.dtype)

    def rope(h, cos, sin):
        half = h.shape[-1] // 2
        rotated = jnp.concatenate([-h[..., half:], h[..., :half]], -1)
        return (h * cos + rotated * sin).astype(h.dtype)

    def lin(x, w, b=None):
        y = x @ w.T
        return y if b is None else y + b

    def attn(ap, h, cos, sin):
        B, T, _ = h.shape
        hs, nh, ng = cfg.head_size, cfg.n_head, cfg.n_query_groups
        q = lin(h, ap["wq"], ap.get("bq")).reshape(B, T, nh, hs).transpose(0, 2, 1, 3)
        k = lin(h, ap["wk"], ap.get("bk")).reshape(B, T, ng, hs).transpose(0, 2, 1, 3)
        v = lin(h, ap["wv"], ap.get("bv")).reshape(B, T, ng, hs).transpose(0, 2, 1, 3)
        ne = cfg.rope_n_elem
        if ne > 0:
            q_r, k_r = rope(q[..., :ne], cos, sin), rope(k[..., :ne], cos, sin)
            q = jnp.concatenate([q_r, q[..., ne:]], -1) if ne < hs else q_r
            k = jnp.concatenate([k_r, k[..., ne:]], -1) if ne < hs else k_r
        if ng != nh:
            k = jnp.repeat(k, nh // ng, axis=1)
            v = jnp.repeat(v, nh // ng, axis=1)
        s = (q @ k.transpose(0, 1, 3, 2)).astype(jnp.float32) / (hs ** 0.5)
        rows = jnp.arange(T)[:, None]
        cols = jnp.arange(T)[None, :]
        mask = cols <= rows
        if cfg.sliding_window is not None:
            mask = mask & (cols > rows - cfg.sliding_window)
        s = jnp.where(mask, s, -jnp.inf)
        y = (jax.nn.softmax(s, axis=-1).astype(q.dtype) @ v)
        y = y.transpose(0, 2, 1, 3).reshape(B, T, nh * hs)
        return lin(y, ap["wo"], ap.get("bo"))

    def gelu(x):
        return jax.nn.gelu(x, approximate=cfg.gelu_approximate == "tanh")

    def mlp(mp, h):
        if cfg.mlp_class == "LLaMAMoE":
            E, kk = cfg.n_expert, cfg.n_expert_per_token
            router = h @ mp["gate"].T
            top_logits, top_idx = jax.lax.top_k(router, kk)
            probs = jax.nn.softmax(top_logits.astype(jnp.float32), -1)
            y = 0.0
            for e in range(E):
                w_e = jnp.sum(probs * (top_idx == e).astype(jnp.float32), -1)
                xe = lin(jax.nn.silu(lin(h, mp["fc_1"][e])) * lin(h, mp["fc_2"][e]), mp["proj"][e])
                y = y + xe * w_e[..., None].astype(h.dtype)
            return y
        if cfg.mlp_class == "LLaMAMLP":
            return lin(jax.nn.silu(lin(h, mp["fc_1"], mp.get("fc_1_b")))
                       * lin(h, mp["fc_2"], mp.get("fc_2_b")), mp["proj"], mp.get("proj_b"))
        if cfg.mlp_class == "GemmaMLP":
            return lin(gelu(lin(h, mp["fc_1"], mp.get("fc_1_b")))
                       * lin(h, mp["fc_2"], mp.get("fc_2_b")), mp["proj"], mp.get("proj_b"))
        return lin(gelu(lin(h, mp["fc"], mp.get("fc_b"))), mp["proj"], mp.get("proj_b"))

    def block(bp, h, cos, sin):
        n1 = norm(h, bp["norm_1"], bp.get("norm_1_b"))
        a = attn(bp["attn"], n1, cos, sin)
        if cfg.parallel_residual:
            n2 = n1 if cfg.shared_attention_norm else norm(h, bp["norm_2"], bp.get("norm_2_b"))
            return h + a + mlp(bp["mlp"], n2)
        h = h + a
        return h + mlp(bp["mlp"], norm(h, bp["norm_2"], bp.get("norm_2_b")))

    def loss(params, idx, targets, cos, sin):
        x = params["wte"][idx]
        if cfg.scale_embedding:
            x = x * (cfg.n_embd ** 0.5)
        if cfg.learned_pos_embedding:
            x = x + params["wpe"][: idx.shape[1]]
        for bp in params["blocks"]:
            x = block(bp, x, cos, sin)
        x = norm(x, params["ln_f"], params.get("ln_f_b"))
        head = params["wte"] if cfg.tie_embeddings else params["lm_head"]
        logits = lin(x, head, params.get("lm_head_b")).astype(jnp.float32)
        V = logits.shape[-1]
        lo, t = logits.reshape(-1, V), targets.reshape(-1)
        lse = jax.nn.logsumexp(lo, axis=-1)
        nll = lse - jnp.take_along_axis(lo, jnp.maximum(t, 0)[:, None], axis=1)[:, 0]
        valid = t != -100
        return jnp.sum(jnp.where(valid, nll, 0.0)) / jnp.maximum(jnp.sum(valid), 1)

    return loss


# model-family grid: (short, CPU debug config, TPU config + overrides,
# TPU batch override).  TPU configs are the real architectures depth-
# truncated to bench on one chip; wide-vocab families get smaller (B, T) —
# Gemma's 256k vocab at the shared B=8,T=2048 preset would materialize a
# 16.8 GB fp32 logits tensor alone (> v5e HBM)
_MODEL_FAMILIES = [
    ("llama2", "tiny-llama-debug", ("Llama-2-7b-hf", {"n_layer": 2}), {}),
    ("gpt2", "nanogpt-debug", ("gpt2-124m", {}), {}),
    ("mistral_sw", "tiny-mistral-debug", ("Mistral-7B-like", {"n_layer": 2}), {}),
    ("gemma", "tiny-gemma-debug", ("Gemma-7b-like", {"n_layer": 2}), {"B": 2, "T": 1024}),
    ("falcon", "tiny-falcon-debug", ("Falcon-7b-like", {"n_layer": 2}), {"B": 4, "T": 1024}),
    ("pythia", "tiny-pythia-debug", ("Pythia-6.9b-like", {"n_layer": 2}), {"B": 4, "T": 1024}),
    ("moe", "tiny-moe-debug", ("Mixtral-8x7B-like", {"n_layer": 1}), {"B": 4, "T": 1024}),
]


def _family_batch(cfg, on_tpu: bool, override: dict | None = None):
    from thunder_tpu.models import llama

    s = _shapes(on_tpu)
    s.update(override or {})
    B, dt = s["B"], s["dt"]
    T = min(s["T"], cfg.block_size)
    key = jax.random.PRNGKey(0)
    params = llama.init_params(cfg, key, dtype=dt)
    idx = jax.random.randint(jax.random.fold_in(key, 1), (B, T), 0, cfg.vocab_size)
    tgt = jax.random.randint(jax.random.fold_in(key, 2), (B, T), 0, cfg.vocab_size)
    cos, sin = llama.build_rope_cache(cfg, T, dtype=jnp.float32)
    return params, idx, tgt, cos, sin


def model_benchmarks(on_tpu: bool, families: list[str] | None = None) -> list[Benchmark]:
    """Per-model tier: every zoo family, forward+loss AND fwd+bwd, each with
    a plain-jax baseline (``jax_gpt_loss``).  ``families`` filters by short
    name (CI smokes one; ``bench.py blocks`` runs the grid).  Device arrays
    allocate LAZILY inside make_batch — eager construction would hold every
    family's multi-GB weights alive at once on TPU."""
    import thunder_tpu as tt
    from thunder_tpu.models import llama

    out = []
    for short, cpu_name, (tpu_name, tpu_kw), tpu_batch in _MODEL_FAMILIES:
        if families is not None and short not in families:
            continue
        cfg = (llama.Config.from_name(tpu_name, **tpu_kw) if on_tpu
               else llama.Config.from_name(cpu_name))
        jloss = jax_gpt_loss(cfg)
        mk = (lambda _c=cfg, _o=tpu_batch if on_tpu else None:
              _family_batch(_c, on_tpu, _o))

        def t_loss(p, i, t, c, s, _cfg=cfg):
            return llama.gpt_loss(p, i, t, c, s, _cfg)

        out.append(Benchmark(f"{short}_loss", t_loss, jloss, mk, tier="model"))
        out.append(Benchmark(
            f"{short}_grad",
            tt.grad(t_loss, argnums=0),
            jax.jit(jax.grad(jloss, argnums=0)),
            mk, tier="model", prejitted=True,
        ))
    return out


def ablation_benchmarks(on_tpu: bool) -> list[Benchmark]:
    """Executor-ablation axis (reference executor-zoo benchmarks,
    benchmarks/__init__.py:699-975): the SAME llama loss workload with one
    lever flipped per class, so a regression is attributable to the lever —
    pallas kernels off, fused head CE on, int8 quantized train step."""
    import optax

    import thunder_tpu as tt
    from thunder_tpu import distributed as dist
    from thunder_tpu.models import llama

    cfg = (llama.Config.from_name("Llama-2-7b-hf", n_layer=2) if on_tpu
           else llama.Config.from_name("tiny-llama-debug"))
    cfg_fused = llama.Config.from_name(cfg.name, n_layer=cfg.n_layer, fused_head_ce=True)
    mk = lambda: _family_batch(cfg, on_tpu)  # lazy: allocate when timed

    out = [
        Benchmark("ablate_no_pallas_loss",
                  lambda p, i, t, c, s_, _c=cfg: llama.gpt_loss(p, i, t, c, s_, _c),
                  None, mk, tier="ablation",
                  jit_kwargs={"executors": ["xla", "jax"]}),
        Benchmark("ablate_fused_ce_loss",
                  lambda p, i, t, c, s_, _c=cfg_fused: llama.gpt_loss(p, i, t, c, s_, _c),
                  None, mk, tier="ablation"),
    ]

    # quant on/off: the int8 train step vs the fp train step (same model,
    # same optimizer; donate=False so the timed args survive repeat calls).
    # Params + optimizer state also allocate lazily, inside make_batch; the
    # prejitted fn is the step itself over those args.
    mesh = dist.make_mesh({"dp": 1}, devices=jax.devices()[:1])

    def _mk(quant):
        step = dist.make_train_step(
            lambda p, i, t, c, s_: llama.gpt_loss(p, i, t, c, s_, cfg),
            optax.adamw(1e-4), mesh, donate=False, quant=quant,
        )

        def batch():
            params, idx, tgt, cos, sin = _family_batch(cfg, on_tpu)
            return (params, step.init_optimizer_state(params), idx, tgt, cos, sin)

        return Benchmark(f"ablate_train_step_{quant or 'fp'}",
                         lambda *a: step(*a), None, batch,
                         tier="ablation", prejitted=True)

    out.append(_mk(None))
    out.append(_mk("int8"))
    return out


def all_benchmarks(on_tpu: bool) -> list[Benchmark]:
    return (op_benchmarks(on_tpu) + block_benchmarks(on_tpu)
            + model_benchmarks(on_tpu) + ablation_benchmarks(on_tpu))
