"""Dispatch-overhead microbenchmark: µs/call vs number of cached
specializations.

The quantity bench.py's headline cannot see: the HOST cost of re-entering an
already-compiled function.  With the linear prologue scan this grew
O(entries) (every cached specialization's prologue ran — and raised — until
one matched); the two-tier keyed cache makes it one key computation + one
dict lookup + one prologue run, so the curve over 1 → 8 → 64 specializations
should be roughly flat.  Host-side measurement only (``host_us_per_call``) —
the tiny computation exists to make the call real, not to be timed.
"""
from __future__ import annotations

import numpy as np

from thunder_tpu.benchmarks.timing import host_us_per_call

__all__ = ["dispatch_overhead_bench"]

_COUNTERS = ("key_hits", "scan_hits", "guard_evictions", "prologue_runs", "key_computations")


def dispatch_overhead_bench(spec_counts: tuple = (1, 8, 64), iters: int = 200) -> dict:
    """For each N in ``spec_counts``: build a fresh jitted function, populate
    N specializations (distinct baked static scalars under CONSTANT_VALUES),
    then measure µs/call of a repeat call against the LAST-compiled
    specialization — the linear scan's worst case, the keyed cache's common
    case.  Returns ``{str(N): {"us_per_call": ..., <dispatch counters>}}``."""
    import thunder_tpu as tt

    x = np.ones((8,), dtype=np.float32)
    results: dict = {}
    for n in spec_counts:
        jfn = tt.jit(lambda a, k: a + float(k))
        for k in range(n):
            jfn(x, k)  # each distinct k bakes a new specialization
        target = n - 1
        us = host_us_per_call(jfn, x, target, iters=iters)
        stats = tt.dispatch_stats(jfn)
        results[str(n)] = {
            "us_per_call": round(us, 3),
            "cached_specializations": stats["cached_specializations"],
            **{c: stats[c] for c in _COUNTERS},
        }
    return results
