"""Mesh-serving benchmark: SPMD continuous batching vs the single-device engine.

The claim under test is ROADMAP item 1's gate: at **equal total batch**, the
mesh engine (params TP-sharded, KV block arena heads-over-``tp``, pjit
bucket programs) must at least match the single-device engine in tokens/sec
on the virtual CPU mesh — the virtual mesh can't show a real-HBM win, so
the bar is "SPMD costs nothing at equal resources" while the *capacity* win
(per-shard arena bytes, a model too big for one chip) is recorded as facts:
``arena_shard_bytes`` vs ``arena_total_bytes`` and the decode collective
census.  Token parity with solo sharded ``generate()`` is asserted inline —
a throughput number from a diverging engine would be meaningless.

Both engines are warmed first (bucket programs land in the module program
cache, keyed by mesh fingerprint), so the measured window is compile-free
for both; the compile counts and bucket bound of the warm mesh engine are
part of the artifact (one compile per (mesh, bucket) is a gated property).

Config note: the tiny-llama architecture at ``n_embd=512`` (vs the
single-device serving bench's 128).  A virtual CPU mesh shares one
machine's cores, so tp=2 cannot show the real-hardware compute win — the
question is where the halved per-device GEMMs running concurrently on two
device threads outweigh the mesh engine's extra per-step cost (a second
device dispatch + the layer collectives).  Measured on the 8-virtual-
device host: 0.83x at width 128, ~0.95x at 256-384 (dispatch-bound), and
consistently >=1.0x from width 512 where compute decides the comparison.
That crossover is a CPU-host artifact of dispatch cost, not a property of
the sharding (on TPU per-step compute dominates at any serving width).
"""
from __future__ import annotations

import time

import jax
import jax.numpy as jnp
import numpy as np


def serving_mesh_bench(on_tpu: bool = False, *, smoke: bool = False, tp: int = 2) -> dict:
    """Returns ``{"results": {...}}`` in the BENCH_MICRO artifact shape."""
    import thunder_tpu as tt
    from thunder_tpu import distributed as dist
    from thunder_tpu.models import generate as gen
    from thunder_tpu.models import llama

    if smoke:
        n_requests, max_new, max_batch, lens = 4, 8, 4, (4, 6, 8)
    else:
        n_requests, max_new, max_batch, lens = 8, 32, 8, (8, 12, 16, 24)
    overrides = dict(n_embd=512, intermediate_size=1376)
    cfg = llama.Config.from_name("tiny-llama-debug", **overrides)
    params = llama.init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    assert len(jax.devices()) >= tp, f"need {tp} devices, have {len(jax.devices())}"
    mesh = dist.make_mesh({"tp": tp}, devices=jax.devices()[:tp])
    rng = np.random.default_rng(0)
    prompts = [
        rng.integers(0, cfg.vocab_size, (lens[i % len(lens)],)).astype(np.int32)
        for i in range(n_requests)
    ]
    reqs = [{"prompt": p, "max_new_tokens": max_new} for p in prompts]
    block_size = 16
    num_blocks = max_batch * (-(-(max(lens) + max_new) // block_size)) + 1

    def make_engine(with_mesh: bool):
        return tt.serve(
            None, params, cfg, block_size=block_size, num_blocks=num_blocks,
            max_batch=max_batch, cache_dtype=jnp.float32,
            mesh=mesh if with_mesh else None,
        )

    def timed_drive(eng):
        t0 = time.perf_counter()
        results = eng.run([dict(r) for r in reqs])
        dt = time.perf_counter() - t0
        return results, sum(len(r.new_tokens) for r in results) / dt

    # warm both paths first: each compiles its programs into the module
    # cache (keyed by mesh fingerprint), so every measured drive below is
    # compile-free
    timed_drive(make_engine(False))
    warm = make_engine(True)
    timed_drive(warm)
    compile_counts = dict(warm.stats()["compile_counts"])
    bucket_bound = warm.stats()["bucket_bound"]
    mesh_facts = warm.stats()["mesh"]

    # interleaved best-of-reps (the tracing-bench methodology): single-shot
    # drives jitter by ~15% on shared CI hosts, which is bigger than the
    # effect under test
    reps = 2 if smoke else 6
    single_tps = mesh_tps = 0.0
    single_results = mesh_results = None
    eng = None
    for _ in range(reps):
        rs, tps = timed_drive(make_engine(False))
        if tps > single_tps:
            single_results, single_tps = rs, tps
        eng = make_engine(True)
        rs, tps = timed_drive(eng)
        if tps > mesh_tps:
            mesh_results, mesh_tps = rs, tps
    stats = eng.stats()
    cold_measured = sum(1 for r in mesh_results if r.prefill_compiled)

    # token parity: mesh-served == single-device-served == solo sharded
    # generate() for every request (the differential guarantee, asserted on
    # the bench config before any throughput number is reported)
    p_tp = dist.tp_fsdp(params, mesh)
    for p, rm, rs in zip(prompts, mesh_results, single_results):
        solo = np.asarray(
            gen.generate(p_tp, p[None], cfg, max_new, cache_dtype=jnp.float32, mesh=mesh)
        )[0]
        np.testing.assert_array_equal(rm.tokens, solo)
        np.testing.assert_array_equal(rs.tokens, solo)

    return {
        "results": {
            "mesh_tokens_per_sec": round(mesh_tps, 1),
            "single_tokens_per_sec": round(single_tps, 1),
            "throughput_ratio": round(mesh_tps / single_tps, 3),
            "mean_batch_occupancy": round(stats["mean_batch_occupancy"], 3),
            "prefill_compiles": compile_counts["prefill"],
            "decode_compiles": compile_counts["decode"],
            "bucket_bound": bucket_bound,
            "cold_compile_prefills_measured": cold_measured,
            "token_parity": True,                  # asserted above
            "mesh_axes": mesh_facts["axes"],
            "mesh_devices": mesh_facts["devices"],
            "arena_shard_bytes": mesh_facts["arena_shard_bytes"],
            "arena_total_bytes": mesh_facts["arena_total_bytes"],
            "collectives_decode": mesh_facts["collectives_decode"],
            "n_requests": n_requests,
            "max_new_tokens": max_new,
            "max_batch": max_batch,
            "config": f"tiny-llama n_embd={cfg.n_embd} n_layer={cfg.n_layer}",
            "smoke": smoke,
        }
    }
