"""Data-parallel serving benchmark: replicated lanes + prefix-affinity
routing vs one engine at equal total occupancy.

The claim under test is the router's reason to exist on a host-bound
fleet: **shape segregation**.  A solo engine serving a mixed population
decodes every row at the batch's WIDEST block-table bucket — 8 long
shared-prefix requests (35 blocks each → the 64-wide table bucket) drag
8 short requests (4 blocks → the 4-wide bucket) up to a 16x wider
gather for every decoded token.  Two replicas behind the
prefix-affinity router segregate the population: the long family
co-locates on one lane (routed there by the routing-history map —
nothing is *resident* yet under burst submission), shorts fill the
other, and each lane decodes at its own narrow bucket.  Same devices,
same total batch slots, same total arena blocks — fewer bytes gathered
per token.  The win is superlinear, not proportional: the solo batch's
dense gather (16 rows × 1024-token cap ≈ 32 MB of K/V per step) falls
out of last-level cache, while the segregated lanes (8×1024 + 8×64)
stay inside it — measured per-step cost is ~21 ms solo vs ~6+1 ms
split, a 3x ideal that survives router/step overhead at ~2.5x.

Workload: 8 long prompts (500 tokens, a shared block-aligned 480-token
prefix, distinct last token) submitted first, then 8 distinct short
prompts (14–16 tokens), all greedy at ``max_new_tokens=48`` — one
steady full-occupancy wave on both sides (no admission churn in the
comparison).  dp: 2 replicas × (max_batch=8, num_blocks=288); solo:
max_batch=16, num_blocks=576 — equal aggregate occupancy and arena
capacity.  All 8 longs fit one replica (8×35 = 280 ≤ 287 usable
blocks), so affinity never has a capacity excuse to spill the family.

Interleaved best-of-3 (solo/dp alternating, best wall time per config)
after one warmup run of each shape; ``num_blocks``/``max_batch`` are not
in the program static key, so the warmup leaves every measured run
compile-free (asserted: 0 cold prefills).  Exact token parity dp-vs-solo
is asserted request-by-request — a throughput win from a diverging
router is meaningless — and the dp run must count routed affinity hits
(the segregation mechanism, not a side effect).
"""
from __future__ import annotations

import time

import jax
import jax.numpy as jnp
import numpy as np


def serving_dp_bench(on_tpu: bool = False, *, smoke: bool = False) -> dict:
    """Returns ``{"results": {...}}`` in the BENCH_MICRO artifact shape."""
    import thunder_tpu as tt
    from thunder_tpu.models import llama

    if smoke:
        n_long, long_len, shared_len = 2, 80, 64
        n_short, short_lens, max_new = 6, (14, 15, 16), 8
        block_size, rep_batch, rep_blocks, rounds = 16, 4, 24, 1
    else:
        n_long, long_len, shared_len = 8, 500, 480
        n_short, short_lens, max_new = 8, (14, 15, 16), 48
        block_size, rep_batch, rep_blocks, rounds = 16, 8, 288, 3
    overrides = dict(n_embd=128, intermediate_size=344)
    cfg = llama.Config.from_name("tiny-llama-debug", **overrides)
    params = llama.init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    rng = np.random.default_rng(0)
    # the long family: one shared block-aligned prefix, distinct tails —
    # the canonical prefix-sharing population (few-shot prompt + question)
    base = rng.integers(0, cfg.vocab_size, (long_len,)).astype(np.int32)
    longs = []
    for i in range(n_long):
        p = base.copy()
        p[shared_len:] = rng.integers(0, cfg.vocab_size, (long_len - shared_len,))
        p[-1] = i + 1
        longs.append(p)
    shorts = [rng.integers(0, cfg.vocab_size, (short_lens[i % len(short_lens)],))
              .astype(np.int32) for i in range(n_short)]
    prompts = longs + shorts                       # longs first (burst FIFO)
    reqs = [{"prompt": p, "max_new_tokens": max_new} for p in prompts]

    def make_engine(dp: bool):
        kw = dict(block_size=block_size, cache_dtype=jnp.float32)
        if dp:
            # 2 lanes at half the slots/blocks each: equal aggregate
            kw.update(replicas=2, max_batch=rep_batch, num_blocks=rep_blocks)
        else:
            kw.update(max_batch=2 * rep_batch, num_blocks=2 * rep_blocks)
        return tt.serve(None, params, cfg, **kw)

    def drive(dp: bool):
        eng = make_engine(dp)
        t0 = time.perf_counter()
        results = eng.run([dict(r) for r in reqs])
        dt = time.perf_counter() - t0
        stats = eng.stats()
        eng.shutdown()
        return results, dt, stats

    # warm both shapes once: every bucket program both configs can reach
    # lands in the module program cache (pool size / max_batch are not in
    # the static key, so the measured runs below pay zero XLA compiles)
    drive(False)
    drive(True)

    solo_best = dp_best = None
    for _ in range(rounds):                        # interleaved best-of-N
        run_s = drive(False)
        run_d = drive(True)
        if solo_best is None or run_s[1] < solo_best[1]:
            solo_best = run_s
        if dp_best is None or run_d[1] < dp_best[1]:
            dp_best = run_d
    solo_results, solo_s, solo_stats = solo_best
    dp_results, dp_s, dp_stats = dp_best

    parity = all(
        np.array_equal(d.tokens, s.tokens)
        for d, s in zip(dp_results, solo_results)
    )
    cold = (sum(1 for r in dp_results if r.prefill_compiled)
            + sum(1 for r in solo_results if r.prefill_compiled))
    n_tokens = sum(len(r.new_tokens) for r in dp_results)
    router = dp_stats["router"]
    per = dp_stats["per_replica"]

    return {
        "results": {
            "solo_tokens_per_sec": round(n_tokens / solo_s, 1),
            "dp_tokens_per_sec": round(n_tokens / dp_s, 1),
            "throughput_ratio": round(solo_s / dp_s, 3),
            "token_parity_exact": bool(parity),
            "replicas": dp_stats["replicas"],
            "routed": router["routed"],
            "affinity_hits": router["affinity_hits"],
            "routed_by_replica": router["routed_by_replica"],
            "imbalance": router["imbalance"],
            "per_replica_decode_steps": [p["decode_steps"] for p in per],
            "per_replica_mean_occupancy": [
                round(p["mean_batch_occupancy"], 3) for p in per
            ],
            "per_replica_free_blocks_low_water": (
                dp_stats["aggregate"]["pool_free_blocks_low_water"]
            ),
            "solo_mean_occupancy": round(solo_stats["mean_batch_occupancy"], 3),
            "decode_compiles": sum(p["compile_counts"]["decode"] for p in per)
            + solo_stats["compile_counts"]["decode"],
            "bucket_bound": solo_stats["bucket_bound"],
            # the measured (steady-state) runs must pay no XLA compile
            "cold_compile_prefills_measured": cold,
            "n_long": n_long,
            "long_prompt_tokens": long_len,
            "shared_prefix_tokens": shared_len,
            "n_short": n_short,
            "max_new_tokens": max_new,
            "config": f"tiny-llama n_embd={cfg.n_embd} n_layer={cfg.n_layer}",
            "smoke": smoke,
        }
    }
