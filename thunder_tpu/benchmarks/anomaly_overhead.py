"""Anomaly-detection overhead microbench: instrumented vs uninstrumented
dispatch on the llama block target.

Anomaly mode (observability/debug.py) is opt-in; when it IS on, its cost is
one ``jnp.isfinite().all()`` reduction + host sync per instrumented symbol.
This bench measures the plain jit vs the anomaly-mode jit of the same llama
forward so ``bench.py anomaly`` can police that (a) disabled anomaly
detection costs nothing (byte-identical program, same code path) and (b)
enabled detection stays proportionate to the debugging value.  The artifact
(``BENCH_ANOMALY.json``) uses the BENCH_MICRO schema.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from thunder_tpu.benchmarks.timing import host_us_per_call

__all__ = ["anomaly_overhead_bench"]


def anomaly_overhead_bench(on_tpu: bool = False, iters: int = 50) -> dict:
    """Returns ``{"shapes": {...}, "results": {...}}`` (the BENCH_MICRO.json
    artifact schema).  Results: µs/call for the plain and anomaly-mode jits
    of the llama block forward, the overhead ratio, the number of
    instrumented (checked) symbols, and the registry's anomaly counter
    (must stay 0 on healthy inputs)."""
    import thunder_tpu as tt
    from thunder_tpu.models import llama
    from thunder_tpu.observability.metrics import registry

    if on_tpu:
        cfg = llama.Config.from_name(
            "Llama-2-7b-hf", n_layer=1, n_embd=2048, n_head=16, intermediate_size=5504
        )
        B, T, dt = 4, 2048, jnp.bfloat16
    else:
        cfg = llama.Config.from_name("tiny-llama-debug")
        B, T, dt = 2, 64, jnp.float32
    T = min(T, cfg.block_size)
    key = jax.random.PRNGKey(0)
    params = llama.init_params(cfg, key, dtype=dt)
    idx = jax.random.randint(jax.random.PRNGKey(1), (B, T), 0, cfg.vocab_size)
    cos, sin = llama.build_rope_cache(cfg, T, dtype=jnp.float32)

    def block_fwd(p, i, c, s):
        return llama.gpt_forward(p, i, c, s, cfg)

    plain = tt.jit(block_fwd)
    anomaly = tt.jit(block_fwd, detect_anomalies=True)

    detected_before = registry().counter("anomaly.detected").value
    results = {
        "block_fwd_plain_us": round(
            host_us_per_call(plain, params, idx, cos, sin, iters=iters), 3
        ),
        "block_fwd_anomaly_us": round(
            host_us_per_call(anomaly, params, idx, cos, sin, iters=iters), 3
        ),
    }
    plain_us = results["block_fwd_plain_us"]
    results["overhead_x"] = (
        round(results["block_fwd_anomaly_us"] / plain_us, 3) if plain_us > 0 else None
    )
    results["checked_symbols"] = sum(
        1
        for b in tt.last_traces(anomaly)[-1].bound_symbols
        if b.sym.name.startswith("_dbg")
    )
    results["anomalies_detected"] = (
        registry().counter("anomaly.detected").value - detected_before
    )
    return {
        "shapes": {
            "cfg": cfg.name,
            "n_layer": cfg.n_layer,
            "B": B,
            "T": T,
            "dtype": jnp.dtype(dt).name,
        },
        "results": results,
    }
