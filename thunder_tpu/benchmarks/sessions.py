"""Stateful-serving benchmark: session re-attach TTFT, preemption latency,
and zero-compile constrained decoding.

Three claims under test, one per subsystem of the stateful serving PR:

**Sessions.**  A turn-2 request whose session KV is resident re-prefills
only the block-unaligned tail, so its TTFT must beat a cold engine
re-prefilling the full history by at least 2x (the gate) — and the
tokens must be bit-identical to the cold run (re-attach rides the
shared-prefix path; the speedup is only comparable because the streams
are exact).

**Priorities.**  Under a pool sized so a high-priority arrival cannot be
funded while a long low-priority request runs, evict-and-resume
preemption bounds the high class's TTFT near its solo latency, where the
FIFO engine makes it wait out the whole low stream — the p95 ratio is
the headline.  The preempted low stream is asserted bit-identical to an
undisturbed run (preemption is a checkpoint, not a restart).

**Constraints.**  Schemas are program *arguments*: after one warmup
request, serving several brand-new constraint automata (different
classes, different allowed sets) must compile exactly zero programs.

All engines are warmed before measurement (bucket programs land in the
module cache), so the measured windows pay zero XLA compiles (gated via
``cold_compile_prefills_measured``).
"""
from __future__ import annotations

import numpy as np


def _p95(xs):
    return float(np.percentile(np.asarray(xs, dtype=np.float64), 95))


def sessions_bench(on_tpu: bool = False, *, smoke: bool = False) -> dict:
    """Returns ``{"results": {...}}`` in the BENCH_MICRO artifact shape."""
    import jax
    import jax.numpy as jnp

    import thunder_tpu as tt
    from thunder_tpu.models import llama
    from thunder_tpu.serving import TokenSetConstraint, sequence_constraint

    if smoke:
        hist_len, tail_len, turn_new, reps = 48, 7, 4, 1
        low_prompt, low_new, high_prompt, high_new, n_high = 16, 16, 8, 3, 2
    else:
        hist_len, tail_len, turn_new, reps = 192, 15, 8, 3
        low_prompt, low_new, high_prompt, high_new, n_high = 32, 48, 16, 4, 3
    overrides = dict(n_embd=128, intermediate_size=344, n_layer=4)
    cfg = llama.Config.from_name("tiny-llama-debug", **overrides)
    params = llama.init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    rng = np.random.default_rng(0)
    V = cfg.padded_vocab_size

    def prompt(n):
        return rng.integers(0, cfg.vocab_size, (n,)).astype(np.int32)

    def make_engine(**kw):
        base = dict(block_size=8, num_blocks=96, max_batch=2,
                    cache_dtype=jnp.float32, batch_buckets=(2,),
                    prefill_buckets=(32, 256))
        base.update(kw)
        return tt.serve(None, params, cfg, **base)

    #
    # 1. sessions: turn-2 TTFT, resident vs cold full-history re-prefill
    #
    warm = make_engine(sessions=True)
    cold = make_engine()

    def turn_cycle(eng, sid, measured):
        """Turn 1 (unmeasured) then turn 2; returns the turn-2 result."""
        p1 = prompt(hist_len)
        kw = dict(session_id=sid) if sid is not None else {}
        r1 = eng.submit(p1, max_new_tokens=turn_new, **kw).result()
        p2 = np.concatenate([p1, np.asarray(r1.new_tokens, np.int32),
                             prompt(tail_len)])
        r2 = eng.submit(p2, max_new_tokens=turn_new, **kw).result()
        if measured is not None:
            measured.append(r2)
        return p2, r2

    # warm both engines through a full two-turn cycle: every bucket shape
    # (full-history prefill, tail re-prefill, decode) lands in the cache
    turn_cycle(warm, "warmup", None)
    turn_cycle(cold, None, None)

    resident_ms, cold_ms, parity, measured = [], [], True, []
    reattach_before = warm.stats()["sessions"]["reattach_hits"]
    for rep in range(reps):
        p2, r2 = turn_cycle(warm, f"chat{rep}", measured)
        resident_ms.append(r2.ttft_s * 1e3)
        rc = cold.submit(p2, max_new_tokens=turn_new).result()
        measured.append(rc)
        cold_ms.append(rc.ttft_s * 1e3)
        parity = parity and (r2.new_tokens == rc.new_tokens)
        assert r2.shared_prefix_blocks > 0, "turn 2 never re-attached"
    reattach_hits = warm.stats()["sessions"]["reattach_hits"] - reattach_before
    warm.shutdown()
    cold.shutdown()

    #
    # 2. priorities: high-class TTFT, evict-and-resume vs FIFO starvation
    #
    def priority_run(priorities):
        # one batch slot: while the low request runs, a high arrival can
        # only get in by evicting it (or, FIFO, by waiting it out)
        kw = dict(num_blocks=13, max_batch=1, batch_buckets=(1,))
        if priorities:
            kw["priorities"] = True
        eng = make_engine(**kw)
        # warm every shape: a solo low-style and high-style request each
        eng.submit(prompt(low_prompt), max_new_tokens=2).result()
        eng.submit(prompt(high_prompt), max_new_tokens=2).result()
        p_low = prompt(low_prompt)
        lkw = dict(priority="low") if priorities else {}
        hkw = dict(priority="high") if priorities else {}
        h_low = eng.submit(p_low, max_new_tokens=low_new, **lkw)
        for _ in range(4):
            eng.step()                    # low is mid-decode, pool committed
        ttfts = []
        for _ in range(n_high):
            r = eng.submit(prompt(high_prompt), max_new_tokens=high_new,
                           **hkw).result()
            ttfts.append(r.ttft_s * 1e3)
        r_low = h_low.result()
        preempted = eng.preempted if priorities else 0
        eng.shutdown()
        return ttfts, r_low, p_low, preempted

    pre_ttfts, pre_low, p_low, preemptions = priority_run(True)
    fifo_ttfts, fifo_low, _, _ = priority_run(False)
    # the preempted-then-resumed low stream must match an undisturbed run
    ref = make_engine(num_blocks=13, max_batch=1, batch_buckets=(1,))
    low_parity = (pre_low.new_tokens
                  == ref.submit(p_low, max_new_tokens=low_new)
                  .result().new_tokens)
    ref.shutdown()

    #
    # 3. constraints: new schemas compile nothing after warmup
    #
    ceng = make_engine(constraints=True)
    ceng.submit(prompt(high_prompt), max_new_tokens=3,
                constraint=TokenSetConstraint(V, [1, 2])).result()
    warm_counts = dict(ceng.compile_counts)
    schemas = [
        TokenSetConstraint(V, [5, 6, 7]),
        sequence_constraint(V, [[3], [4, 5]]),
        sequence_constraint(V, [[9], [10]], cycle=True),
    ]
    for c in schemas:
        r = ceng.submit(prompt(high_prompt), max_new_tokens=3,
                        constraint=c).result()
        measured.append(r)
    new_programs = (sum(ceng.compile_counts.values())
                    - sum(warm_counts.values()))
    ceng.shutdown()

    cold_compiles = sum(1 for r in measured if r.prefill_compiled)

    return {
        "results": {
            "ttft_resident_ms": round(float(np.median(resident_ms)), 3),
            "ttft_cold_ms": round(float(np.median(cold_ms)), 3),
            "ttft_speedup_x": round(
                float(np.median(cold_ms)) / float(np.median(resident_ms)), 2),
            "session_token_parity_exact": bool(parity),
            "reattach_hits": int(reattach_hits),
            "history_tokens": hist_len + turn_new,
            "tail_tokens": tail_len,
            "preempt_p95_ms": round(_p95(pre_ttfts), 3),
            "fifo_p95_ms": round(_p95(fifo_ttfts), 3),
            "preempt_p95_ratio": round(_p95(fifo_ttfts) / _p95(pre_ttfts), 2),
            "preemptions": int(preemptions),
            "preempt_token_parity_exact": bool(low_parity),
            "constrained_new_programs": int(new_programs),
            "constrained_schemas_tried": len(schemas),
            "cold_compile_prefills_measured": int(cold_compiles),
            "config": f"tiny-llama n_embd={cfg.n_embd} n_layer={cfg.n_layer}",
            "smoke": smoke,
        }
    }
