"""Tunnel-proof timing primitives shared by bench.py and the benchmark
library.

On the tunneled axon TPU backend ``jax.block_until_ready`` returns without
waiting (measured round 3: a B=8 H=32 T=2048 SDPA "completed" in 50 µs —
20× the chip's peak FLOPS).  Only a real device→host transfer round-trips,
so every timing loop here ends with a one-element fetch (``sync``) and the
measured fetch-floor latency (~84 ms over axon, ~µs locally) is subtracted.
"""
from __future__ import annotations

import sys
import time

import jax
import jax.numpy as jnp

__all__ = ["sync", "fetch_floor", "time_fn", "best_ms", "reset_floor", "host_us_per_call"]

_FETCH_FLOOR: float | None = None


def sync(x) -> float:
    """Force execution by fetching one element to the host.  Execution is
    in-order per device, so fetching the last output fences the whole
    preceding dispatch stream."""
    leaf = next(l for l in jax.tree_util.tree_leaves(x) if hasattr(l, "dtype"))
    return float(jnp.reshape(leaf, (-1,))[0].astype(jnp.float32))


def fetch_floor() -> float:
    """Median cost of a tiny compute+fetch — the tunnel round-trip latency,
    memoized (subtracted from loop times)."""
    global _FETCH_FLOOR
    if _FETCH_FLOOR is None:
        xs = jnp.zeros((8,), jnp.float32)
        sync(xs + 1.0)
        ts = []
        for i in range(5):
            t0 = time.perf_counter()
            sync(xs + float(i))
            ts.append(time.perf_counter() - t0)
        _FETCH_FLOOR = sorted(ts)[len(ts) // 2]
    return _FETCH_FLOOR


def reset_floor() -> None:
    """Drop the memoized floor (backend switch in one process)."""
    global _FETCH_FLOOR
    _FETCH_FLOOR = None


def time_fn(fn, *args, iters: int = 20) -> float:
    """Seconds per call, fetch-fenced; NaN when fetch-floor jitter swamps
    the signal even at the max iteration count."""
    out = fn(*args)
    sync(out)  # compile + warm
    floor = fetch_floor()
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    sync(out)
    dt = time.perf_counter() - t0 - floor
    per = max(dt / iters, 1e-9)
    if dt < 5 * floor:  # fetch floor dominates: redo with enough iterations
        iters = min(max(iters, int(10 * floor / per)), 2000)
        t0 = time.perf_counter()
        for _ in range(iters):
            out = fn(*args)
        sync(out)
        dt = time.perf_counter() - t0 - floor
        if dt < 0.5 * floor:  # fetch-floor jitter swamped the signal even at max iters
            print(
                f"time_fn: measurement unreliable (loop {dt*1e3:.1f} ms vs floor "
                f"{floor*1e3:.1f} ms at {iters} iters)",
                file=sys.stderr, flush=True,
            )
            return float("nan")
        per = max(dt / iters, 1e-9)
    return per


def host_us_per_call(fn, *args, iters: int = 200) -> float:
    """Mean host-side wall time per call in µs.  For dispatch-overhead
    measurements, where the cost under test is the HOST work before the
    program launches (key computation, prologue guards, framework plumbing)
    — no device fence, so use ``time_fn`` for anything device-dominated."""
    fn(*args)  # warm: compile/caches populated outside the timed loop
    t0 = time.perf_counter()
    for _ in range(iters):
        fn(*args)
    return (time.perf_counter() - t0) / iters * 1e6


def best_ms(fn, *args, reps: int = 3) -> float:
    """Best-of-reps wall time in ms — rides out tunnel cold-start drift.
    NaN (unreliable) reps are dropped; all-NaN returns NaN."""
    vals = [v for v in (time_fn(fn, *args) for _ in range(reps)) if v == v]
    return min(vals) * 1e3 if vals else float("nan")
