"""Speculative-serving benchmark: tokens/sec at occupancy 8, spec vs plain.

The claim under test is the speculative lane's reason to exist: when the
draft proposes well, one draft-decode dispatch plus ONE (K+1)-position
verify dispatch replaces K+1 sequential decode dispatches — per-token
dispatch/step overhead amortizes by the acceptance length.  On CPU the
tiny-model decode step is dispatch-bound, which is exactly the regime the
TPU serving loop lives in (host step latency dominating a small-batch
decode), so the measured ratio exercises the real mechanism: fewer
round-trips per emitted token.

Workload: a high-acceptance draft/target pair built from ONE parameter
set — the target is 4 layers with layers 1..3 made residual no-ops
(``attn.wo`` and ``mlp.proj`` zeroed), the draft is the 1-layer prefix of
the same weights, so draft logits equal target logits and greedy
acceptance is 100% while the target still pays 4 layers of compute.  This
is the benchmark analogue of a well-distilled draft (acceptance ~1), and
it keeps parity honest: greedy spec serving must equal the plain engine's
tokens bit-for-bit REGARDLESS of acceptance, which is asserted
request-by-request.

At occupancy 8 both engines serve the same 8 requests; both are warmed
first so the measured windows are compile-free (asserted).  The gated
metric is ``speedup_x`` = spec tokens/sec over plain tokens/sec
(``tools.bench_targets.check_serving_spec_targets``, floor 1.2x), plus
the acceptance-rate/accept-length histogram the lane's observability
reports.
"""
from __future__ import annotations

import time

import jax
import jax.numpy as jnp
import numpy as np


def _high_acceptance_pair(cfg, dcfg, key):
    """One weight set, two models: 4-layer target whose layers 1..3 are
    residual no-ops, and its 1-layer prefix as the draft — bit-equal
    logits, 4x compute asymmetry."""
    from thunder_tpu.models import llama

    params = llama.init_params(cfg, key, dtype=jnp.float32)
    for blk in params["blocks"][1:]:
        blk["attn"]["wo"] = jnp.zeros_like(blk["attn"]["wo"])
        blk["mlp"]["proj"] = jnp.zeros_like(blk["mlp"]["proj"])
    draft_params = {
        "wte": params["wte"],
        "blocks": params["blocks"][:dcfg.n_layer],
        "ln_f": params["ln_f"],
        "lm_head": params["lm_head"],
    }
    return params, draft_params


def serving_spec_bench(on_tpu: bool = False, *, smoke: bool = False) -> dict:
    """Returns ``{"results": {...}}`` in the BENCH_MICRO artifact shape."""
    import thunder_tpu as tt
    from thunder_tpu.models import llama
    from thunder_tpu.serving import SpecConfig

    K = 4
    if smoke:
        n_req, prompt_len, max_new, max_batch, block_size = 4, 8, 10, 4, 8
        overrides = dict(n_embd=128, intermediate_size=344, n_layer=4)
    else:
        n_req, prompt_len, max_new, max_batch, block_size = 8, 16, 64, 8, 8
        overrides = dict(n_embd=128, intermediate_size=344, n_layer=4)
    cfg = llama.Config.from_name("tiny-llama-debug", **overrides)
    dcfg = llama.Config.from_name("tiny-llama-debug", **{**overrides, "n_layer": 1})
    params, draft_params = _high_acceptance_pair(cfg, dcfg, jax.random.PRNGKey(0))

    rng = np.random.default_rng(0)
    prompts = [rng.integers(0, cfg.vocab_size, (prompt_len,)).astype(np.int32)
               for _ in range(n_req)]
    reqs = [{"prompt": p, "max_new_tokens": max_new} for p in prompts]
    per_req = -(-(prompt_len + max_new + K) // block_size)
    num_blocks = n_req * per_req + per_req + 1

    def make_engine(spec: bool):
        kw = dict(block_size=block_size, num_blocks=num_blocks,
                  max_batch=max_batch, cache_dtype=jnp.float32,
                  batch_buckets=(max_batch,))
        if spec:
            kw["speculative"] = SpecConfig(draft_params, dcfg, K=K)
        return tt.serve(None, params, cfg, **kw)

    def drive(spec: bool):
        eng = make_engine(spec)
        t0 = time.perf_counter()
        results = eng.run([dict(r) for r in reqs])
        dt = time.perf_counter() - t0
        return eng, results, dt

    # warm both engines: bucket programs land in the module cache, so the
    # measured engines pay zero XLA compiles (asserted via prefill_compiled
    # and the gate's cold-compile check)
    for mode in (False, True):
        drive(mode)

    plain_eng, plain_results, plain_s = drive(False)
    spec_eng, spec_results, spec_s = drive(True)

    parity = all(
        np.array_equal(a.tokens, b.tokens)
        for a, b in zip(spec_results, plain_results)
    )
    cold = (sum(1 for r in spec_results if r.prefill_compiled)
            + sum(1 for r in plain_results if r.prefill_compiled))
    n_tokens = sum(len(r.new_tokens) for r in spec_results)
    stats = spec_eng.stats()
    sp = stats["spec"]
    plain_tps = n_tokens / plain_s
    spec_tps = n_tokens / spec_s

    return {
        "results": {
            "plain_tokens_per_sec": round(plain_tps, 1),
            "spec_tokens_per_sec": round(spec_tps, 1),
            "speedup_x": round(spec_tps / plain_tps, 3),
            "K": K,
            "acceptance_rate": round(sp["acceptance_rate"], 4),
            "accept_len_hist": {str(k): v for k, v in sp["accept_len_hist"].items()},
            "tokens_per_round": round(sp["tokens_per_round"], 3),
            "spec_rounds": sp["rounds"],
            "token_parity_exact": bool(parity),
            "mean_batch_occupancy": round(stats["mean_batch_occupancy"], 3),
            "draft_decode_compiles": stats["compile_counts"]["draft_decode"],
            "verify_compiles": (stats["compile_counts"]["verify"]
                                + stats["compile_counts"]["verify_paged"]),
            "spec_prefill_compiles": stats["compile_counts"]["spec_prefill"],
            "decode_compiles": stats["compile_counts"]["decode"],
            "bucket_bound": stats["bucket_bound"],
            "cold_compile_prefills_measured": cold,
            "n_requests": n_req,
            "prompt_tokens": prompt_len,
            "max_new_tokens": max_new,
            "config": f"tiny-llama n_embd={cfg.n_embd} n_layer={cfg.n_layer} "
                      f"draft_n_layer={dcfg.n_layer}",
            "smoke": smoke,
        }
    }
