"""Ragged paged decode + paged chunk-prefill bench (`bench.py ragged`).

Four claims, one artifact (BENCH_RAGGED.json):

1. **Blocks walked vs real** — the headline: on a mixed short/long cohort
   sharing one decode bucket, the compiled grid "walks" ``Bb x nbb`` blocks
   per step but the ragged clamp streams only each request's actual block
   count.  The goodput ledger records both integers per dispatch
   (position math, fully deterministic), and the walked/real ratio is
   gated ≥ 2x at the committed cohort — the bucket tax the ragged kernel
   stops paying.
2. **Token parity** — gated on every backend: the ragged paged engine and
   the chunked paged-prefill engine serve tokens bit-identical to their
   gather twins over the same workloads.
3. **Chunk arena traffic** — the *why* of ``prefill_chunk_paged``: the
   gather chunk round-trips the whole bucketed dense cache per piece
   (arena→dense gather, dense re-write, full-arena scatter copy under
   donation) where the paged chunk reads table blocks once and writes only
   the chunk's blocks.  Byte counts are analytic (static shapes), the
   ratio is gated > 1.
4. **Program identity** — raggedness is data and the chunk kind swaps 1:1
   for the gather chunk kind, so a warm engine compiles ZERO new programs
   and the compile count stays inside the engine's own bucket bound.

Wall-clock is recorded but informational: on CPU the kernels run in Pallas
interpret mode, so throughput claims wait for a real TPU window.
"""
from __future__ import annotations

import time

import jax
import jax.numpy as jnp
import numpy as np


def ragged_bench(on_tpu: bool = False, *, smoke: bool = False) -> dict:
    """Returns ``{"shapes": ..., "results": ...}`` in the BENCH_MICRO
    artifact shape.  ``smoke=True`` shrinks the cohort (3x16 + 1x64-token,
    block_size 4) for CI; the committed artifact uses the full
    6x64 + 2x1024-token occupancy-8 cohort at block_size 16."""
    import thunder_tpu as tt
    from thunder_tpu.models import llama

    if smoke:
        bs, nbb, Bb = 4, 18, 4
        short_len, long_len, n_short, n_long = 16, 64, 3, 1
        prefill_buckets, chunk = (16, 64), 16
        chunk_long = 32
        num_blocks, max_new, seq_cap = 64, 6, 128
    else:
        bs, nbb, Bb = 16, 66, 8
        short_len, long_len, n_short, n_long = 64, 1024, 6, 2
        prefill_buckets, chunk = (64, 1024), 64
        chunk_long = 256
        num_blocks, max_new, seq_cap = 192, 8, 1152

    cfg = llama.Config.from_name(
        "tiny-llama-debug",
        n_layer=2, n_head=4, n_query_groups=2, n_embd=32,
        intermediate_size=64, vocab_size=64, block_size=seq_cap,
    )
    params = llama.init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    rng = np.random.default_rng(0)
    prompts = (
        [rng.integers(0, cfg.vocab_size, (short_len,)).astype(np.int32)
         for _ in range(n_short)]
        + [rng.integers(0, cfg.vocab_size, (long_len,)).astype(np.int32)
           for _ in range(n_long)]
    )
    base_kw = dict(block_size=bs, num_blocks=num_blocks, max_batch=Bb,
                   cache_dtype=jnp.float32, batch_buckets=(Bb,),
                   block_buckets=(nbb,), prefill_buckets=prefill_buckets)

    def drive(attn, reqs, **extra_kw):
        eng = tt.serve(None, params, cfg, attn=attn, **base_kw, **extra_kw)
        hs = [eng.submit(p, max_new_tokens=max_new) for p in reqs]
        t0 = time.perf_counter()
        eng.drain()
        dt = time.perf_counter() - t0
        return [tuple(h.result(drive=False).tokens) for h in hs], dt, eng

    # 1+2: mixed cohort, ragged ledger off the paged engine, parity vs gather
    toks_g, gather_s, _ = drive("gather", prompts)
    toks_p, paged_s, eng_p = drive("paged", prompts, goodput=True)
    parity_ok = toks_g == toks_p
    tokens_checked = sum(len(t) for t in toks_g)
    blk = eng_p.stats()["goodput"]["blocks"]
    walked, real = blk["walked"], blk["real"]
    per_kind = eng_p.goodput_report().get("blocks_per_kind", {})
    decode_dispatches = sum(
        row["dispatches"] for k, row in per_kind.items() if k.startswith("decode"))

    # 2 again: chunked prefill, paged chunk vs gather chunk
    chunk_kw = dict(prefill_chunk=chunk)
    chunk_prompts = [prompts[0],
                     rng.integers(0, cfg.vocab_size,
                                  (chunk_long,)).astype(np.int32)]
    ctoks_g, _, _ = drive("gather", chunk_prompts, **chunk_kw)
    ctoks_p, _, eng_c = drive("paged", chunk_prompts, **chunk_kw)
    chunk_parity_ok = ctoks_g == ctoks_p
    chunk_st = eng_c.stats()["attn"]["kinds"]["prefill_chunk"]

    # 4: a warm engine (identical config, module program cache already
    # carries every program) must compile nothing
    toks_w, _, eng_w = drive("paged", prompts, goodput=True)
    warm_new_programs = sum(eng_w.stats()["compile_counts"].values())
    warm_parity_ok = toks_w == toks_p
    bucket_bound = eng_p.stats()["bucket_bound"]
    compiles_total = sum(eng_p.stats()["compile_counts"].values())

    # 3: analytic per-chunk-piece arena traffic (static shapes, f32).
    # gather chunk: arena->dense gather (K+V), the dense re-write inside
    # attention, and the scatter's full-arena copy under donation; paged
    # chunk: the kernel reads each table block once (bounded by the dense
    # cache) and writes only the chunk's own blocks.
    L, ng, hd = cfg.n_layer, cfg.n_query_groups, cfg.head_size
    itm = 4
    dense_elems = nbb * bs * L * ng * hd          # one K or V dense cache
    arena_elems = num_blocks * bs * L * ng * hd   # one whole arena
    chunk_elems = chunk * L * ng * hd             # the piece's own tokens
    gather_chunk_bytes = 2 * itm * (3 * dense_elems + arena_elems)
    paged_chunk_bytes = 2 * itm * (dense_elems + chunk_elems)
    chunk_ratio = gather_chunk_bytes / paged_chunk_bytes

    return {
        "shapes": {
            "cfg": "tiny-llama-debug(2L,4h,2g)",
            "cohort": f"{n_short}x{short_len} + {n_long}x{long_len} tokens",
            "max_new_tokens": max_new, "bucket": [Bb, nbb], "block_size": bs,
            "prefill_chunk": chunk, "chunk_prompt": chunk_long,
        },
        "results": {
            **({"smoke": True} if smoke else {}),
            "parity_ok": bool(parity_ok),
            "tokens_checked": int(tokens_checked),
            "blocks_walked": int(walked),
            "blocks_real": int(real),
            "blocks_ratio_x": round(walked / max(real, 1), 3),
            "decode_dispatches": int(decode_dispatches),
            "chunk_parity_ok": bool(chunk_parity_ok),
            "chunk_attn_mode": chunk_st["mode"],
            "chunk_kernel_steps": int(chunk_st["kernel_steps"]),
            "gather_chunk_bytes_per_piece": int(gather_chunk_bytes),
            "paged_chunk_bytes_per_piece": int(paged_chunk_bytes),
            "chunk_traffic_ratio_x": round(chunk_ratio, 3),
            "warm_engine_new_programs": int(warm_new_programs),
            "warm_parity_ok": bool(warm_parity_ok),
            "bucket_bound": int(bucket_bound),
            "compiles_total": int(compiles_total),
            "drive_gather_ms": round(gather_s * 1e3, 3),
            "drive_paged_ms": round(paged_s * 1e3, 3),
        },
    }
