"""Async-engine serving benchmark: TTFT under long-prompt contention.

The claim under test is the async core's reason to exist: with long
prompts in the admission wave, short requests' TTFT must no longer pay for
whole long prefills.  The synchronous engine admits and prefills each
request back-to-back, host-blocked — a short request admitted behind two
256-token prompts waits out both full prefills before its own first token.
The async engine (``async_step=True`` + ``prefill_chunk``) dispatches long
prompts as chunks and defers every materialization, so the short cohort's
first tokens arrive after only one chunk per long plus their own prefills.

Workload: at occupancy 8 (the committed BENCH_SERVING.json operating
point), 2 long prompts are submitted first and 6 short prompts behind them
— strict FIFO admits all 8 into one wave, so every short pays maximal
contention.  The gated metric is the **short-cohort TTFT p95** ratio
sync/async (the long requests' own TTFT is a different trade: chunking
spreads their prefill across steps by design, buying the batch's TPOT).
Exact token parity between the two engines is asserted request-by-request
— a latency win from a diverging engine is meaningless — and the compiled
program count must stay inside the chunk-extended bucket bound.

Config note: tiny-llama at ``n_embd=128`` (the BENCH_SERVING.json width,
where CPU compute beats dispatch); both engines are warmed to steady state
first so the measured windows are compile-free.
"""
from __future__ import annotations

import time

import jax
import jax.numpy as jnp
import numpy as np


def serving_async_bench(on_tpu: bool = False, *, smoke: bool = False) -> dict:
    """Returns ``{"results": {...}}`` in the BENCH_MICRO artifact shape."""
    import thunder_tpu as tt
    from thunder_tpu.models import llama

    if smoke:
        n_long, long_len, n_short, short_lens = 1, 64, 3, (6, 8, 10)
        max_new, max_batch, chunk, block_size = 6, 4, 16, 8
        overrides = dict(n_embd=128, intermediate_size=344)
    else:
        n_long, long_len, n_short, short_lens = 2, 512, 6, (8, 10, 12, 14, 16, 12)
        max_new, max_batch, chunk, block_size = 16, 8, 64, 16
        overrides = dict(n_embd=128, intermediate_size=344)
    cfg = llama.Config.from_name("tiny-llama-debug", **overrides)
    params = llama.init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    rng = np.random.default_rng(0)
    longs = [rng.integers(0, cfg.vocab_size, (long_len,)).astype(np.int32)
             for _ in range(n_long)]
    shorts = [rng.integers(0, cfg.vocab_size, (short_lens[i % len(short_lens)],))
              .astype(np.int32) for i in range(n_short)]
    # longs first: strict FIFO puts every short behind every long prefill
    prompts = longs + shorts
    reqs = [{"prompt": p, "max_new_tokens": max_new} for p in prompts]
    per_req = max(-(-(long_len + max_new) // block_size),
                  -(-(max(len(s) for s in shorts) + max_new) // block_size))
    num_blocks = (n_long * (-(-(long_len + max_new) // block_size))
                  + n_short * (-(-(max(len(s) for s in shorts) + max_new) // block_size))
                  + per_req + 1)

    def make_engine(async_step: bool):
        kw = dict(block_size=block_size, num_blocks=num_blocks,
                  max_batch=max_batch, cache_dtype=jnp.float32)
        if async_step:
            kw["prefill_chunk"] = chunk
        else:
            kw["async_step"] = False
        return tt.serve(None, params, cfg, **kw)

    def drive(async_step: bool):
        eng = make_engine(async_step)
        t0 = time.perf_counter()
        results = eng.run([dict(r) for r in reqs])
        dt = time.perf_counter() - t0
        return eng, results, dt

    # warm both engines: the bucket programs land in the module cache, so
    # the measured engines below pay zero XLA compiles (asserted)
    for mode in (False, True):
        drive(mode)

    sync_eng, sync_results, sync_s = drive(False)
    async_eng, async_results, async_s = drive(True)

    parity = all(
        np.array_equal(a.tokens, s.tokens)
        for a, s in zip(async_results, sync_results)
    )
    cold_async = sum(1 for r in async_results if r.prefill_compiled)
    cold_sync = sum(1 for r in sync_results if r.prefill_compiled)

    def short_ttft_p95(results):
        ttfts = sorted(r.ttft_s for r in results[n_long:])
        return float(np.percentile(ttfts, 95))

    sync_p95 = short_ttft_p95(sync_results)
    async_p95 = short_ttft_p95(async_results)
    stats = async_eng.stats()
    n_tokens = sum(len(r.new_tokens) for r in async_results)

    return {
        "results": {
            "sync_short_ttft_p95_s": round(sync_p95, 6),
            "async_short_ttft_p95_s": round(async_p95, 6),
            "ttft_p95_improvement_x": round(sync_p95 / async_p95, 3),
            "sync_tokens_per_sec": round(n_tokens / sync_s, 1),
            "async_tokens_per_sec": round(n_tokens / async_s, 1),
            "throughput_ratio": round(sync_s / async_s, 3),
            "token_parity_exact": bool(parity),
            "mean_batch_occupancy": round(stats["mean_batch_occupancy"], 3),
            "overlap_frac_mean": round(stats["overlap_frac_mean"], 3),
            "decode_stall_s_mean": round(stats["decode_stall_s_mean"], 6),
            "chunk_runs": stats["chunk_runs"],
            "prefill_compiles": stats["compile_counts"]["prefill"],
            "prefill_chunk_compiles": stats["compile_counts"]["prefill_chunk"],
            "decode_compiles": stats["compile_counts"]["decode"],
            "bucket_bound": stats["bucket_bound"],
            # the measured (steady-state) engines must pay no XLA compile:
            # their TTFT percentiles are compile-free by construction
            "cold_compile_prefills_measured": cold_async + cold_sync,
            "n_long": n_long,
            "long_prompt_tokens": long_len,
            "n_short": n_short,
            "prefill_chunk": chunk,
            "max_new_tokens": max_new,
            "config": f"tiny-llama n_embd={cfg.n_embd} n_layer={cfg.n_layer}",
            "smoke": smoke,
        }
    }
