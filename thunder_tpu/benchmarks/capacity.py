"""Multi-tenant capacity benchmark: int8 KV pool + per-request LoRA.

Two claims under test, both from ROADMAP item 5:

1. **Admitted concurrency at fixed arena bytes** — the reason int8 block
   storage exists.  Two engines get the *same arena byte budget*; the
   baseline stores blocks at float32 (the compute dtype of the CPU bench,
   and what the parity contract is tested against), the quantized engine
   at int8 + per-slot-per-head float32 scales.  The int8 pool affords
   ``hs*4/(hs+4)`` = 3.2x the blocks at ``hs=16``, which must show up as
   >= 3x the *measured* peak of concurrently resident requests under an
   identical request flood — with exact greedy token parity against the
   full-precision engine (argmax margins dominate the ~1e-2 quantization
   noise at these shapes; the measured ``serving.kv_quant.rel_err`` is
   recorded in the artifact).

2. **Adapter-mix overhead** — one engine serving several LoRA tenants out
   of one base model must not recompile per adapter: a drive mixing >= 3
   distinct adapter_ids in one batch stays inside the (bucket,
   registry-geometry) program set, registering a NEW adapter afterwards
   compiles zero fresh programs, and the tokens/sec cost of the in-step
   low-rank deltas is recorded as ``adapter_mix_overhead_x``.

Config note: the tiny-llama-debug architecture (hs=16) keeps the run
CPU-fast; the capacity ratio is a *bytes* property and transfers to real
widths unchanged (it grows with hs — 3.76x at hs=64).
"""
from __future__ import annotations

import time

import jax
import jax.numpy as jnp
import numpy as np


def _drive_peak(eng, reqs):
    """Submits everything, then steps to completion recording the peak
    number of concurrently resident (running) requests and the peak count
    of distinct adapter slots sharing one decode batch."""
    handles = [eng.submit(**r) for r in reqs]
    peak = 0
    peak_distinct = 0
    while eng.scheduler.queue or eng.scheduler.running:
        running = eng.scheduler.running
        peak = max(peak, len(running))
        # distinct adapter_ids (slot 0 is the base model, not a tenant)
        peak_distinct = max(
            peak_distinct, len({r.adapter_slot for r in running if r.adapter_slot})
        )
        if not eng.step():
            break
    return handles, peak, peak_distinct


def capacity_bench(on_tpu: bool = False, *, smoke: bool = False) -> dict:
    """Returns ``{"results": {...}}`` in the BENCH_MICRO artifact shape."""
    import thunder_tpu as tt
    from thunder_tpu.models import llama
    from thunder_tpu.serving import (
        AdapterRegistry,
        arena_block_bytes,
        blocks_for_arena_bytes,
        make_lora_factors,
    )

    cfg = llama.Config.from_name("tiny-llama-debug")          # hs=16, ng=2, L=2
    params = llama.init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    block_size = 4
    prompt_len, max_new = 8, 8                                 # 16 tokens = 4 blocks
    n_flood = 16 if smoke else 32
    base_usable = 16 if smoke else 32                          # baseline resident blocks

    # -- equal arena-byte budget → two pool sizes
    f32_bb = arena_block_bytes(cfg, block_size, jnp.float32)
    int8_bb = arena_block_bytes(cfg, block_size, jnp.float32, kv_dtype="int8")
    budget = (base_usable + 1) * f32_bb                        # + the sink block
    base_blocks = blocks_for_arena_bytes(cfg, block_size, budget, jnp.float32)
    int8_blocks = blocks_for_arena_bytes(cfg, block_size, budget, jnp.float32,
                                         kv_dtype="int8")

    rng = np.random.default_rng(0)
    prompts = [rng.integers(0, cfg.vocab_size, (prompt_len,)).astype(np.int32)
               for _ in range(n_flood)]
    reqs = [{"prompt": p, "max_new_tokens": max_new} for p in prompts]

    def make_engine(num_blocks, **kw):
        return tt.serve(
            None, params, cfg, block_size=block_size, num_blocks=num_blocks,
            max_batch=n_flood, max_queue=2 * n_flood, cache_dtype=jnp.float32, **kw,
        )

    base_eng = make_engine(base_blocks)
    _, base_peak, _ = _drive_peak(base_eng, [dict(r) for r in reqs])
    int8_eng = make_engine(int8_blocks, kv_dtype="int8")
    _, int8_peak, _ = _drive_peak(int8_eng, [dict(r) for r in reqs])
    int8_stats = int8_eng.stats()
    snap = tt.metrics_snapshot()
    rel_err = snap.get("serving.kv_quant.rel_err", 0.0)

    # -- exact greedy token parity: int8 cache vs the f32 cache, same seeds
    par_prompts = [rng.integers(0, cfg.vocab_size, (n,)).astype(np.int32)
                   for n in (3, 6, 9, 13)]
    par_reqs = [{"prompt": p, "max_new_tokens": 6} for p in par_prompts]
    f32_tokens = make_engine(64).run([dict(r) for r in par_reqs])
    int8_tokens = make_engine(64, kv_dtype="int8").run([dict(r) for r in par_reqs])
    parity = all(
        np.array_equal(a.tokens, b.tokens) for a, b in zip(f32_tokens, int8_tokens)
    )

    # -- adapter mix: >= 3 distinct tenants in one batch, zero per-adapter
    #    compiles, measured tokens/sec overhead of the in-step deltas
    mix_batch = 4 if smoke else 8
    mix_new = 8 if smoke else 16
    registry = AdapterRegistry(cfg, rank=4, max_adapters=6)
    for i, name in enumerate(("tenant-a", "tenant-b", "tenant-c")):
        registry.register(name, make_lora_factors(cfg, 4, jax.random.PRNGKey(10 + i),
                                                  std=0.5))
    ids = ["tenant-a", "tenant-b", "tenant-c", None]
    mix_prompts = [rng.integers(0, cfg.vocab_size, (prompt_len,)).astype(np.int32)
                   for _ in range(mix_batch)]
    mix_reqs = [
        {"prompt": p, "max_new_tokens": mix_new, "adapter_id": ids[i % len(ids)]}
        for i, p in enumerate(mix_prompts)
    ]
    base_reqs = [{"prompt": p, "max_new_tokens": mix_new} for p in mix_prompts]

    def make_mix_engine(**kw):
        return tt.serve(
            None, params, cfg, block_size=block_size,
            num_blocks=mix_batch * ((prompt_len + mix_new) // block_size) + 1,
            max_batch=mix_batch, cache_dtype=jnp.float32, **kw,
        )

    # warm both program sets, then measure steady-state drives
    make_mix_engine().run([dict(r) for r in base_reqs])
    warm = make_mix_engine(lora=registry)
    _, _, warm_distinct = _drive_peak(warm, [dict(r) for r in mix_reqs])
    # ...including the solo (batch-bucket-1) shape the post-register probe
    # uses, so that probe isolates adapter identity from bucket coverage
    warm.run([{"prompt": mix_prompts[0], "max_new_tokens": mix_new,
               "adapter_id": "tenant-a"}])

    eng_b = make_mix_engine()
    t0 = time.perf_counter()
    rb = eng_b.run([dict(r) for r in base_reqs])
    base_s = time.perf_counter() - t0
    base_tps = sum(len(r.new_tokens) for r in rb) / base_s

    eng_m = make_mix_engine(lora=registry)
    t0 = time.perf_counter()
    handles, _, mix_distinct = _drive_peak(eng_m, [dict(r) for r in mix_reqs])
    mix_s = time.perf_counter() - t0
    rm = [h.result(drive=False) for h in handles]
    mix_tps = sum(len(r.new_tokens) for r in rm) / mix_s

    # registering a NEW adapter is a data write: zero fresh programs
    registry.register("tenant-d", make_lora_factors(cfg, 4, jax.random.PRNGKey(99),
                                                    std=0.5))
    post = make_mix_engine(lora=registry)
    post.run([{"prompt": mix_prompts[0], "max_new_tokens": mix_new,
               "adapter_id": "tenant-d"}])
    post_compiles = sum(post.stats()["compile_counts"].values())

    return {
        "results": {
            "baseline_dtype": "float32",
            "kv_dtype": "int8",
            "arena_budget_bytes": budget,
            "f32_block_bytes": f32_bb,
            "int8_block_bytes": int8_bb,
            "baseline_num_blocks": base_blocks,
            "int8_num_blocks": int8_blocks,
            "blocks_per_request": (prompt_len + max_new) // block_size,
            "baseline_admitted_peak": base_peak,
            "int8_admitted_peak": int8_peak,
            "admitted_ratio": round(int8_peak / base_peak, 3),
            "token_parity_exact": bool(parity),
            "kv_quant_rel_err": round(float(rel_err), 6),
            "prefill_compiles": int8_stats["compile_counts"]["prefill"],
            "decode_compiles": int8_stats["compile_counts"]["decode"],
            "bucket_bound": int8_stats["bucket_bound"],
            "base_tokens_per_sec": round(base_tps, 1),
            "adapter_mix_tokens_per_sec": round(mix_tps, 1),
            "adapter_mix_overhead_x": round(base_tps / mix_tps, 3) if mix_tps else None,
            "adapter_mix_max_distinct": max(warm_distinct, mix_distinct),
            "adapter_mix_new_programs_after_register": post_compiles,
            "lora_rank": 4,
            "lora_slots": registry.max_adapters,
            "config": f"tiny-llama-debug hs={cfg.head_size} n_layer={cfg.n_layer}",
            "smoke": smoke,
        }
    }
