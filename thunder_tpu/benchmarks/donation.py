"""Buffer-donation microbench: transformer-block train step with donation
on/off.

Two measurements on a llama-block train step (``tt.value_and_grad`` of the
block loss + a compiled optimizer update):

1. **Peak-bytes delta** — the optimizer update is the canonical donation
   target (``new_p = p - lr*g``: every input dies, every output is
   shape/dtype-compatible with a dead input).  With donation off the del-aware
   estimate must hold params + grads + new params live at the peak (~3N);
   with donation on the update writes into the donated buffers (~2N).  The
   estimate comes from ``examine.memory_timeline`` (donation-aware since this
   PR), which is exact about what XLA is ALLOWED to reuse — the in-container
   CPU backend has no real donation to measure against.

2. **steps/sec + dispatch cost** — the same step timed with donation on, off
   (``donate=False``), and unspecified (the plain path).  ``donate=False``
   must cost the same as plain: the pass never runs and the program is
   byte-identical, so the dispatch-ns ratio between the two is the
   CI-policed "donation overhead" number (``tools/bench_targets.py``).

The artifact (``BENCH_DONATION.json``) uses the BENCH_MICRO schema.
"""
from __future__ import annotations

import time

import jax
import jax.numpy as jnp

from thunder_tpu.benchmarks.timing import host_us_per_call

__all__ = ["donation_bench"]


def donation_bench(on_tpu: bool = False, iters: int = 20) -> dict:
    """Returns ``{"shapes": {...}, "results": {...}}``.  Results: µs/call and
    steps/sec for the donated / undonated / plain train step, the donation
    pass's own accounting (buffers/bytes donated, aliases), the peak-bytes
    estimates of the update program with donation on vs off, and the
    donate=False-vs-plain dispatch ratio."""
    import thunder_tpu as tt
    from thunder_tpu.examine import memory_timeline
    from thunder_tpu.models import llama
    from thunder_tpu.observability.metrics import registry

    if on_tpu:
        cfg = llama.Config.from_name(
            "Llama-2-7b-hf", n_layer=1, n_embd=2048, n_head=16, intermediate_size=5504
        )
        B, T, dt = 4, 1024, jnp.bfloat16
    else:
        cfg = llama.Config.from_name("tiny-llama-debug")
        B, T, dt = 2, 64, jnp.float32
    T = min(T, cfg.block_size)
    key = jax.random.PRNGKey(0)
    params = llama.init_params(cfg, key, dtype=dt)
    idx = jax.random.randint(jax.random.PRNGKey(1), (B, T), 0, cfg.vocab_size)
    tgt = jax.random.randint(jax.random.PRNGKey(2), (B, T), 0, cfg.vocab_size)
    cos, sin = llama.build_rope_cache(cfg, T, dtype=jnp.float32)

    def loss_fn(p, i, t, c, s):
        return llama.gpt_loss(p, i, t, c, s, cfg)

    # grads come from the framework's fw/bw pipeline; the UPDATE is the
    # donation target: params and grads die inside it and the new params
    # alias straight into the donated buffers (the copy_/optimizer pattern)
    def sgd_update(p, g):
        return jax.tree_util.tree_map(lambda a, b: a - 0.01 * b, p, g)

    vg = tt.value_and_grad(loss_fn)
    upd_plain = tt.jit(sgd_update)
    upd_off = tt.jit(sgd_update, donate=False)
    upd_on = tt.jit(sgd_update, donate=True)

    donated_before = registry().counter("donation.buffers_donated").value
    bytes_before = registry().counter("donation.bytes_donated").value

    _, grads = vg(params, idx, tgt, cos, sin)
    # warm the undonated specializations (they leave their inputs alive)
    p_plain = upd_plain(params, grads)
    p_off = upd_off(params, grads)

    # dispatch-only cost of the donate=False path vs the plain path: the
    # pass never ran for either, so any ratio above noise is a regression
    # (tools/bench_targets.py gates on this).  Measured BEFORE the donating
    # variant runs — a donated call CONSUMES params/grads (for real: even
    # this CPU backend deletes the buffers), and these loops reuse them.
    plain_us = host_us_per_call(upd_plain, params, grads, iters=max(iters, 20))
    off_us = host_us_per_call(upd_off, params, grads, iters=max(iters, 20))

    def step_seconds(update, p):
        t0 = time.perf_counter()
        for _ in range(iters):
            _, g = vg(p, idx, tgt, cos, sin)
            p = update(p, g)
        jax.block_until_ready(jax.tree_util.tree_leaves(p))
        return (time.perf_counter() - t0) / iters

    s_off = step_seconds(upd_off, p_off)
    s_plain = step_seconds(upd_plain, p_plain)
    # the donated step consumes its param/grad buffers each iteration and
    # feeds the (aliased) outputs forward — exactly the serving/training
    # loop donation is for.  Runs on copies so params/grads stay usable.
    p_on = upd_on(
        jax.tree_util.tree_map(lambda x: x.copy(), params),
        jax.tree_util.tree_map(lambda x: x.copy(), grads),
    )
    s_on = step_seconds(upd_on, p_on)

    peak_off = memory_timeline(tt.last_traces(upd_off)[-1])["peak_bytes_estimate"]
    t_on = memory_timeline(tt.last_traces(upd_on)[-1])
    peak_on = t_on["peak_bytes_estimate"]

    results = {
        "steps_per_sec_donate_on": round(1.0 / s_on, 3),
        "steps_per_sec_donate_off": round(1.0 / s_off, 3),
        "steps_per_sec_plain": round(1.0 / s_plain, 3),
        "update_peak_bytes_off": int(peak_off),
        "update_peak_bytes_on": int(peak_on),
        "peak_bytes_saved": int(peak_off - peak_on),
        "peak_reduction_pct": round(100.0 * (peak_off - peak_on) / peak_off, 2)
        if peak_off
        else 0.0,
        "update_donated_bytes": int(t_on["donated_bytes"]),
        "buffers_donated": registry().counter("donation.buffers_donated").value
        - donated_before,
        "bytes_donated": registry().counter("donation.bytes_donated").value
        - bytes_before,
        "update_plain_dispatch_us": round(plain_us, 3),
        "update_donate_off_dispatch_us": round(off_us, 3),
        "donate_off_overhead_x": round(off_us / plain_us, 3) if plain_us > 0 else None,
        "aliased_outputs": len(
            tt.donation_stats(upd_on)["forward"]["regions"][0]["aliases"]
        )
        if tt.donation_stats(upd_on)["forward"]["regions"]
        else 0,
    }
    return {
        "shapes": {
            "cfg": cfg.name,
            "n_layer": cfg.n_layer,
            "B": B,
            "T": T,
            "dtype": jnp.dtype(dt).name,
        },
        "results": results,
    }
