"""Paged-attention decode bench (`bench.py paged_attn`).

Three claims, one artifact (BENCH_PAGED_ATTN.json):

1. **Token parity** — the gated claim on every backend: an ``attn="paged"``
   engine serves tokens bit-identical to ``attn="gather"`` over a mixed
   greedy workload (the kernel's online softmax + fused fresh-token fold
   reproduces the dense math at the token level).
2. **Program purity** — gated: the compiled ``decode_paged`` program
   contains zero arena-sized gather primitives and zero scatters, while the
   gather program (the positive control, proving the census sees through
   pjit) contains both.
3. **Arena traffic** — the *why*: the gather decode path moves the whole
   bucketed cache per step (arena→dense gather, dense re-write, plus the
   scatter's full-arena copy under donation semantics) where the kernel
   reads blocks once and writes one slot.  The byte counts are analytic
   (shapes are static), the ratio is gated >1; wall-clock per step is
   recorded but only informational — on CPU the kernel runs in Pallas
   interpret mode, so throughput claims are reserved for real TPU windows.
"""
from __future__ import annotations

import time

import jax
import jax.numpy as jnp
import numpy as np


def _prim_census(jaxpr, arena_shapes, *, skip=("pallas_call",)):
    """(arena_gathers, scatters) over a jaxpr, recursing into sub-jaxprs
    but not pallas kernel bodies — same walk tests/test_paged_attention.py
    gates on."""
    arena_gathers = scatters = 0
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        if name == "gather" and tuple(eqn.invars[0].aval.shape) in arena_shapes:
            arena_gathers += 1
        if name.startswith("scatter"):
            scatters += 1
        if name in skip:
            continue
        for v in eqn.params.values():
            sub = getattr(v, "jaxpr", None)
            if sub is None and hasattr(v, "eqns"):
                sub = v
            if sub is not None and hasattr(sub, "eqns"):
                g, s = _prim_census(sub, arena_shapes, skip=skip)
                arena_gathers += g
                scatters += s
    return arena_gathers, scatters


def _program_census(eng, kind: str, Bb: int, nbb: int):
    prog, _ = eng._program(kind, Bb, nbb)
    key = jax.random.PRNGKey(0)
    args = (
        eng.params,
        jnp.zeros((Bb,), jnp.int32),
        jnp.zeros((Bb,), jnp.int32),
        jnp.zeros((Bb, nbb), jnp.int32),
        eng.pool.arenas,
        jnp.zeros((Bb, *key.shape), key.dtype),
        eng._lora_arenas(),
        jnp.zeros((Bb,), jnp.int32),
    )
    jaxpr = jax.make_jaxpr(prog)(*args).jaxpr
    shapes = {tuple(a.shape) for a in jax.tree_util.tree_leaves(eng.pool.arenas)}
    return _prim_census(jaxpr, shapes)


def paged_attention_bench(on_tpu: bool = False, *, reps: int = 3,
                          n_requests: int = 4, max_new: int = 8) -> dict:
    """Returns ``{"shapes": ..., "results": ...}`` in the BENCH_MICRO
    artifact shape."""
    import thunder_tpu as tt
    from thunder_tpu.models import llama

    cfg = llama.Config.from_name(
        "tiny-llama-debug",
        n_layer=2, n_head=4, n_query_groups=2, n_embd=32,
        intermediate_size=64, vocab_size=64, block_size=64,
    )
    params = llama.init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    rng = np.random.default_rng(0)
    prompts = [
        rng.integers(0, cfg.vocab_size, (3 + (i % 3) * 4,)).astype(np.int32)
        for i in range(n_requests)
    ]
    Bb, nbb, bs = 4, 6, 4
    base_kw = dict(block_size=bs, num_blocks=32, max_batch=4,
                   cache_dtype=jnp.float32, batch_buckets=(Bb,),
                   block_buckets=(nbb,), prefill_buckets=(16,))

    def drive(attn):
        eng = tt.serve(None, params, cfg, attn=attn, **base_kw)
        hs = [eng.submit(p, max_new_tokens=max_new) for p in prompts]
        t0 = time.perf_counter()
        eng.drain()
        dt = time.perf_counter() - t0
        return [tuple(h.result(drive=False).tokens) for h in hs], dt, eng

    # warm both program sets, collect tokens + census off the warm engines
    toks_g, _, eng_g = drive("gather")
    toks_p, _, eng_p = drive("paged")
    parity_ok = toks_g == toks_p
    tokens_checked = sum(len(t) for t in toks_g)
    g_gathers, g_scatters = _program_census(eng_g, "decode", Bb, nbb)
    p_gathers, p_scatters = _program_census(eng_p, "decode_paged", Bb, nbb)
    kernel_steps = eng_p.stats()["attn"]["kernel_steps"]

    # interleaved best-of-reps: informational on CPU (interpret-mode kernel)
    t_g, t_p = [], []
    for _ in range(reps):
        t_g.append(drive("gather")[1])
        t_p.append(drive("paged")[1])
    gather_s, paged_s = min(t_g), min(t_p)

    # analytic arena traffic per decode step (static shapes, f32):
    # gather path: arena->dense gather (K+V), the dense cache write, the
    # dense read inside attention, and the scatter's full-arena copy under
    # donation; paged path: the kernel reads each table block once and the
    # write touches one slot per layer/group
    L, ng, hs_ = cfg.n_layer, cfg.n_query_groups, cfg.head_size
    itm = 4
    dense_elems = Bb * nbb * bs * L * ng * hs_          # one K or V dense cache
    arena_elems = 32 * L * ng * bs * hs_                # one whole arena
    dense_bytes = 2 * itm * (3 * dense_elems + arena_elems)
    paged_bytes = 2 * itm * (dense_elems + Bb * L * ng * hs_)
    ratio = dense_bytes / paged_bytes

    return {
        "shapes": {"cfg": "tiny-llama-debug(2L,4h,2g)", "n_requests": n_requests,
                   "max_new_tokens": max_new, "reps": reps,
                   "bucket": [Bb, nbb], "block_size": bs},
        "results": {
            "parity_ok": bool(parity_ok),
            "tokens_checked": int(tokens_checked),
            "kernel_steps": int(kernel_steps),
            "paged_arena_gathers": int(p_gathers),
            "paged_scatters": int(p_scatters),
            "gather_arena_gathers": int(g_gathers),
            "gather_scatters": int(g_scatters),
            "drive_gather_ms": round(gather_s * 1e3, 3),
            "drive_paged_ms": round(paged_s * 1e3, 3),
            "paged_vs_gather_x": round(gather_s / paged_s, 4),
            "dense_bytes_per_step": int(dense_bytes),
            "paged_bytes_per_step": int(paged_bytes),
            "arena_traffic_ratio_x": round(ratio, 3),
        },
    }
