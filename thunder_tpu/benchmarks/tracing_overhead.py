"""Serving-plane tracing overhead microbench (`bench.py tracing`).

Two claims, one artifact (BENCH_TRACING.json):

1. **Off-path overhead ≈ 1.0x** — the gated claim.  An engine constructed
   with tracing/SLO/flight-recorder explicitly off must drive requests at
   the same speed as a default engine (the observability hooks are one
   ``is None`` check per touch point; a regression here is a category
   error — some instrumentation leaked onto the untraced path — not
   timing jitter, which best-of-reps interleaved measurement suppresses).
2. **On-path overhead is measured, not guessed** — with spans + SLO +
   flight ring all armed, the same drive costs `on_overhead_x`; reported
   for the docs, not gated (host-side appends are workload-relative).

The drive under test is the full engine loop (submit → prefill → decode →
finish) on the micro llama at serving-test shapes — small enough that host
work, the thing tracing could tax, dominates; a real model would hide an
off-path regression under device compute.
"""
from __future__ import annotations

import time

import jax
import jax.numpy as jnp
import numpy as np


def _drive(make_engine, reqs) -> float:
    """One timed engine drive over fresh copies of ``reqs``."""
    eng = make_engine()
    t0 = time.perf_counter()
    eng.run([dict(r) for r in reqs])
    return time.perf_counter() - t0


def tracing_overhead_bench(on_tpu: bool = False, *, reps: int = 12,
                           n_requests: int = 6, max_new: int = 12) -> dict:
    """Returns ``{"shapes": ..., "results": ...}`` in the BENCH_MICRO
    artifact shape."""
    import thunder_tpu as tt
    from thunder_tpu.models import llama
    from thunder_tpu.observability import clear_events

    cfg = llama.Config.from_name(
        "tiny-llama-debug",
        n_layer=1, n_head=2, n_embd=16, intermediate_size=32,
        vocab_size=32, block_size=64,
    )
    params = llama.init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    rng = np.random.default_rng(0)
    reqs = [
        {"prompt": rng.integers(0, cfg.vocab_size, (3 + (i % 3) * 4,)).astype(np.int32),
         "max_new_tokens": max_new}
        for i in range(n_requests)
    ]
    base_kw = dict(block_size=4, num_blocks=64, max_batch=4, cache_dtype=jnp.float32)
    slo_cfg = {"ttft_s": 1.0, "tpot_s": 0.5, "queue_s": 1.0}

    def plain():
        return tt.serve(None, params, cfg, **base_kw)

    def off():
        # every serving-plane observability knob EXPLICITLY off: must take
        # the identical code path as the default engine
        return tt.serve(None, params, cfg, trace=False, slo=None,
                        flight_recorder=False, **base_kw)

    def on():
        return tt.serve(None, params, cfg, trace=True, slo=slo_cfg,
                        flight_recorder=True, **base_kw)

    # warm every bucket program once so all timed drives are compile-free
    _drive(plain, reqs)

    # interleave the variants so clock drift / cache state hits them alike;
    # best-of-reps per variant is the jitter-robust summary
    t_plain, t_off, t_on = [], [], []
    for _ in range(reps):
        t_plain.append(_drive(plain, reqs))
        t_off.append(_drive(off, reqs))
        t_on.append(_drive(on, reqs))
    plain_s, off_s, on_s = min(t_plain), min(t_off), min(t_on)

    # span accounting from one final traced drive over a clean ring
    clear_events()
    eng = tt.serve(None, params, cfg, trace=True, slo=slo_cfg,
                   flight_recorder=True, **base_kw)
    eng.run([dict(r) for r in reqs])
    from thunder_tpu.observability import events

    serving_events = [e for e in events() if e.get("cat", "").startswith("serving")]
    slo_rep = eng.slo_report()

    return {
        "shapes": {"cfg": "tiny-llama-debug", "n_requests": n_requests,
                   "max_new_tokens": max_new, "reps": reps},
        "results": {
            "drive_plain_ms": round(plain_s * 1e3, 3),
            "drive_tracing_off_ms": round(off_s * 1e3, 3),
            "drive_tracing_on_ms": round(on_s * 1e3, 3),
            "off_overhead_x": round(off_s / plain_s, 4),
            "on_overhead_x": round(on_s / plain_s, 4),
            "serving_events_recorded": len(serving_events),
            "async_spans": sum(1 for e in serving_events if e["ph"] == "b"),
            "slo_dimensions": len(slo_rep.get("dimensions", {})),
            "flight_events": eng._flight.events_recorded,
        },
    }
