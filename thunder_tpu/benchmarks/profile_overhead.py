"""Profiling-transform overhead microbench: instrumented vs uninstrumented
dispatch on the llama block target.

The runtime profiling transform (observability/profiler.py) is opt-in; when
it IS on, its cost is the per-symbol timing wrapper (clock reads + record
update) and, optionally, the ``jax.block_until_ready`` fence.  This bench
measures all three variants on the same compiled llama forward so
``bench.py profile`` can police that (a) disabled profiling costs nothing
(same code path as ever) and (b) enabled profiling stays proportionate.
Host-side µs/call (``host_us_per_call``) is the right meter for the wrapper
cost; the barrier variant is reported separately because the fence
deliberately serializes device work.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from thunder_tpu.benchmarks.timing import host_us_per_call

__all__ = ["profile_overhead_bench"]


def profile_overhead_bench(on_tpu: bool = False, iters: int = 50) -> dict:
    """Returns ``{"shapes": {...}, "results": {...}}`` (the BENCH_MICRO.json
    artifact schema).  Results: µs/call for the plain, instrumented
    (no-barrier), and instrumented+barrier jits of the llama block forward,
    the wrapper overhead ratio, and the profiler's own accounting."""
    import thunder_tpu as tt
    from thunder_tpu.models import llama

    if on_tpu:
        cfg = llama.Config.from_name(
            "Llama-2-7b-hf", n_layer=1, n_embd=2048, n_head=16, intermediate_size=5504
        )
        B, T, dt = 4, 2048, jnp.bfloat16
    else:
        cfg = llama.Config.from_name("tiny-llama-debug")
        B, T, dt = 2, 64, jnp.float32
    T = min(T, cfg.block_size)
    key = jax.random.PRNGKey(0)
    params = llama.init_params(cfg, key, dtype=dt)
    idx = jax.random.randint(jax.random.PRNGKey(1), (B, T), 0, cfg.vocab_size)
    cos, sin = llama.build_rope_cache(cfg, T, dtype=jnp.float32)

    def block_fwd(p, i, c, s):
        return llama.gpt_forward(p, i, c, s, cfg)

    plain = tt.jit(block_fwd)
    instrumented = tt.jit(block_fwd, profile=True, profile_barriers=False)
    instrumented_barrier = tt.jit(block_fwd, profile=True)

    results = {
        "block_fwd_plain_us": round(
            host_us_per_call(plain, params, idx, cos, sin, iters=iters), 3
        ),
        "block_fwd_profiled_us": round(
            host_us_per_call(instrumented, params, idx, cos, sin, iters=iters), 3
        ),
        "block_fwd_profiled_barrier_us": round(
            host_us_per_call(instrumented_barrier, params, idx, cos, sin, iters=iters), 3
        ),
    }
    plain_us = results["block_fwd_plain_us"]
    results["overhead_x"] = (
        round(results["block_fwd_profiled_us"] / plain_us, 3) if plain_us > 0 else None
    )
    results["barrier_overhead_x"] = (
        round(results["block_fwd_profiled_barrier_us"] / plain_us, 3)
        if plain_us > 0
        else None
    )

    report = tt.profile_stats(instrumented)
    stats = dict(report)
    results["instrumented_symbols"] = len(stats)
    results["instrumented_calls"] = sum(r["calls"] for r in stats.values())
    results["profiled_total_ms"] = round(
        sum(r["total_ns"] for r in stats.values()) / 1e6, 3
    )
    return {
        "shapes": {
            "cfg": cfg.name,
            "n_layer": cfg.n_layer,
            "B": B,
            "T": T,
            "dtype": jnp.dtype(dt).name,
        },
        "results": results,
    }
