"""Continuous-batching serving benchmark: engine vs sequential generate().

The claim under test is the serving subsystem's reason to exist: N
concurrent requests through the continuously-batched engine must beat the
same N requests run back-to-back through solo ``generate()`` calls in
tokens/sec, with mean batch occupancy > 1 (requests actually share decode
steps) and the compiled-program count bounded by the bucket sets.

Config note: the CPU run uses the tiny-llama architecture at ``n_embd=128``
(not the 64-wide ``tiny-llama-debug`` default).  At width 64 a CPU decode
step costs ~30µs — less than one XLA dispatch — so the per-step host
overhead of the batched drive loop swamps the batching win; that is a
CPU-host artifact, not a batching property (on TPU the per-step compute is
the dominant term at any serving width).  Width 128 keeps the model tiny
(~1 s warmup) while letting compute, not dispatch, decide the comparison.

Both paths are warmed to steady state first (solo ``generate`` caches its
prefill/scan pair per shape; the engine's bucket programs land in the
module program cache), so the measured window is compile-free for both.
Timing is interleaved best-of-``reps`` for BOTH paths (the tracing-bench
methodology): CI hosts jitter 2-3x run to run, and the ratio of two
single-shot samples inherits both samples' noise.
"""
from __future__ import annotations

import time

import jax
import jax.numpy as jnp
import numpy as np


def serving_bench(on_tpu: bool = False, *, smoke: bool = False, reps: int = 3) -> dict:
    """Returns ``{"results": {...}}`` in the BENCH_MICRO artifact shape."""
    import thunder_tpu as tt
    from thunder_tpu.models import generate as gen
    from thunder_tpu.models import llama

    if smoke:
        n_requests, max_new, max_batch, lens = 4, 8, 4, (4, 6, 8)
        overrides = dict(n_embd=128, intermediate_size=344)
        reps = min(reps, 2)
    else:
        n_requests, max_new, max_batch, lens = 8, 32, 8, (8, 12, 16, 24)
        overrides = dict(n_embd=128, intermediate_size=344)
    cfg = llama.Config.from_name("tiny-llama-debug", **overrides)
    params = llama.init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    rng = np.random.default_rng(0)
    prompts = [
        rng.integers(0, cfg.vocab_size, (lens[i % len(lens)],)).astype(np.int32)
        for i in range(n_requests)
    ]
    reqs = [{"prompt": p, "max_new_tokens": max_new} for p in prompts]
    block_size = 16
    num_blocks = max_batch * (-(-(max(lens) + max_new) // block_size)) + 1

    def make_engine():
        return tt.serve(
            None, params, cfg, block_size=block_size, num_blocks=num_blocks,
            max_batch=max_batch, cache_dtype=jnp.float32,
        )

    # -- warm both paths: solo generate caches its prefill/scan pair per
    # shape; the warm engine compiles the bucket programs into the module
    # cache so every measured engine below is compile-free
    for p in prompts:
        gen.generate(params, p[None], cfg, max_new, cache_dtype=jnp.float32)
    warm = make_engine()
    warm_results = warm.run([dict(r) for r in reqs])
    compile_counts = dict(warm.stats()["compile_counts"])
    bucket_bound = warm.stats()["bucket_bound"]
    # cold-start accounting: prefills that paid an XLA compile on the warm
    # engine (the cold-TTFT outlier population, distinguishable from queue
    # delay via the per-request compile tag)
    cold_prefills_warm = sum(1 for r in warm_results if r.prefill_compiled)

    def seq_once() -> float:
        t0 = time.perf_counter()
        out = None
        for p in prompts:
            out = gen.generate(params, p[None], cfg, max_new, cache_dtype=jnp.float32)
        np.asarray(out)  # host fetch fences the loop
        return time.perf_counter() - t0

    def srv_once():
        eng = make_engine()
        t0 = time.perf_counter()
        results = eng.run([dict(r) for r in reqs])
        return time.perf_counter() - t0, eng, results

    # -- interleaved best-of-reps: each rep times the sequential loop and a
    # fresh (program-cache-warm) engine back to back, so host jitter hits
    # both sides of the ratio alike
    seq_s = float("inf")
    srv_s = float("inf")
    eng = results = None
    for _ in range(max(int(reps), 1)):
        seq_s = min(seq_s, seq_once())
        dt, e, res = srv_once()
        if dt < srv_s:
            srv_s, eng, results = dt, e, res
    seq_tps = n_requests * max_new / seq_s
    n_tokens = sum(len(r.new_tokens) for r in results)
    srv_tps = n_tokens / srv_s
    stats = eng.stats()
    snap = tt.metrics_snapshot()
    ttft = snap.get("serving.ttft_s", {}) or {}
    cold_prefills_measured = sum(1 for r in results if r.prefill_compiled)

    return {
        "results": {
            "serving_tokens_per_sec": round(srv_tps, 1),
            "sequential_tokens_per_sec": round(seq_tps, 1),
            "throughput_ratio": round(srv_tps / seq_tps, 3),
            "mean_batch_occupancy": round(stats["mean_batch_occupancy"], 3),
            "prefill_compiles": compile_counts["prefill"],
            "decode_compiles": compile_counts["decode"],
            "bucket_bound": bucket_bound,
            # requests whose prefill paid a compile: all cold starts land on
            # the warm engine, and the measured (steady-state) engine must
            # see none — its TTFT percentiles are compile-free by design
            "cold_compile_prefills_warm": cold_prefills_warm,
            "cold_compile_prefills_measured": cold_prefills_measured,
            "n_requests": n_requests,
            "max_new_tokens": max_new,
            "tokens_measured": n_tokens,
            "ttft_p50_s": ttft.get("p50"),
            "ttft_p95_s": ttft.get("p95"),
            "config": f"tiny-llama n_embd={cfg.n_embd} n_layer={cfg.n_layer}",
            "smoke": smoke,
        }
    }
