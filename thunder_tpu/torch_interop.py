"""torch.nn.Module interop: ThunderModule + the autograd bridge.

Reference parity: ``thunder/__init__.py:181`` (ThunderModule) and
``thunder/executors/torch_autograd.py:20-78`` (ThunderFunction stitching
compiled fw/bw into torch autograd, including the saved-tensor release
contract).  TPU-first design: the module's forward is *functionalized* — its
parameters/buffers are swapped for proxies during tracing, so the whole
forward records through the ``TensorProxy.__torch_function__`` diversion into
one thunder_tpu trace; execution is the framework's compiled fw/bw pair (XLA
programs), and ``ThunderFunction`` only bridges tensors at the boundary
(torch ↔ jax via host memory on CPU; dlpack where available).

Limitations (v1): gradients flow to module *parameters* (inputs receive
``None`` grads); buffer mutation (BatchNorm running stats) is not recorded —
the functional frontend has no epilogue yet; ``module.training`` is baked at
trace time.
"""
from __future__ import annotations

import itertools
from typing import Any, Callable

import jax.numpy as jnp
import numpy as np
import torch

__all__ = ["ThunderModule", "ThunderFunction", "functional_call", "ThunderTracingMode"]


_const_counter = itertools.count()


def _translate_thunder_metadata(x):
    """thunder dtype → torch dtype; thunder Device → host device (constants
    live on the host); everything else unchanged."""
    from thunder_tpu.core import dtypes as ttd
    from thunder_tpu.core.devices import Device as _TDev

    if isinstance(x, ttd.dtype):
        return ttd.to_torch_dtype(x)
    if isinstance(x, _TDev):
        return torch.device("cpu")
    return x


def _normalize_torch_device_kwarg(kwargs: dict) -> None:
    dev = kwargs.get("device")
    if isinstance(dev, torch.device):
        typ = "tpu" if dev.type in ("cuda", "xla") else dev.type
        kwargs["device"] = f"{typ}:{dev.index}" if dev.index is not None else typ


def _const_tensor_proxy(t: torch.Tensor):
    """Bakes a concrete torch tensor into the active trace as a CONSTANT:
    records a zero-input producer bsym whose call-ctx callable returns the
    jax value (the FusionCallable pattern, executors/xlaex.py) and returns
    its output proxy.  This is how native-torch constant math (masks built
    by the concrete-factory fast path) re-enters the traced program.

    The proxy is memoized per tensor identity on the trace: re-baking the
    SAME tensor returns the SAME proxy object, so an in-place traced edit
    (``m[1:3] = traced`` rebinding the proxy) is visible to every later
    diverted use of ``m``.  (Native real-tensor reads after a traced edit
    still see the old buffer — mixing directions is inherently lossy.)"""
    from thunder_tpu.core.proxies import tensorproxy
    from thunder_tpu.core.symbol import Symbol
    from thunder_tpu.core.trace import get_tracectx

    trace = get_tracectx()
    aliases = getattr(trace, "_torch_const_aliases", None)
    if aliases is None:
        aliases = trace._torch_const_aliases = {}
    hit = aliases.get(id(t))
    if hit is not None and hit[0] is t:
        return hit[1]
    arr = _to_jax(t)  # _to_jax detaches
    p = tensorproxy(arr, requires_grad=False)
    cname = f"TCONST{next(_const_counter)}"
    sym = Symbol(name=cname, meta=None, is_fusion=True)
    bsym = sym.bind(output=p, subsymbols=(), _call_ctx={cname: lambda arr=arr: arr})
    trace.record(bsym)
    aliases[id(t)] = (t, p)  # pins t so the id can't be recycled mid-trace
    return p


def _bake_torch_constants(args, kwargs):
    """Replaces real torch.Tensor leaves in a diverted call's arguments with
    baked constant proxies (lists/tuples walked one level — the layouts the
    torch surface accepts)."""
    from thunder_tpu.core.trace import get_tracectx

    if get_tracectx() is None:
        return args, kwargs

    def conv(x):
        if isinstance(x, torch.Tensor):
            return _const_tensor_proxy(x)
        if isinstance(x, (list, tuple)) and any(isinstance(e, torch.Tensor) for e in x):
            return type(x)(conv(e) for e in x)
        return x

    return tuple(conv(a) for a in args), {k: conv(v) for k, v in kwargs.items()}


class ThunderTracingMode(torch.overrides.TorchFunctionMode):
    """Diverts *every* mapped ``torch.*`` call into the thunder op surface
    while a trace is active — including factory calls with no proxy argument
    (``torch.arange(0, T, device=...)`` in HF models), which the per-proxy
    ``__torch_function__`` protocol can never see.  The reference needs
    interpreter lookasides for this (jit_ext.py:884); a TorchFunctionMode is
    the functional-frontend equivalent."""

    # deterministic factories: a call with fully concrete arguments produces
    # a CONSTANT — keeping it a real torch.Tensor preserves downstream
    # `isinstance(x, torch.Tensor)` branches (HF mask plumbing decides
    # "user supplied a mask" that way) and lets constant mask math run
    # natively once instead of being traced.  RNG factories are NOT here:
    # they must divert so every compiled call resamples through thunder's
    # RNG instead of baking one sample.
    _CONCRETE_FACTORIES = frozenset(
        f for f in (
            getattr(torch, n, None)
            for n in ("ones", "zeros", "full", "arange", "linspace", "eye", "empty")
        ) if f is not None
    )

    @staticmethod
    def _any_thunder_arg(args, kwargs) -> bool:
        from thunder_tpu.core import dtypes as ttd
        from thunder_tpu.core.devices import Device as _TDev
        from thunder_tpu.core.proxies import Proxy

        def is_thunder(x):
            return isinstance(x, (Proxy, ttd.dtype, _TDev))

        return any(is_thunder(a) for a in args) or any(is_thunder(v) for v in kwargs.values())

    def __torch_function__(self, func, types, args=(), kwargs=None):
        kwargs = dict(kwargs or {})
        from thunder_tpu.core.trace import get_tracectx
        from thunder_tpu.torch import _torch_to_thunder_function_map

        if get_tracectx() is not None:
            mapped = _torch_to_thunder_function_map.get(func)
            if mapped is not None:
                if func in self._CONCRETE_FACTORIES and not self._any_thunder_arg(args, kwargs):
                    with torch._C.DisableTorchFunction():
                        return func(*args, **kwargs)
                _normalize_torch_device_kwarg(kwargs)
                args, kwargs = _bake_torch_constants(args, kwargs)
                return mapped(*args, **kwargs)
            # unmapped call on REAL tensors that only carries thunder
            # metadata (e.g. `real.to(dtype=proxy.dtype)` in T5): translate
            # the dtype/device objects to torch equivalents and run natively
            # — the result stays a real-tensor constant
            from thunder_tpu.core import dtypes as ttd
            from thunder_tpu.core.devices import Device as _TDev
            from thunder_tpu.core.proxies import Proxy

            flat_vals = list(args) + list(kwargs.values())
            if any(isinstance(v, (ttd.dtype, _TDev)) for v in flat_vals) and not any(
                isinstance(v, Proxy) for v in flat_vals
            ):
                with torch._C.DisableTorchFunction():
                    return func(
                        *(_translate_thunder_metadata(a) for a in args),
                        **{k: _translate_thunder_metadata(v) for k, v in kwargs.items()},
                    )
        return func(*args, **kwargs)

    # HF transformers builds 4D attention masks by torch.vmap-ing elementwise
    # index predicates (masking_utils._vmap_for_bhqkv); functorch can't batch
    # proxies, but for elementwise predicates vmap ≡ broadcasting, so the
    # mode swaps in a broadcast implementation while tracing.
    @staticmethod
    def _broadcast_bhqkv(mask_function, bh_indices: bool = True):
        if bh_indices:
            def fn(b, h, q, kv):
                return mask_function(
                    b[:, None, None, None],
                    h[None, :, None, None],
                    q[None, None, :, None],
                    kv[None, None, None, :],
                )
        else:
            def fn(q, kv):
                return mask_function(q[:, None], kv[None, :])
        return fn

    # refcounted so nested modes don't restore the originals mid-trace
    _patch_depth = 0
    _patches: list = []

    @staticmethod
    def _tensor_shim(orig):
        # torch.tensor(scalar, dtype=<thunder dtype>, device=<Device>) in HF
        # mask code: translate the dtype, build the constant through the
        # thunder op surface while a trace is active
        def shim(data, *args, dtype=None, device=None, **kwargs):
            from thunder_tpu.core import dtypes as ttd
            from thunder_tpu.core.devices import Device as _TDev
            from thunder_tpu.core.trace import get_tracectx

            if get_tracectx() is not None and isinstance(data, (int, float, bool)):
                import thunder_tpu.torch as ltorch

                return ltorch.full((), data, dtype=dtype)
            if isinstance(dtype, ttd.dtype):
                dtype = ttd.to_torch_dtype(dtype)
            if dtype is not None:
                kwargs["dtype"] = dtype
            # forward real torch devices; only thunder Devices (whose raw str
            # is an xla spec torch can't allocate on) are dropped to CPU
            if device is not None and not isinstance(device, _TDev):
                kwargs["device"] = device
            return orig(data, *args, **kwargs)

        return shim

    @staticmethod
    def _tensor_to_shim(orig):
        # real_tensor.to(dtype=<thunder dtype>) (T5 casts constants to a
        # proxy's dtype): torch's C parser rejects the foreign dtype before
        # any __torch_function__ dispatch, so Tensor.to is patched to
        # translate thunder dtype/Device objects first
        def shim(self_t, *args, **kwargs):
            return orig(
                self_t,
                *(_translate_thunder_metadata(a) for a in args),
                **{k: _translate_thunder_metadata(v) for k, v in kwargs.items()},
            )

        return shim

    @staticmethod
    def _factory_shim(orig):
        # torch.full/zeros/ones/... with dtype=<thunder dtype> (HF mask code
        # feeds a proxy's .dtype back into a factory): torch's C arg parser
        # rejects the foreign dtype BEFORE __torch_function__ dispatch can
        # divert, so these factories are patched to route through the mapped
        # thunder op while a trace is active
        def shim(*args, **kwargs):
            from thunder_tpu.core import dtypes as ttd
            from thunder_tpu.core.devices import Device as _TDev
            from thunder_tpu.core.trace import get_tracectx
            from thunder_tpu.torch import _torch_to_thunder_function_map

            dtype = kwargs.get("dtype")
            if get_tracectx() is not None and isinstance(dtype, ttd.dtype):
                mapped = _torch_to_thunder_function_map.get(orig)
                if mapped is not None:
                    _normalize_torch_device_kwarg(kwargs)
                    return mapped(*args, **kwargs)
                kwargs["dtype"] = ttd.to_torch_dtype(dtype)
            dev = kwargs.get("device")
            if isinstance(dev, _TDev):  # thunder Device str confuses torch
                kwargs.pop("device")
            return orig(*args, **kwargs)

        return shim

    _FACTORY_NAMES = ("full", "zeros", "ones", "empty", "arange", "linspace", "eye")

    @staticmethod
    def _finfo_shim(orig):
        # torch.finfo/iinfo reject thunder dtypes at the C arg parser (they
        # are not torch.dtype); HF mask code calls torch.finfo(t.dtype).min
        def shim(dtype=None):
            from thunder_tpu.core import dtypes as ttd

            if isinstance(dtype, ttd.dtype):
                dtype = ttd.to_torch_dtype(dtype)
            return orig(dtype) if dtype is not None else orig()

        return shim

    def __enter__(self):
        import sys as _sys

        cls = ThunderTracingMode
        if cls._patch_depth == 0:
            cls._patches = []
            mu = _sys.modules.get("transformers.masking_utils")
            if mu is not None and hasattr(mu, "_vmap_for_bhqkv"):
                cls._patches.append((mu, "_vmap_for_bhqkv", mu._vmap_for_bhqkv))
                mu._vmap_for_bhqkv = self._broadcast_bhqkv
            for name in ("finfo", "iinfo"):
                orig = getattr(torch, name)
                cls._patches.append((torch, name, orig))
                setattr(torch, name, self._finfo_shim(orig))
            cls._patches.append((torch, "tensor", torch.tensor))
            torch.tensor = self._tensor_shim(torch.tensor)
            for name in cls._FACTORY_NAMES:
                orig = getattr(torch, name)
                cls._patches.append((torch, name, orig))
                setattr(torch, name, self._factory_shim(orig))
            cls._patches.append((torch.Tensor, "to", torch.Tensor.to))
            torch.Tensor.to = self._tensor_to_shim(torch.Tensor.to)
            # HF mask utils guard data-dependent branches ("skip the mask if
            # torch.all(mask == 1)") behind torch.jit.is_tracing(); answer
            # True so they take the tracing-safe path instead of forcing a
            # TensorProxy into Python bool (modeling_attn_mask_utils.py:454)
            cls._patches.append((torch.jit, "is_tracing", torch.jit.is_tracing))
            torch.jit.is_tracing = lambda: True
        cls._patch_depth += 1
        return super().__enter__()

    def __exit__(self, *exc):
        cls = ThunderTracingMode
        cls._patch_depth -= 1
        if cls._patch_depth == 0:
            for obj, name, orig in reversed(cls._patches):
                setattr(obj, name, orig)
            cls._patches = []
        return super().__exit__(*exc)


def _to_jax(t: torch.Tensor):
    t = t.detach().cpu()
    if t.dtype == torch.bfloat16:  # numpy has no native bf16
        return jnp.asarray(t.float().numpy()).astype(jnp.bfloat16)
    return jnp.asarray(t.numpy())


def _to_torch(a) -> torch.Tensor:
    arr = np.asarray(a)
    if arr.dtype.name == "bfloat16":  # ml_dtypes bf16: not a torch.from_numpy dtype
        return torch.from_numpy(arr.astype(np.float32)).to(torch.bfloat16)
    # copy: np.asarray gives a read-only zero-copy view of the jax buffer, and
    # an in-place torch op on it would corrupt memory jax still references
    return torch.from_numpy(np.array(arr))


def functional_call(module: torch.nn.Module, params_and_buffers: dict, args: tuple, kwargs: dict):
    """Calls ``module`` with its parameters/buffers swapped for the values in
    ``params_and_buffers`` (dotted names), restoring the originals after.

    The swap goes through ``_parameters``/``_buffers`` dicts directly so the
    replacement values may be TensorProxies — ``torch.nn.Module.__setattr__``
    would reject non-Parameters, but attribute *reads* return whatever the
    dicts hold, which is exactly what tracing needs.
    """
    mods = dict(module.named_modules())
    saved: list[tuple[dict, str, Any]] = []
    try:
        swapped: dict[int, Any] = {}  # id(original tensor) → replacement
        for name, value in params_and_buffers.items():
            mod_name, _, attr = name.rpartition(".")
            m = mods[mod_name]
            d = m._parameters if attr in m._parameters else m._buffers
            saved.append((d, attr, d[attr]))
            swapped[id(d[attr])] = value
            d[attr] = value
        # tied weights: named_parameters() deduplicates (e.g. lm_head.weight
        # is wte.weight), so swap any remaining entry that aliases a swapped
        # tensor by identity
        for m in mods.values():
            for d in (m._parameters, m._buffers):
                for attr, t in list(d.items()):
                    rep = swapped.get(id(t))
                    if rep is not None and t is not rep:
                        saved.append((d, attr, t))
                        d[attr] = rep
        return module(*args, **kwargs)
    finally:
        for d, attr, old in saved:
            d[attr] = old


class ThunderFunction(torch.autograd.Function):
    """Stitches a compiled thunder_tpu fw/bw pair into torch autograd.

    ``apply(holder, *param_tensors)``: ``holder`` carries the compiled vjp
    runner and output structure; gradients are returned for the parameter
    tensors in the order given.  Residuals live in the pullback closure and
    are dropped right after backward (the reference's saved-tensor release
    contract, ``torch_autograd.py:57-78``).
    """

    @staticmethod
    def forward(ctx, holder: dict, *param_tensors: torch.Tensor):
        out, pullback = holder["run"]()
        flat_out, out_spec = jax_tree_flatten(out)
        ctx._pullback = pullback
        ctx._holder = holder
        holder["out_spec"] = out_spec
        return tuple(_to_torch(o) for o in flat_out)

    @staticmethod
    def backward(ctx, *grad_outputs: torch.Tensor):
        holder = ctx._holder
        cts = [
            _to_jax(g) if g is not None else None
            for g in grad_outputs
        ]
        ct_tree = jax_tree_unflatten(holder["out_spec"], cts)
        grads_dict = ctx._pullback(ct_tree)
        del ctx._pullback  # release residuals eagerly (memory contract)
        names = holder["param_names"]
        out = tuple(
            _to_torch(grads_dict[n]) if grads_dict.get(n) is not None else None
            for n in names
        )
        return (None,) + out


def jax_tree_flatten(x):
    import jax.tree_util as jtu

    return jtu.tree_flatten(x)


def jax_tree_unflatten(spec, leaves):
    import jax.tree_util as jtu

    return jtu.tree_unflatten(spec, leaves)


class ThunderModule(torch.nn.Module):
    """Wraps a ``torch.nn.Module`` so its forward runs as a compiled
    thunder_tpu program while torch autograd keeps working on the outside.

    ``thunder_tpu.jit(module)`` returns one of these (reference
    ``thunder.jit`` on modules, ``thunder/__init__.py:181``).
    """

    def __init__(self, module: torch.nn.Module, **jit_kwargs):
        super().__init__()
        self._orig_mod = module
        self._jit_kwargs = jit_kwargs
        self._vjp_fn = None  # built lazily (imports thunder_tpu)
        self._fwd_fn = None  # forward-only compiled path (no-grad inference)
        self._gen_shim = None  # cached GenerationMixin shim instance
        # torch→jax transfer cache keyed by (tensor identity, version): params
        # only re-upload after an in-place update (optimizer step), not on
        # every forward
        self._xfer_cache: dict[str, tuple[tuple[int, int], Any]] = {}

    def _make_functional_fwd(self):
        """The functionalized forward both compile paths share: swaps
        params/buffers for proxies, runs under the tracing mode, and unwraps
        HF ModelOutput (an OrderedDict subclass the pytree won't open) to a
        plain dict of present fields — remembering the class in ONE shared
        cell so forward() rewraps for the caller regardless of which path
        traced it."""
        module = self._orig_mod
        if not hasattr(self, "_out_cls_cell"):
            self._out_cls_cell = [None]
        out_cls_cell = self._out_cls_cell

        def functional_fwd(params, buffers, *args, **kwargs):
            with ThunderTracingMode():
                out = functional_call(module, {**params, **buffers}, args, kwargs)
            if isinstance(out, dict) and type(out) is not dict:
                out_cls_cell[0] = type(out)
                out = {k: v for k, v in out.items() if v is not None}
            return out

        return functional_fwd

    def _get_vjp(self):
        if self._vjp_fn is None:
            import thunder_tpu as ttpu

            self._vjp_fn = ttpu.vjp(self._make_functional_fwd(), argnums=(0,), **self._jit_kwargs)
        self._last_compiled = self._vjp_fn
        return self._vjp_fn

    def _get_fwd_only(self):
        """Forward-only compiled path for no-grad inference (generate()
        decode loops, eval): no VJP split, no pullback residuals
        materialized per call."""
        if self._fwd_fn is None:
            import thunder_tpu as ttpu

            kw = dict(self._jit_kwargs)
            kw["disable_grad"] = True
            self._fwd_fn = ttpu.jit(self._make_functional_fwd(), **kw)
        self._last_compiled = self._fwd_fn
        return self._fwd_fn

    def _cached_to_jax(self, name: str, t: torch.Tensor):
        key = (id(t), t._version)
        ent = self._xfer_cache.get(name)
        if ent is not None and ent[0] == key:
            return ent[1]
        a = _to_jax(t)
        self._xfer_cache[name] = (key, a)
        return a

    def forward(self, *args, **kwargs):
        params = dict(self._orig_mod.named_parameters())
        buffers = dict(self._orig_mod.named_buffers())

        jax_params = {n: self._cached_to_jax(n, p) for n, p in params.items()}
        jax_buffers = {n: self._cached_to_jax(n, b) for n, b in buffers.items()}
        jax_args = tuple(_to_jax(a) if isinstance(a, torch.Tensor) else a for a in args)
        jax_kwargs = {
            k: _to_jax(v) if isinstance(v, torch.Tensor) else v for k, v in kwargs.items()
        }

        if not torch.is_grad_enabled():
            # inference: forward-only compiled program, no residuals
            out = self._get_fwd_only()(jax_params, jax_buffers, *jax_args, **jax_kwargs)
            flat, spec = jax_tree_flatten(out)
            out = jax_tree_unflatten(spec, [
                _to_torch(x) if not isinstance(x, torch.Tensor) else x for x in flat
            ])
        else:
            param_names = sorted(params)
            param_tensors = [params[n] for n in param_names]
            vjp_fn = self._get_vjp()
            holder = {
                "run": lambda: vjp_fn(jax_params, jax_buffers, *jax_args, **jax_kwargs),
                "param_names": param_names,
            }
            flat_out = ThunderFunction.apply(holder, *param_tensors)
            out = jax_tree_unflatten(holder["out_spec"], list(flat_out))
        out_cls = getattr(self, "_out_cls_cell", [None])[0]
        if out_cls is not None and isinstance(out, dict):
            out = out_cls(**out)
        return out

    def generate(self, *args, **kwargs):
        """HF GenerationMixin support: runs the wrapped model's ``generate``
        with the main (decoder) forward dispatched through the compiled
        thunder program (each new sequence length is one compile; repeated
        lengths hit the cache; no-grad forwards take the forward-only path).
        Encoder-decoder models run their encoder eagerly (HF calls
        ``get_encoder()`` directly).

        HF's mutating KV caches (``use_cache=True``) don't trace — the
        compiled step is functional — so the cache is disabled: every step
        recomputes the full prefix (our native ``models/generate.py`` is the
        cached serving path).  HF resolves decoding methods off
        ``type(self)``, so the call runs on a shim instance whose CLASS
        subclasses the wrapped model's (keeping ``_sample``/config plumbing)
        while ``forward`` routes here; the shim shares the wrapped module's
        state dict-for-dict."""
        if kwargs.get("use_cache"):
            raise NotImplementedError(
                "generate(use_cache=True) would mutate an HF KV cache inside the "
                "compiled functional forward; pass use_cache=False (full-prefix "
                "recompute) or serve with thunder_tpu.models.generate (one-program "
                "KV-cache decode)"
            )
        kwargs["use_cache"] = False
        cls = type(self._orig_mod)
        if not hasattr(cls, "generate"):
            raise AttributeError(f"{cls.__name__} has no generate()")

        if self._gen_shim is None:
            import functools as _ft
            import inspect as _inspect

            tm = self

            def shim_forward(s, *a, **k):
                return ThunderModule.forward(tm, *a, **k)

            # HF validates model kwargs against inspect.signature(forward):
            # carry the wrapped forward's real signature onto the shim
            shim_forward = _ft.wraps(cls.forward)(shim_forward)
            shim_forward.__signature__ = _inspect.signature(cls.forward)
            shim_cls = type(f"Thunder{cls.__name__}", (cls,), {"forward": shim_forward})
            shim = object.__new__(shim_cls)  # share state; skip __init__
            shim.__dict__ = self._orig_mod.__dict__
            self._gen_shim = shim
        return type(self._gen_shim).generate(self._gen_shim, *args, **kwargs)

    def __getattr__(self, name):
        # delegate config/generation_config/prepare_inputs_for_generation/…
        # lookups to the wrapped module (nn.Module.__getattr__ covers
        # registered params/buffers/submodules first)
        try:
            return super().__getattr__(name)
        except AttributeError:
            return getattr(super().__getattr__("_orig_mod"), name)

    # reference ThunderModule passes state_dict through to the wrapped module
    def state_dict(self, *args, **kwargs):
        return self._orig_mod.state_dict(*args, **kwargs)

    def load_state_dict(self, *args, **kwargs):
        return self._orig_mod.load_state_dict(*args, **kwargs)

    def named_parameters(self, *args, **kwargs):
        return self._orig_mod.named_parameters(*args, **kwargs)

    def parameters(self, *args, **kwargs):
        return self._orig_mod.parameters(*args, **kwargs)
