"""torch.nn.Module interop: ThunderModule + the autograd bridge.

Reference parity: ``thunder/__init__.py:181`` (ThunderModule) and
``thunder/executors/torch_autograd.py:20-78`` (ThunderFunction stitching
compiled fw/bw into torch autograd, including the saved-tensor release
contract).  TPU-first design: the module's forward is *functionalized* — its
parameters/buffers are swapped for proxies during tracing, so the whole
forward records through the ``TensorProxy.__torch_function__`` diversion into
one thunder_tpu trace; execution is the framework's compiled fw/bw pair (XLA
programs), and ``ThunderFunction`` only bridges tensors at the boundary
(torch ↔ jax via host memory on CPU; dlpack where available).

Limitations (v1): gradients flow to module *parameters* (inputs receive
``None`` grads); buffer mutation (BatchNorm running stats) is not recorded —
the functional frontend has no epilogue yet; ``module.training`` is baked at
trace time.
"""
from __future__ import annotations

from typing import Any, Callable

import jax.numpy as jnp
import numpy as np
import torch

__all__ = ["ThunderModule", "ThunderFunction", "functional_call"]


def _to_jax(t: torch.Tensor):
    t = t.detach().cpu()
    if t.dtype == torch.bfloat16:  # numpy has no native bf16
        return jnp.asarray(t.float().numpy()).astype(jnp.bfloat16)
    return jnp.asarray(t.numpy())


def _to_torch(a) -> torch.Tensor:
    arr = np.asarray(a)
    if arr.dtype.name == "bfloat16":  # ml_dtypes bf16: not a torch.from_numpy dtype
        return torch.from_numpy(arr.astype(np.float32)).to(torch.bfloat16)
    # copy: np.asarray gives a read-only zero-copy view of the jax buffer, and
    # an in-place torch op on it would corrupt memory jax still references
    return torch.from_numpy(np.array(arr))


def functional_call(module: torch.nn.Module, params_and_buffers: dict, args: tuple, kwargs: dict):
    """Calls ``module`` with its parameters/buffers swapped for the values in
    ``params_and_buffers`` (dotted names), restoring the originals after.

    The swap goes through ``_parameters``/``_buffers`` dicts directly so the
    replacement values may be TensorProxies — ``torch.nn.Module.__setattr__``
    would reject non-Parameters, but attribute *reads* return whatever the
    dicts hold, which is exactly what tracing needs.
    """
    mods = dict(module.named_modules())
    saved: list[tuple[dict, str, Any]] = []
    try:
        for name, value in params_and_buffers.items():
            mod_name, _, attr = name.rpartition(".")
            m = mods[mod_name]
            d = m._parameters if attr in m._parameters else m._buffers
            saved.append((d, attr, d[attr]))
            d[attr] = value
        return module(*args, **kwargs)
    finally:
        for d, attr, old in saved:
            d[attr] = old


class ThunderFunction(torch.autograd.Function):
    """Stitches a compiled thunder_tpu fw/bw pair into torch autograd.

    ``apply(holder, *param_tensors)``: ``holder`` carries the compiled vjp
    runner and output structure; gradients are returned for the parameter
    tensors in the order given.  Residuals live in the pullback closure and
    are dropped right after backward (the reference's saved-tensor release
    contract, ``torch_autograd.py:57-78``).
    """

    @staticmethod
    def forward(ctx, holder: dict, *param_tensors: torch.Tensor):
        out, pullback = holder["run"]()
        flat_out, out_spec = jax_tree_flatten(out)
        ctx._pullback = pullback
        ctx._holder = holder
        holder["out_spec"] = out_spec
        return tuple(_to_torch(o) for o in flat_out)

    @staticmethod
    def backward(ctx, *grad_outputs: torch.Tensor):
        holder = ctx._holder
        cts = [
            _to_jax(g) if g is not None else None
            for g in grad_outputs
        ]
        ct_tree = jax_tree_unflatten(holder["out_spec"], cts)
        grads_dict = ctx._pullback(ct_tree)
        del ctx._pullback  # release residuals eagerly (memory contract)
        names = holder["param_names"]
        out = tuple(
            _to_torch(grads_dict[n]) if grads_dict.get(n) is not None else None
            for n in names
        )
        return (None,) + out


def jax_tree_flatten(x):
    import jax.tree_util as jtu

    return jtu.tree_flatten(x)


def jax_tree_unflatten(spec, leaves):
    import jax.tree_util as jtu

    return jtu.tree_unflatten(spec, leaves)


class ThunderModule(torch.nn.Module):
    """Wraps a ``torch.nn.Module`` so its forward runs as a compiled
    thunder_tpu program while torch autograd keeps working on the outside.

    ``thunder_tpu.jit(module)`` returns one of these (reference
    ``thunder.jit`` on modules, ``thunder/__init__.py:181``).
    """

    def __init__(self, module: torch.nn.Module, **jit_kwargs):
        super().__init__()
        self._orig_mod = module
        self._jit_kwargs = jit_kwargs
        self._vjp_fn = None  # built lazily (imports thunder_tpu)
        # torch→jax transfer cache keyed by (tensor identity, version): params
        # only re-upload after an in-place update (optimizer step), not on
        # every forward
        self._xfer_cache: dict[str, tuple[tuple[int, int], Any]] = {}

    def _get_vjp(self):
        if self._vjp_fn is None:
            import thunder_tpu as ttpu

            module = self._orig_mod

            def functional_fwd(params, buffers, *args, **kwargs):
                return functional_call(module, {**params, **buffers}, args, kwargs)

            self._vjp_fn = ttpu.vjp(functional_fwd, argnums=(0,), **self._jit_kwargs)
        return self._vjp_fn

    def _cached_to_jax(self, name: str, t: torch.Tensor):
        key = (id(t), t._version)
        ent = self._xfer_cache.get(name)
        if ent is not None and ent[0] == key:
            return ent[1]
        a = _to_jax(t)
        self._xfer_cache[name] = (key, a)
        return a

    def forward(self, *args, **kwargs):
        vjp_fn = self._get_vjp()
        params = dict(self._orig_mod.named_parameters())
        buffers = dict(self._orig_mod.named_buffers())
        param_names = sorted(params)
        param_tensors = [params[n] for n in param_names]

        jax_params = {n: self._cached_to_jax(n, p) for n, p in params.items()}
        jax_buffers = {n: self._cached_to_jax(n, b) for n, b in buffers.items()}
        jax_args = tuple(_to_jax(a) if isinstance(a, torch.Tensor) else a for a in args)
        jax_kwargs = {
            k: _to_jax(v) if isinstance(v, torch.Tensor) else v for k, v in kwargs.items()
        }

        holder = {
            "run": lambda: vjp_fn(jax_params, jax_buffers, *jax_args, **jax_kwargs),
            "param_names": param_names,
        }
        flat_out = ThunderFunction.apply(holder, *param_tensors)
        out = jax_tree_unflatten(holder["out_spec"], list(flat_out))
        return out

    # reference ThunderModule passes state_dict through to the wrapped module
    def state_dict(self, *args, **kwargs):
        return self._orig_mod.state_dict(*args, **kwargs)

    def load_state_dict(self, *args, **kwargs):
        return self._orig_mod.load_state_dict(*args, **kwargs)

    def named_parameters(self, *args, **kwargs):
        return self._orig_mod.named_parameters(*args, **kwargs)

    def parameters(self, *args, **kwargs):
        return self._orig_mod.parameters(*args, **kwargs)
