"""The torch-like operation surface ("ltorch").

Capability analog of the reference's ``thunder/torch/__init__.py`` (173
``@torchsymbol`` ops, ``_torch_to_thunder_function_map`` :61).  Each op is a
non-prim Symbol whose meta is its decomposition into clang/prims, so executors
can claim it whole (e.g. Pallas flash attention claiming
``scaled_dot_product_attention``) or execute its decomposition.

Real ``torch.*`` functions map here via ``_torch_to_thunder_function_map``;
combined with ``TensorProxy.__torch_function__`` this lets user code written
against torch run under thunder_tpu tracing without a bytecode interpreter.
"""
from __future__ import annotations

import builtins
import functools
import math
import sys
from numbers import Number
from typing import Any, Callable, Sequence

from thunder_tpu import clang
from thunder_tpu.core import dtypes, prims, utils
from thunder_tpu.core.baseutils import check
from thunder_tpu.core.devices import Device, to_device
from thunder_tpu.core.langctxs import LanguageContext, Languages, register_langctx
from thunder_tpu.core.proxies import NumberProxy, TensorProxy, pyval
from thunder_tpu.core.symbol import Symbol

_this_module = sys.modules[__name__]
__print_alias__ = "ltorch"

#
# Language context: tensor methods resolve here
#

_torch_ctx = LanguageContext("torch")
register_langctx(Languages.TORCH, _torch_ctx)

_torch_to_thunder_function_map: dict[Any, Callable] = {}

_torchsymbols: dict[str, Symbol] = {}


class torchsymbol:
    def __init__(self, *torchfns, is_method: bool = False, method_name: str | None = None, id: str | None = None):
        self.torchfns = torchfns
        self.is_method = is_method
        self.method_name = method_name
        self.id = id

    def __call__(self, fn: Callable) -> Symbol:
        name = fn.__name__
        # real torch.Tensor operands bake to constant proxies centrally in
        # Symbol.__call__ (pre-bind), so the meta needs no wrapping here
        sym = Symbol(name=name, meta=fn, id=self.id or f"torch.{name}", module=_this_module)
        _torchsymbols[name] = sym
        if self.is_method or self.method_name is not None:
            _torch_ctx.register_method(self.method_name or name, sym)
        for tfn in self.torchfns:
            if tfn is not None:
                _torch_to_thunder_function_map[tfn] = sym
        return sym


def _maybe_torch():
    try:
        import torch as _t

        return _t
    except ImportError:  # pragma: no cover
        return None


_torch = _maybe_torch()


def _tfn(*path: str):
    """Resolves torch.<path> safely (None when torch is unavailable)."""
    obj = _torch
    for p in path:
        if obj is None:
            return None
        obj = getattr(obj, p, None)
    return obj


#
# Elementwise unary
#

_unary_ops = [
    "abs", "acos", "acosh", "asin", "asinh", "atan", "atanh", "ceil", "cos", "cosh",
    "digamma", "erf", "erfc", "erfinv", "exp", "exp2", "expm1", "floor", "isfinite",
    "isinf", "isnan", "lgamma", "log", "log10", "log1p", "log2", "neg", "reciprocal",
    "round", "rsqrt", "sign", "signbit", "sin", "sinh", "sqrt", "tan", "tanh", "trunc",
    "real", "bitwise_not",
]


def _make_unary(opname: str) -> Symbol:
    clang_fn = getattr(clang, opname)

    def meta(a):
        return clang_fn(a)

    meta.__name__ = opname
    sym = torchsymbol(_tfn(opname), is_method=True)(meta)
    return sym


for _op in _unary_ops:
    setattr(_this_module, _op, _make_unary(_op))

#
# Elementwise binary
#

_binary_ops = [
    ("add", "add"),
    ("sub", "sub"),
    ("mul", "mul"),
    ("true_divide", "true_divide"),
    ("floor_divide", "floor_divide"),
    ("pow", "pow"),
    ("remainder", "remainder"),
    ("fmod", "fmod"),
    ("atan2", "atan2"),
    ("eq", "eq"),
    ("ne", "ne"),
    ("ge", "ge"),
    ("gt", "gt"),
    ("le", "le"),
    ("lt", "lt"),
    ("maximum", "maximum"),
    ("minimum", "minimum"),
    ("bitwise_and", "bitwise_and"),
    ("bitwise_or", "bitwise_or"),
    ("bitwise_xor", "bitwise_xor"),
    ("copysign", "copysign"),
    ("nextafter", "nextafter"),
]


def _make_binary(name: str, clang_name: str) -> Symbol:
    clang_fn = getattr(clang, clang_name)

    def meta(a, b, *, alpha=None):
        if alpha is not None and alpha != 1:
            b = clang.mul(b, alpha)
        return clang_fn(a, b)

    meta.__name__ = name
    sym = torchsymbol(_tfn(name), is_method=True)(meta)
    return sym


for _name, _cname in _binary_ops:
    setattr(_this_module, _name, _make_binary(_name, _cname))

_torch_to_thunder_function_map[_tfn("div")] = getattr(_this_module, "true_divide")
_torch_ctx.register_method("div", getattr(_this_module, "true_divide"))


@torchsymbol(_tfn("logical_and"))
def logical_and(a, b):
    return clang.bitwise_and(_to_bool(a), _to_bool(b))


@torchsymbol(_tfn("logical_or"))
def logical_or(a, b):
    return clang.bitwise_or(_to_bool(a), _to_bool(b))


@torchsymbol(_tfn("logical_not"))
def logical_not(a):
    return clang.bitwise_not(_to_bool(a))


def _to_bool(a):
    if isinstance(a, TensorProxy) and not dtypes.is_boolean_dtype(a.dtype):
        return clang.ne(a, 0)
    return a


@torchsymbol(_tfn("where"), is_method=True)
def where(pred, a, b):
    return clang.where(pred, a, b)


@torchsymbol(_tfn("clamp"), is_method=True)
def clamp(a, min=None, max=None):
    check(min is not None or max is not None,
          lambda: "clamp: at least one of min or max must not be None")
    return clang.clamp(a, min, max)


@torchsymbol(_tfn("clip"))
def clip(a, min=None, max=None):
    return clamp(a, min, max)


@torchsymbol(_tfn("masked_fill"), is_method=True)
def masked_fill(a, mask, value):
    return clang.where(mask, value, a)


@torchsymbol(_tfn("tril"), is_method=True)
def tril(a, diagonal: int = 0):
    check(a.ndim >= 2, lambda: "tril requires at least 2 dims")
    nrows, ncols = a.shape[-2], a.shape[-1]
    row = clang.arange(0, nrows, device=a.device, dtype=dtypes.int32)
    col = clang.arange(0, ncols, device=a.device, dtype=dtypes.int32)
    row = clang.reshape(row, (nrows, 1))
    col = clang.reshape(col, (1, ncols))
    mask = clang.ge(clang.sub(clang.add(row, diagonal), col), 0)
    return clang.where(mask, a, 0)


@torchsymbol(_tfn("triu"), is_method=True)
def triu(a, diagonal: int = 0):
    check(a.ndim >= 2, lambda: "triu requires at least 2 dims")
    nrows, ncols = a.shape[-2], a.shape[-1]
    row = clang.arange(0, nrows, device=a.device, dtype=dtypes.int32)
    col = clang.arange(0, ncols, device=a.device, dtype=dtypes.int32)
    row = clang.reshape(row, (nrows, 1))
    col = clang.reshape(col, (1, ncols))
    mask = clang.le(clang.sub(clang.add(row, diagonal), col), 0)
    return clang.where(mask, a, 0)


#
# Creation
#


@torchsymbol(_tfn("full"))
def full(size, fill_value, *, device=None, dtype=None):
    return clang.full(size, fill_value, device=device, dtype=_to_thunder_dtype(dtype))


@torchsymbol(_tfn("full_like"))
def full_like(a, fill_value, *, device=None, dtype=None):
    return clang.full_like(a, fill_value, device=device, dtype=_to_thunder_dtype(dtype))


@torchsymbol(_tfn("zeros"))
def zeros(*size, device=None, dtype=None):
    size = _flatten_size(size)
    return clang.zeros(size, device=device, dtype=_to_thunder_dtype(dtype))


@torchsymbol(_tfn("ones"))
def ones(*size, device=None, dtype=None):
    size = _flatten_size(size)
    return clang.ones(size, device=device, dtype=_to_thunder_dtype(dtype))


@torchsymbol(_tfn("zeros_like"))
def zeros_like(a, *, device=None, dtype=None):
    return clang.zeros_like(a, device=device, dtype=_to_thunder_dtype(dtype))


@torchsymbol(_tfn("ones_like"))
def ones_like(a, *, device=None, dtype=None):
    return clang.ones_like(a, device=device, dtype=_to_thunder_dtype(dtype))


@torchsymbol(_tfn("empty"))
def empty(*size, device=None, dtype=None):
    size = _flatten_size(size)
    return clang.zeros(size, device=device, dtype=_to_thunder_dtype(dtype))


@torchsymbol(is_method=True)
def new_ones(a, *size, device=None, dtype=None):
    size = _flatten_size(size)
    return clang.full(
        size, 1, device=device or a.device, dtype=_to_thunder_dtype(dtype) or a.dtype
    )


@torchsymbol(is_method=True)
def new_zeros(a, *size, device=None, dtype=None):
    size = _flatten_size(size)
    return clang.full(
        size, 0, device=device or a.device, dtype=_to_thunder_dtype(dtype) or a.dtype
    )


@torchsymbol(is_method=True)
def new_full(a, size, fill_value, *, device=None, dtype=None):
    return clang.full(
        size, fill_value, device=device or a.device, dtype=_to_thunder_dtype(dtype) or a.dtype
    )


@torchsymbol(_tfn("arange"))
def arange(start, end=None, step=1, *, device=None, dtype=None):
    return clang.arange(start, end, step, device=device, dtype=_to_thunder_dtype(dtype))


@torchsymbol(_tfn("rand"))
def rand(*size, device=None, dtype=None):
    size = _flatten_size(size)
    return clang.uniform(size, 0.0, 1.0, device=device, dtype=_to_thunder_dtype(dtype))


@torchsymbol(_tfn("randn"))
def randn(*size, device=None, dtype=None):
    size = _flatten_size(size)
    return clang.randn(size, device=device, dtype=_to_thunder_dtype(dtype))


@torchsymbol(_tfn("randint"))
def randint(low, high=None, size=(), *, device=None, dtype=None):
    if high is None:
        low, high = 0, low
    return clang.randint(low, high, size, device=device, dtype=_to_thunder_dtype(dtype) or dtypes.int64)


@torchsymbol(_tfn("bernoulli"))
def bernoulli(a):
    return clang.bernoulli(a)


@torchsymbol(_tfn("uniform"))
def uniform(shape, minval=0.0, maxval=1.0, *, device=None, dtype=None):
    return clang.uniform(shape, minval, maxval, device=device, dtype=_to_thunder_dtype(dtype))


def _flatten_size(size) -> tuple:
    if len(size) == 1 and isinstance(size[0], (tuple, list)):
        return tuple(size[0])
    return tuple(size)


def _to_thunder_dtype(dtype):
    if dtype is None:
        return None
    if isinstance(dtype, dtypes.dtype) or dtypes.is_numbertype(dtype):
        return dtype
    return dtypes.to_dtype(dtype)


#
# Shape ops
#


@torchsymbol(_tfn("reshape"), is_method=True)
def reshape(a, *shape):
    shape = _flatten_size(shape)
    return clang.reshape(a, shape)


@torchsymbol(method_name="view")
def view(a, *shape):
    shape = _flatten_size(shape)
    return clang.reshape(a, shape)


@torchsymbol(method_name="view_as")
def view_as(a, b):
    return clang.reshape(a, b.shape)


@torchsymbol(_tfn("permute"), is_method=True)
def permute(a, *dims):
    dims = _flatten_size(dims)
    return clang.permute(a, dims)


@torchsymbol(_tfn("transpose"), is_method=True)
def transpose(a, dim0, dim1):
    return clang.transpose(a, dim0, dim1)


@torchsymbol(_tfn("t"), is_method=True)
def t(a):
    check(a.ndim <= 2, lambda: "t() requires a tensor with at most 2 dims")
    if a.ndim < 2:
        return a
    return clang.transpose(a, 0, 1)


@torchsymbol(method_name="matrix_transpose")
def matrix_transpose(a):
    check(a.ndim >= 2, lambda: ".mT requires at least 2 dims")
    return clang.transpose(a, -2, -1)


@torchsymbol(_tfn("squeeze"), is_method=True)
def squeeze(a, dim=None):
    return clang.squeeze(a, dim)


@torchsymbol(_tfn("unsqueeze"), is_method=True)
def unsqueeze(a, dim):
    return clang.unsqueeze(a, dim)


@torchsymbol(_tfn("flatten"), is_method=True)
def flatten(a, start_dim=0, end_dim=-1):
    return clang.flatten(a, start_dim, end_dim)


@torchsymbol(_tfn("cat"), _tfn("concat"))
def cat(tensors, dim=0):
    return clang.cat(list(tensors), dim)


@torchsymbol(_tfn("stack"))
def stack(tensors, dim=0):
    return clang.stack(list(tensors), dim)


@torchsymbol(_tfn("split"), is_method=True)
def split(a, split_size_or_sections, dim=0):
    return clang.split(a, split_size_or_sections, dim)


@torchsymbol(_tfn("chunk"), is_method=True)
def chunk(a, chunks, dim=0):
    check(isinstance(chunks, (int, NumberProxy)) and chunks > 0,
          lambda: f"chunk expects chunks > 0, got {chunks}")
    return clang.chunk(a, chunks, dim)


@torchsymbol(method_name="expand")
def expand(a, *shape):
    shape = _flatten_size(shape)
    return clang.expand(a, shape)


@torchsymbol(_tfn("broadcast_to"), method_name="broadcast_to")
def broadcast_to(a, shape):
    return clang.expand(a, shape)


@torchsymbol(_tfn("movedim"), is_method=True)
def movedim(a, source, destination):
    return clang.movedim(a, source, destination)


@torchsymbol(_tfn("flip"), is_method=True)
def flip(a, dims):
    return clang.flip(a, dims)


@torchsymbol(_tfn("narrow"), is_method=True)
def narrow(a, dim, start, length):
    return clang.slice_in_dim(a, start, start + length, dim=dim)


@torchsymbol(method_name="contiguous")
def contiguous(a):
    return a  # layout is XLA's concern on TPU


@torchsymbol(_tfn("clone"), is_method=True)
def clone(a, *, memory_format=None):
    """Tracing is functional, so clone's one obligation is a DISTINCT proxy:
    in-place edits (``__setitem__`` rebinding) on the clone must not follow
    the source object.  The same-dtype convert records a fresh named proxy;
    XLA folds it to nothing."""
    return prims.convert_element_type(a, a.dtype)


@torchsymbol(_tfn("repeat_interleave"), is_method=True)
def repeat_interleave(a, repeats: int, dim: int):
    dim = utils.canonicalize_dim(a.ndim, dim)
    b = clang.unsqueeze(a, dim + 1)
    target = list(b.shape)
    target[dim + 1] = repeats
    b = clang.expand(b, target)
    shape = list(a.shape)
    shape[dim] *= repeats
    return clang.reshape(b, shape)


@torchsymbol(_tfn("unfold"), is_method=True)
def unfold(a, dimension, size, step):
    return prims.unfold(a, dimension, size, step)


@torchsymbol(_tfn("roll"), is_method=True)
def roll(a, shifts, dims):
    if isinstance(shifts, int):
        shifts = (shifts,)
    if isinstance(dims, int):
        dims = (dims,)
    out = a
    for shift, dim in zip(shifts, dims):
        dim = utils.canonicalize_dim(a.ndim, dim)
        n = out.shape[dim]
        shift = shift % n if n else 0
        if shift == 0:
            continue
        left = clang.slice_in_dim(out, n - shift, n, dim=dim)
        right = clang.slice_in_dim(out, 0, n - shift, dim=dim)
        out = clang.cat([left, right], dim)
    return out


#
# Indexing
#


@torchsymbol(method_name="getitem")
def getitem(a, key):
    return clang.getitem(a, key)


@torchsymbol(method_name="setitem")
def setitem(a, key, value):
    """Functional basic-indexing assignment: returns ``a`` with
    ``a[key] = value``.  ``TensorProxy.__setitem__`` rebinds the Python
    object to this result, which gives in-place semantics under tracing
    (the HF mask-editing pattern ``m[:, :, :, :L] = m2.masked_fill(...)``).

    Supported keys: ints, stride-1 slices, Ellipsis.  Lowering: the value is
    broadcast into the selected region, zero-padded to ``a``'s shape, and
    merged with an iota-derived region mask — static shapes throughout, so
    XLA fuses the whole edit.
    """
    keyt = key if isinstance(key, tuple) else (key,)
    if any(k is Ellipsis for k in keyt):
        i = next(i for i, k in enumerate(keyt) if k is Ellipsis)
        n_spec = sum(1 for k in keyt if k is not Ellipsis)
        keyt = keyt[:i] + (slice(None),) * (a.ndim - n_spec) + keyt[i + 1 :]
    keyt = keyt + (slice(None),) * (a.ndim - len(keyt))
    check(len(keyt) == a.ndim, lambda: f"setitem: too many indices for rank {a.ndim}")

    starts, stops, value_dims = [], [], []
    for d, k in enumerate(keyt):
        n = a.shape[d]
        if isinstance(k, (int, NumberProxy)):
            ki = int(pyval(k) if isinstance(k, NumberProxy) else k)
            ki = ki + n if ki < 0 else ki
            check(0 <= ki < n, lambda: f"setitem: index {ki} out of range for dim {d} (size {n})")
            starts.append(ki)
            stops.append(ki + 1)
        elif isinstance(k, slice):
            start, stop, step = k.indices(n)
            check(step == 1, lambda: "setitem supports stride-1 slices only")
            starts.append(start)
            stops.append(builtins.max(start, stop))
            value_dims.append(d)
        else:
            raise NotImplementedError(
                "setitem supports int/slice/Ellipsis keys; use index_put for tensor indices"
            )
    region = tuple(stops[d] - starts[d] for d in range(a.ndim))

    if isinstance(value, TensorProxy):
        v = clang.maybe_convert_to_dtype(value, a.dtype)
        # torch broadcasting: extra LEADING size-1 dims beyond the selection
        # rank are legal (c[0, :] = ones(1, 8)) — strip them
        while v.ndim > len(value_dims) and v.shape[0] == 1:
            v = clang.reshape(v, v.shape[1:])
        check(
            v.ndim <= len(value_dims),
            lambda: f"setitem: value rank {v.ndim} exceeds selection rank {len(value_dims)}",
        )
        # right-align the value's dims against the sliced dims (torch
        # broadcasting), with int-indexed dims as size-1
        vshape = [1] * a.ndim
        for vd, d in zip(reversed(range(v.ndim)), reversed(value_dims)):
            vshape[d] = v.shape[vd]
        v = clang.reshape(v, tuple(vshape))
        v = clang.expand(v, region)
    else:
        v = clang.full(region, value, device=a.device, dtype=a.dtype)

    pad_cfg = tuple((starts[d], a.shape[d] - stops[d], 0) for d in range(a.ndim))
    v = clang.pad(v, 0, pad_cfg)

    mask = None
    for d in range(a.ndim):
        if starts[d] == 0 and stops[d] == a.shape[d]:
            continue  # full dim: no constraint
        row = clang.arange(0, a.shape[d], device=a.device, dtype=dtypes.int32)
        m = clang.bitwise_and(clang.ge(row, starts[d]), clang.lt(row, stops[d]))
        m = clang.reshape(m, (1,) * d + (a.shape[d],) + (1,) * (a.ndim - d - 1))
        mask = m if mask is None else clang.bitwise_and(mask, m)
    if mask is None:  # whole-tensor assignment
        return v
    return clang.where(mask, v, a)


@torchsymbol(_tfn("index_select"), is_method=True)
def index_select(a, dim, index):
    return clang.take(a, index, dim)


@torchsymbol(_tfn("gather"), is_method=True)
def gather(a, dim, index):
    return clang.gather(a, index, dim)


@torchsymbol(_tfn("scatter_add"), is_method=True)
def scatter_add(a, dim, index, src):
    return clang.scatter_add(a, index, src, dim)


@torchsymbol(_tfn("index_add"), is_method=True)
def index_add(a, dim, index, source):
    return clang.index_add(a, index, source, dim)


@torchsymbol(_tfn("index_put"), is_method=True)
def index_put(a, indices, values, accumulate=False):
    return clang.index_put(a, indices, values, accumulate)


@torchsymbol(_tfn("take_along_dim"), is_method=True)
def take_along_dim(a, indices, dim):
    return clang.take_along_axis(a, indices, dim)


#
# Type conversions
#


@torchsymbol(method_name="to")
def to(a, *args, **kwargs):
    device = kwargs.get("device")
    dtype = kwargs.get("dtype")
    for arg in args:
        if isinstance(arg, (dtypes.dtype,)) or (_torch is not None and isinstance(arg, _torch.dtype)):
            dtype = arg
        elif isinstance(arg, (str, Device)):
            try:
                device = to_device(arg)
            except Exception:
                pass
        elif isinstance(arg, TensorProxy):
            dtype, device = arg.dtype, arg.device
    out = a
    if dtype is not None:
        out = clang.maybe_convert_to_dtype(out, _to_thunder_dtype(dtype))
    if device is not None:
        out = clang.device_put(out, device)
    return out


@torchsymbol(method_name="type_as")
def type_as(a, b):
    return clang.maybe_convert_to_dtype(a, b.dtype)


def _conv_method(name, dt):
    def meta(a):
        return clang.maybe_convert_to_dtype(a, dt)

    meta.__name__ = name
    return torchsymbol(method_name=name)(meta)


float_ = _conv_method("float", dtypes.float32)
double = _conv_method("double", dtypes.float64)
half = _conv_method("half", dtypes.float16)
bfloat16_m = _conv_method("bfloat16", dtypes.bfloat16)
long = _conv_method("long", dtypes.int64)
int_ = _conv_method("int", dtypes.int32)
bool_ = _conv_method("bool", dtypes.bool8)


@torchsymbol(method_name="item")
def item(a):
    return clang.item(a)


@torchsymbol(method_name="type")
def type(a, dtype=None):
    if dtype is None:
        return a
    return clang.maybe_convert_to_dtype(a, _to_thunder_dtype(dtype))


#
# Reductions
#


@torchsymbol(_tfn("sum"), is_method=True)
def sum(a, dim=None, keepdim=False, *, dtype=None):
    return clang.sum(a, dim, keepdim, dtype=_to_thunder_dtype(dtype))


@torchsymbol(_tfn("mean"), is_method=True)
def mean(a, dim=None, keepdim=False, *, dtype=None):
    return clang.mean(a, dim, keepdim, dtype=_to_thunder_dtype(dtype))


@torchsymbol(_tfn("prod"), is_method=True)
def prod(a, dim=None, keepdim=False, *, dtype=None):
    return clang.prod(a, dim, keepdim, dtype=_to_thunder_dtype(dtype))


@torchsymbol(_tfn("amax"), is_method=True)
def amax(a, dim=None, keepdim=False):
    return clang.amax(a, dim, keepdim)


@torchsymbol(_tfn("amin"), is_method=True)
def amin(a, dim=None, keepdim=False):
    return clang.amin(a, dim, keepdim)


@torchsymbol(_tfn("max"), is_method=True)
def max(a, dim=None, keepdim=False):
    if dim is None:
        return clang.amax(a, None, False)
    if isinstance(dim, TensorProxy):  # torch.max(a, other): elementwise
        return clang.maximum(a, dim)
    dim = utils.canonicalize_dim(a.ndim, dim)
    values = clang.amax(a, dim, keepdim)
    indices = clang.argmax(a, dim, keepdim)
    return values, indices


@torchsymbol(_tfn("min"), is_method=True)
def min(a, dim=None, keepdim=False):
    if dim is None:
        return clang.amin(a, None, False)
    if isinstance(dim, TensorProxy):  # torch.min(a, other): elementwise
        return clang.minimum(a, dim)
    dim = utils.canonicalize_dim(a.ndim, dim)
    values = clang.amin(a, dim, keepdim)
    indices = clang.argmin(a, dim, keepdim)
    return values, indices


@torchsymbol(_tfn("var"), is_method=True)
def var(a, dim=None, keepdim=False, *, correction=1):
    return clang.var(a, dim, keepdim, correction=correction)


@torchsymbol(_tfn("std"), is_method=True)
def std(a, dim=None, keepdim=False, *, correction=1):
    return clang.std(a, dim, keepdim, correction=correction)


@torchsymbol(_tfn("var_mean"))
def var_mean(a, dim=None, keepdim=False, *, correction=1):
    return clang.var_mean(a, dim, keepdim, correction=correction)


@torchsymbol(_tfn("argmax"), is_method=True)
def argmax(a, dim=None, keepdim=False):
    return clang.argmax(a, dim, keepdim)


@torchsymbol(_tfn("argmin"), is_method=True)
def argmin(a, dim=None, keepdim=False):
    return clang.argmin(a, dim, keepdim)


@torchsymbol(_tfn("topk"), is_method=True)
def topk(a, k, dim=-1, largest=True, sorted=True):
    return clang.topk(a, k, dim, largest, sorted)


@torchsymbol(_tfn("sort"), is_method=True)
def sort(a, dim=-1, descending=False):
    return clang.sort(a, dim, descending)


@torchsymbol(_tfn("argsort"), is_method=True)
def argsort(a, dim=-1, descending=False):
    return clang.argsort(a, dim, descending)


@torchsymbol(_tfn("diff"), is_method=True)
def diff(a, n=1, dim=-1, prepend=None, append=None):
    pieces = [x for x in (prepend, a, append) if x is not None]
    if len(pieces) > 1:
        a = clang.cat(pieces, dim)
    for _ in range(n):
        d = a.shape[dim] if dim >= 0 else a.shape[dim + len(a.shape)]
        hi = clang.slice_in_dim(a, 1, d, dim=dim)
        lo = clang.slice_in_dim(a, 0, d - 1, dim=dim)
        a = hi - lo
    return a


@torchsymbol(_tfn("cumsum"), is_method=True)
def cumsum(a, dim, *, dtype=None):
    out = clang.cumsum(a, dim)
    if dtype is not None:
        out = clang.maybe_convert_to_dtype(out, _to_thunder_dtype(dtype))
    return out


@torchsymbol(_tfn("any"), is_method=True)
def any_(a, dim=None, keepdim=False):
    b = _to_bool(a)
    s = clang.sum(clang.maybe_convert_to_dtype(b, dtypes.int32), dim, keepdim)
    return clang.gt(s, 0)


@torchsymbol(_tfn("all"), is_method=True)
def all_(a, dim=None, keepdim=False):
    b = _to_bool(a)
    inv = clang.bitwise_not(b)
    s = clang.sum(clang.maybe_convert_to_dtype(inv, dtypes.int32), dim, keepdim)
    return clang.eq(s, 0)


#
# Matmul family
#


@torchsymbol(_tfn("matmul"), is_method=True)
def matmul(a, b):
    return clang.matmul(a, b)


@torchsymbol(_tfn("mm"))
def mm(a, b):
    check(a.ndim == 2 and b.ndim == 2, lambda: "mm requires 2D tensors")
    return clang.matmul(a, b)


@torchsymbol(_tfn("bmm"), is_method=True)
def bmm(a, b):
    check(a.ndim == 3 and b.ndim == 3, lambda: "bmm requires 3D tensors")
    return clang.matmul(a, b)


@torchsymbol(_tfn("addmm"))
def addmm(bias, a, b, *, beta=1, alpha=1):
    out = clang.matmul(a, b)
    if alpha != 1:
        out = clang.mul(out, alpha)
    if beta != 1:
        bias = clang.mul(bias, beta)
    return clang.add(out, bias)


@torchsymbol(_tfn("outer"), is_method=True)
def outer(a, b):
    return clang.mul(clang.reshape(a, (a.shape[0], 1)), clang.reshape(b, (1, b.shape[0])))


#
# NN functional ops
#


@torchsymbol(_tfn("nn", "functional", "linear"))
def linear(a, w, bias=None):
    return clang.linear(a, w, bias)


@torchsymbol(_tfn("nn", "functional", "embedding"))
def embedding(indices, weight, padding_idx=None, max_norm=None, norm_type=2.0, scale_grad_by_freq=False, sparse=False):
    check(max_norm is None, lambda: "embedding max_norm is not supported")
    return clang.embedding(indices, weight, padding_idx=padding_idx)


@torchsymbol(_tfn("nn", "functional", "one_hot"))
def one_hot(a, num_classes):
    return clang.one_hot(a, num_classes)


@torchsymbol(_tfn("relu"), _tfn("nn", "functional", "relu"), is_method=True)
def relu(a, inplace=False):
    return clang.maximum(a, 0)


@torchsymbol(_tfn("nn", "functional", "relu6"))
def relu6(a, inplace=False):
    return clang.clamp(a, 0, 6)


@torchsymbol(_tfn("nn", "functional", "leaky_relu"))
def leaky_relu(a, negative_slope=0.01, inplace=False):
    return clang.where(clang.gt(a, 0), a, clang.mul(a, negative_slope))


@torchsymbol(_tfn("sigmoid"), _tfn("nn", "functional", "sigmoid"), is_method=True)
def sigmoid(a):
    return clang.reciprocal(clang.add(clang.exp(clang.neg(a)), 1.0))


@torchsymbol(_tfn("nn", "functional", "softplus"))
def softplus(a, beta=1.0, threshold=20.0):
    scaled = clang.mul(a, beta)
    soft = clang.true_divide(clang.log1p(clang.exp(scaled)), beta)
    return clang.where(clang.gt(scaled, threshold), a, soft)


@torchsymbol(_tfn("nn", "functional", "silu"))
def silu(a, inplace=False):
    return clang.mul(a, sigmoid(a))


@torchsymbol(_tfn("nn", "functional", "mish"))
def mish(a, inplace=False):
    return clang.mul(a, clang.tanh(softplus(a)))


@torchsymbol(_tfn("nn", "functional", "gelu"))
def gelu(a, approximate: str = "none"):
    check(approximate in ("none", "tanh"),
          lambda: f"gelu: approximate must be 'none' or 'tanh', got {approximate!r}")
    if approximate == "tanh":
        inner = clang.mul(
            math.sqrt(2.0 / math.pi), clang.add(a, clang.mul(0.044715, clang.mul(a, clang.mul(a, a))))
        )
        return clang.mul(clang.mul(0.5, a), clang.add(1.0, clang.tanh(inner)))
    return clang.mul(clang.mul(0.5, a), clang.add(1.0, clang.erf(clang.true_divide(a, math.sqrt(2.0)))))


@torchsymbol(_tfn("softmax"), _tfn("nn", "functional", "softmax"), is_method=True)
def softmax(a, dim=-1, *, dtype=None, _stacklevel=3):
    dim = utils.canonicalize_dim(a.ndim, dim)
    computation_dtype = _to_thunder_dtype(dtype) or (dtypes.float32 if dtypes.is_low_precision_dtype(a.dtype) else a.dtype)
    a_ = clang.maybe_convert_to_dtype(a, computation_dtype)
    m = clang.amax(a_, dim, True)
    e = clang.exp(clang.sub(a_, m))
    s = clang.sum(e, dim, True)
    out = clang.true_divide(e, s)
    if dtype is None:
        out = clang.maybe_convert_to_dtype(out, a.dtype)
    return out


@torchsymbol(_tfn("log_softmax"), _tfn("nn", "functional", "log_softmax"), is_method=True)
def log_softmax(a, dim=-1, *, dtype=None, _stacklevel=3):
    dim = utils.canonicalize_dim(a.ndim, dim)
    computation_dtype = _to_thunder_dtype(dtype) or (dtypes.float32 if dtypes.is_low_precision_dtype(a.dtype) else a.dtype)
    a_ = clang.maybe_convert_to_dtype(a, computation_dtype)
    m = clang.amax(a_, dim, True)
    shifted = clang.sub(a_, m)
    lse = clang.log(clang.sum(clang.exp(shifted), dim, True))
    out = clang.sub(shifted, lse)
    if dtype is None:
        out = clang.maybe_convert_to_dtype(out, a.dtype)
    return out


@torchsymbol(_tfn("nn", "functional", "dropout"))
def dropout(a, p=0.5, training=True, inplace=False):
    if not training or p == 0.0:
        return a
    check(0.0 <= p < 1.0, lambda: f"dropout p must be in [0, 1), got {p}")
    mask = clang.bernoulli(1.0 - p, a.shape, device=a.device, dtype=a.dtype)
    return clang.mul(clang.mul(a, mask), 1.0 / (1.0 - p))


@torchsymbol(_tfn("nn", "functional", "layer_norm"))
def layer_norm(a, normalized_shape, weight=None, bias=None, eps=1e-5):
    normalized_shape = tuple(normalized_shape)
    ndims = len(normalized_shape)
    check(
        tuple(a.shape[a.ndim - ndims :]) == normalized_shape,
        lambda: f"layer_norm: {normalized_shape} does not match input tail {a.shape}",
    )
    dims = tuple(range(a.ndim - ndims, a.ndim))
    computation_dtype = dtypes.float32 if dtypes.is_low_precision_dtype(a.dtype) else a.dtype
    a_ = clang.maybe_convert_to_dtype(a, computation_dtype)
    v, m = clang.var_mean(a_, dims, True, correction=0)
    rstd = clang.rsqrt(clang.add(v, eps))
    out = clang.mul(clang.sub(a_, m), rstd)
    if weight is not None:
        out = clang.mul(out, clang.maybe_convert_to_dtype(weight, computation_dtype))
    if bias is not None:
        out = clang.add(out, clang.maybe_convert_to_dtype(bias, computation_dtype))
    return clang.maybe_convert_to_dtype(out, a.dtype)


@torchsymbol(_tfn("nn", "functional", "rms_norm"))
def rms_norm(a, normalized_shape, weight=None, eps=None):
    normalized_shape = tuple(normalized_shape)
    ndims = len(normalized_shape)
    dims = tuple(range(a.ndim - ndims, a.ndim))
    if eps is None:
        eps = 1e-6
    computation_dtype = dtypes.float32 if dtypes.is_low_precision_dtype(a.dtype) else a.dtype
    a_ = clang.maybe_convert_to_dtype(a, computation_dtype)
    ms = clang.mean(clang.mul(a_, a_), dims, True)
    out = clang.mul(a_, clang.rsqrt(clang.add(ms, eps)))
    if weight is not None:
        out = clang.mul(out, clang.maybe_convert_to_dtype(weight, computation_dtype))
    return clang.maybe_convert_to_dtype(out, a.dtype)


@torchsymbol(_tfn("nn", "functional", "group_norm"))
def group_norm(a, num_groups, weight=None, bias=None, eps=1e-5):
    check(a.ndim >= 2, lambda: "group_norm requires at least 2 dims")
    N, C = a.shape[0], a.shape[1]
    check(C % num_groups == 0, lambda: "group_norm: channels not divisible by groups")
    rest = a.shape[2:]
    grouped = clang.reshape(a, (N, num_groups, C // num_groups) + tuple(rest))
    dims = tuple(range(2, grouped.ndim))
    computation_dtype = dtypes.float32 if dtypes.is_low_precision_dtype(a.dtype) else a.dtype
    g = clang.maybe_convert_to_dtype(grouped, computation_dtype)
    v, m = clang.var_mean(g, dims, True, correction=0)
    out = clang.mul(clang.sub(g, m), clang.rsqrt(clang.add(v, eps)))
    out = clang.reshape(out, a.shape)
    if weight is not None:
        w = clang.reshape(weight, (1, C) + (1,) * len(rest))
        out = clang.mul(out, clang.maybe_convert_to_dtype(w, computation_dtype))
    if bias is not None:
        b = clang.reshape(bias, (1, C) + (1,) * len(rest))
        out = clang.add(out, clang.maybe_convert_to_dtype(b, computation_dtype))
    return clang.maybe_convert_to_dtype(out, a.dtype)


@torchsymbol(_tfn("nn", "functional", "batch_norm"))
def batch_norm(a, running_mean=None, running_var=None, weight=None, bias=None, training=False, momentum=0.1, eps=1e-5):
    C = a.shape[1]
    reduce_dims = (0,) + tuple(range(2, a.ndim))
    computation_dtype = dtypes.float32 if dtypes.is_low_precision_dtype(a.dtype) else a.dtype
    a_ = clang.maybe_convert_to_dtype(a, computation_dtype)
    if training or running_mean is None:
        v, m = clang.var_mean(a_, reduce_dims, False, correction=0)
    else:
        m, v = running_mean, running_var
    bshape = (1, C) + (1,) * (a.ndim - 2)
    m_ = clang.reshape(clang.maybe_convert_to_dtype(m, computation_dtype), bshape)
    v_ = clang.reshape(clang.maybe_convert_to_dtype(v, computation_dtype), bshape)
    out = clang.mul(clang.sub(a_, m_), clang.rsqrt(clang.add(v_, eps)))
    if weight is not None:
        out = clang.mul(out, clang.reshape(clang.maybe_convert_to_dtype(weight, computation_dtype), bshape))
    if bias is not None:
        out = clang.add(out, clang.reshape(clang.maybe_convert_to_dtype(bias, computation_dtype), bshape))
    return clang.maybe_convert_to_dtype(out, a.dtype)


@torchsymbol(_tfn("conv1d"), _tfn("nn", "functional", "conv1d"))
def conv1d(a, weight, bias=None, stride=1, padding=0, dilation=1, groups=1):
    return _convnd(a, weight, bias, stride, padding, dilation, groups, 1)


@torchsymbol(_tfn("conv2d"), _tfn("nn", "functional", "conv2d"))
def conv2d(a, weight, bias=None, stride=1, padding=0, dilation=1, groups=1):
    return _convnd(a, weight, bias, stride, padding, dilation, groups, 2)


@torchsymbol(_tfn("conv3d"), _tfn("nn", "functional", "conv3d"))
def conv3d(a, weight, bias=None, stride=1, padding=0, dilation=1, groups=1):
    return _convnd(a, weight, bias, stride, padding, dilation, groups, 3)


def _convnd(a, weight, bias, stride, padding, dilation, groups, n):
    def _tup(x):
        return (x,) * n if isinstance(x, int) else tuple(x)

    return prims.convolution(a, weight, bias, _tup(stride), _tup(padding), _tup(dilation), False, (0,) * n, int(groups))


@torchsymbol(_tfn("nn", "functional", "scaled_dot_product_attention"))
def scaled_dot_product_attention(
    query, key, value, attn_mask=None, dropout_p=0.0, is_causal=False, scale=None, enable_gqa=False,
    sliding_window=None,
):
    """SDPA decomposition; the Pallas executor claims this whole symbol with a
    flash-attention kernel (analog of reference sdpaex/cudnnex claiming).

    Masked (bool or additive-float ``attn_mask``) and grouped-query
    (``enable_gqa`` / fewer K/V heads) calls route through the fused prim too
    — boolean masks are canonicalized to an additive float bias first, so HF
    padding-mask models keep O(T) attention residuals (reference checker
    matrix: sdpaex.py:240-474).  Only dropout and mask-needs-grad fall back
    to the explicit decomposition.
    """
    d = query.shape[-1]
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    if is_causal:
        check(attn_mask is None, lambda: "is_causal and attn_mask are mutually exclusive")
    gqa_ok = query.shape[:-2] == key.shape[:-2] == value.shape[:-2] or (
        query.ndim >= 3
        and key.ndim >= 3
        and key.shape[:-2] == value.shape[:-2]
        and query.shape[:-3] == key.shape[:-3]
        and key.shape[-3] != 0
        and query.shape[-3] % key.shape[-3] == 0
    )
    mask_ok = attn_mask is None or not getattr(attn_mask, "requires_grad", False)
    if dropout_p == 0.0 and gqa_ok and mask_ok:
        mask = attn_mask
        if mask is not None and dtypes.is_boolean_dtype(mask.dtype):
            # additive form: 0 where attended, a large-negative (not -inf:
            # exp(finite - lse) underflows to 0 without the inf-inf NaN) where
            # masked — matches the kernels' _MASK_VALUE convention
            zeros = clang.full_like(mask, 0.0, dtype=dtypes.float32)
            mask = clang.where(mask, zeros, -0.7 * 3.4028235e38)  # -0.7 * f32 max
        elif mask is not None:
            mask = clang.maybe_convert_to_dtype(mask, dtypes.float32)
        out, _lse = prims.sdpa(
            query, key, value, mask, bool(is_causal), float(scale),
            None if sliding_window is None else int(sliding_window),
        )
        return out
    check(
        sliding_window is None,
        lambda: "sliding_window is only supported on the fused sdpa path "
                "(no dropout, mask without requires_grad)",
    )
    if enable_gqa and query.shape[-3] != key.shape[-3]:
        rep = query.shape[-3] // key.shape[-3]
        key = repeat_interleave(key, rep, dim=-3)
        value = repeat_interleave(value, rep, dim=-3)
    q = clang.mul(query, scale)
    kt = clang.transpose(key, -2, -1)
    scores = clang.matmul(q, kt)
    L, S = query.shape[-2], key.shape[-2]
    if is_causal:
        check(attn_mask is None, lambda: "is_causal and attn_mask are mutually exclusive")
        row = clang.arange(0, L, device=query.device, dtype=dtypes.int32)
        col = clang.arange(0, S, device=query.device, dtype=dtypes.int32)
        causal = clang.ge(clang.reshape(row, (L, 1)), clang.reshape(col, (1, S)))
        scores = clang.where(causal, scores, float("-inf"))
    elif attn_mask is not None:
        if dtypes.is_boolean_dtype(attn_mask.dtype):
            scores = clang.where(attn_mask, scores, float("-inf"))
        else:
            scores = clang.add(scores, attn_mask)
    probs = softmax(scores, -1)
    if dropout_p > 0.0:
        probs = dropout(probs, dropout_p, training=True)
    return clang.matmul(probs, value)


@torchsymbol(_tfn("nn", "functional", "nll_loss"))
def nll_loss(log_probs, target, weight=None, size_average=None, ignore_index=-100, reduce=None, reduction="mean"):
    check(size_average is None and reduce is None, lambda: "legacy size_average/reduce are not supported; use reduction=")
    C = log_probs.shape[-1]
    flat_logp = clang.reshape(log_probs, (-1, C))
    flat_t = clang.reshape(target, (-1,))
    safe_t = clang.where(clang.eq(flat_t, ignore_index), 0, flat_t)
    safe_t = clang.maybe_convert_to_dtype(safe_t, dtypes.int32)
    idx = clang.reshape(safe_t, (-1, 1))
    picked = clang.take_along_axis(flat_logp, idx, 1)
    picked = clang.reshape(picked, (-1,))
    losses = clang.neg(picked)
    valid = clang.ne(flat_t, ignore_index)
    if weight is not None:
        # torch: per-sample loss scaled by weight[target]; mean divides by the
        # summed weights of the non-ignored samples
        w = clang.take(weight, safe_t, 0)
        losses = clang.mul(losses, w)
        norm = clang.where(valid, w, 0.0)
    else:
        norm = clang.maybe_convert_to_dtype(valid, losses.dtype)
    losses = clang.where(valid, losses, 0.0)
    if reduction == "none":
        return clang.reshape(losses, target.shape)
    total = clang.sum(losses, None, False)
    if reduction == "sum":
        return total
    return clang.true_divide(total, clang.maximum(clang.sum(norm, None, False), 1e-12))


@torchsymbol(_tfn("nn", "functional", "cross_entropy"))
def cross_entropy(logits, target, weight=None, size_average=None, ignore_index=-100, reduce=None, reduction="mean", label_smoothing=0.0):
    check(size_average is None and reduce is None, lambda: "legacy size_average/reduce are not supported; use reduction=")
    # fast path: fused row-wise CE prim (no (N, C) log-prob residual saved for
    # backward).  Class-index targets with the standard 2D/1D layouts only
    if (
        weight is None
        and label_smoothing == 0.0
        and reduction in ("mean", "sum", "none")
        and logits.ndim == 2
        and target.ndim == 1
        and dtypes.is_exact_dtype(target.dtype)
    ):
        safe_t = clang.where(clang.eq(target, ignore_index), 0, target)
        losses, _lse = prims.cross_entropy_fwd(logits, clang.maybe_convert_to_dtype(safe_t, dtypes.int32))
        valid = clang.ne(target, ignore_index)
        losses = clang.where(valid, losses, 0.0)
        # reductions accumulate in the prim's float32 row losses (torch keeps
        # f32 accumulation for low-precision logits); only the result is cast
        out_dtype = logits.dtype if dtypes.is_inexact_dtype(logits.dtype) else dtypes.float32
        if reduction == "none":
            return clang.maybe_convert_to_dtype(losses, out_dtype)
        total = clang.sum(losses, None, False)
        if reduction == "sum":
            return clang.maybe_convert_to_dtype(total, out_dtype)
        n_valid = clang.sum(clang.maybe_convert_to_dtype(valid, losses.dtype), None, False)
        mean = clang.true_divide(total, clang.maximum(n_valid, 1.0))
        return clang.maybe_convert_to_dtype(mean, out_dtype)
    dim = -1 if logits.ndim != 1 else 0
    if logits.ndim > 2:
        # torch layout: (N, C, d1, ...) -> log_softmax over C, move C last
        logp = log_softmax(logits, 1)
        perm = (0,) + tuple(range(2, logits.ndim)) + (1,)
        logp = clang.permute(logp, perm)
    else:
        logp = log_softmax(logits, dim)
    nll = nll_loss(logp, target, weight, ignore_index=ignore_index, reduction=reduction)
    if label_smoothing == 0.0:
        return nll
    # label smoothing (torch aten cross_entropy_loss_label_smoothing):
    # smooth_i = -sum_c w_c * logp[i, c]; final = (1-ls)*nll + ls/C * smooth
    C = logp.shape[-1]
    wl = clang.mul(logp, clang.reshape(weight, (1,) * (logp.ndim - 1) + (C,))) if weight is not None else logp
    smooth = clang.neg(clang.sum(wl, -1, False))
    flat_t = clang.reshape(target, (-1,))
    valid = clang.ne(flat_t, ignore_index)
    smooth = clang.where(clang.reshape(valid, smooth.shape), smooth, 0.0)
    if reduction == "sum":
        smooth_ret = clang.sum(smooth, None, False)
    elif reduction == "mean":
        if weight is not None:
            safe_t = clang.maybe_convert_to_dtype(clang.where(valid, flat_t, 0), dtypes.int32)
            norm = clang.where(valid, clang.take(weight, safe_t, 0), 0.0)
        else:
            norm = clang.maybe_convert_to_dtype(valid, smooth.dtype)
        smooth_ret = clang.true_divide(clang.sum(smooth, None, False), clang.maximum(clang.sum(norm, None, False), 1e-12))
    else:
        smooth_ret = smooth
    return clang.add(clang.mul(nll, 1.0 - label_smoothing), clang.mul(smooth_ret, label_smoothing / C))


@torchsymbol()
def fused_linear_cross_entropy(h, weight, target, ignore_index=-100, reduction="mean"):
    """Fused lm-head linear + cross-entropy: ``cross_entropy(h @ weight.T, target)``
    without materializing the (N, V) logits (thunder extension; the
    Liger-kernel-class capability — the reference's apex/triton CE executors
    take materialized logits, apex_entropyex.py:15).  Backward saves
    (h, weight, target, lse) and recomputes the softmax chunkwise.
    """
    check(h.ndim == 2, lambda: f"fused_linear_cross_entropy: h must be 2D, got {h.ndim}D")
    check(reduction in ("mean", "sum", "none"), lambda: f"unsupported reduction {reduction!r}")
    # ignore_index lives in ONE layer: the prim (executors mask both the row
    # losses and the backward's row cotangents); raw targets pass through.
    # The loss stays float32 regardless of h's dtype — the matmul accumulates
    # f32 and the plain gpt_loss path (CE over f32 logits) returns f32 too.
    losses, _lse = prims.fused_linear_ce(
        h, weight, clang.maybe_convert_to_dtype(target, dtypes.int32), int(ignore_index)
    )
    if reduction == "none":
        return losses
    total = clang.sum(losses, None, False)
    if reduction == "sum":
        return total
    valid = clang.ne(target, ignore_index)
    n_valid = clang.sum(clang.maybe_convert_to_dtype(valid, losses.dtype), None, False)
    return clang.true_divide(total, clang.maximum(n_valid, 1.0))


@torchsymbol(_tfn("nn", "functional", "mse_loss"))
def mse_loss(a, b, reduction="mean"):
    d = clang.sub(a, b)
    sq = clang.mul(d, d)
    if reduction == "none":
        return sq
    if reduction == "sum":
        return clang.sum(sq, None, False)
    return clang.mean(sq, None, False)


@torchsymbol(_tfn("nn", "functional", "l1_loss"))
def l1_loss(a, b, reduction="mean"):
    d = clang.abs(clang.sub(a, b))
    if reduction == "none":
        return d
    if reduction == "sum":
        return clang.sum(d, None, False)
    return clang.mean(d, None, False)


def _smooth_l1(a, b, beta):
    d = clang.sub(a, b)
    ad = clang.abs(d)
    quad = clang.true_divide(clang.mul(clang.mul(d, d), 0.5), beta)
    lin = clang.sub(ad, 0.5 * beta)
    return clang.where(clang.lt(ad, beta), quad, lin)


@torchsymbol(_tfn("nn", "functional", "smooth_l1_loss"))
def smooth_l1_loss(a, b, reduction="mean", beta=1.0):
    if beta == 0.0:
        return l1_loss(a, b, reduction)
    out = _smooth_l1(a, b, beta)
    if reduction == "none":
        return out
    if reduction == "sum":
        return clang.sum(out, None, False)
    return clang.mean(out, None, False)


@torchsymbol(_tfn("nn", "functional", "huber_loss"))
def huber_loss(a, b, reduction="mean", delta=1.0):
    # huber = delta * smooth_l1(beta=delta)
    out = clang.mul(_smooth_l1(a, b, delta), delta)
    if reduction == "none":
        return out
    if reduction == "sum":
        return clang.sum(out, None, False)
    return clang.mean(out, None, False)


@torchsymbol(_tfn("nn", "functional", "binary_cross_entropy"))
def binary_cross_entropy(a, target, weight=None, size_average=None, reduce=None, reduction="mean"):
    check(size_average is None and reduce is None, lambda: "legacy size_average/reduce are not supported; use reduction=")
    # torch clamps each log term at -100
    log_a = clang.maximum(clang.log(a), -100.0)
    log_1ma = clang.maximum(clang.log(clang.sub(1.0, a)), -100.0)
    out = clang.neg(clang.add(clang.mul(target, log_a), clang.mul(clang.sub(1.0, target), log_1ma)))
    if weight is not None:
        out = clang.mul(out, weight)
    if reduction == "none":
        return out
    if reduction == "sum":
        return clang.sum(out, None, False)
    return clang.mean(out, None, False)


@torchsymbol(_tfn("nn", "functional", "binary_cross_entropy_with_logits"))
def binary_cross_entropy_with_logits(a, target, weight=None, size_average=None, reduce=None, reduction="mean", pos_weight=None):
    check(size_average is None and reduce is None, lambda: "legacy size_average/reduce are not supported; use reduction=")
    # stable: max(x,0) - x*t + log1p(exp(-|x|)); pos_weight scales the t term
    softplus_nabs = clang.log1p(clang.exp(clang.neg(clang.abs(a))))
    if pos_weight is not None:
        # torch aten: loss = (1-t)·x + lw·(log1p(exp(-|x|)) + max(-x, 0)),
        # lw = 1 + (pos_weight - 1)·t
        log_w = clang.add(clang.mul(clang.sub(pos_weight, 1.0), target), 1.0)
        out = clang.add(
            clang.mul(clang.sub(1.0, target), a),
            clang.mul(log_w, clang.add(softplus_nabs, clang.maximum(clang.neg(a), 0.0))),
        )
    else:
        out = clang.add(clang.sub(clang.maximum(a, 0.0), clang.mul(a, target)), softplus_nabs)
    if weight is not None:
        out = clang.mul(out, weight)
    if reduction == "none":
        return out
    if reduction == "sum":
        return clang.sum(out, None, False)
    return clang.mean(out, None, False)


@torchsymbol(_tfn("nn", "functional", "kl_div"))
def kl_div(a, target, size_average=None, reduce=None, reduction="mean", log_target=False):
    check(size_average is None and reduce is None, lambda: "legacy size_average/reduce are not supported; use reduction=")
    if log_target:
        out = clang.mul(clang.exp(target), clang.sub(target, a))
    else:
        # torch zeroes the contribution where target == 0 (0·log0 := 0)
        safe = clang.where(clang.gt(target, 0), target, 1.0)
        out = clang.where(
            clang.gt(target, 0), clang.mul(target, clang.sub(clang.log(safe), a)), 0.0
        )
    if reduction == "none":
        return out
    total = clang.sum(out, None, False)
    if reduction == "sum":
        return total
    if reduction == "batchmean":
        return clang.true_divide(total, a.shape[0])
    return clang.mean(out, None, False)


@torchsymbol(_tfn("nn", "functional", "pad"))
def nn_pad(a, pad_widths, mode="constant", value=0.0):
    check(mode == "constant", lambda: "only constant padding is supported")
    check(len(pad_widths) % 2 == 0, lambda: "pad widths must be pairs")
    npairs = len(pad_widths) // 2
    config = [(0, 0, 0)] * (a.ndim - npairs)
    for i in range(npairs):
        lo = pad_widths[2 * i]
        hi = pad_widths[2 * i + 1]
        config.append((lo, hi, 0))
    # torch pads last dims first
    config = config[: a.ndim - npairs] + list(reversed(config[a.ndim - npairs :]))
    return clang.pad(a, value if value is not None else 0.0, config)


@torchsymbol(_tfn("nn", "functional", "normalize"))
def normalize(a, p=2.0, dim=1, eps=1e-12):
    norm = clang.pow(clang.sum(clang.pow(clang.abs(a), p), dim, True), 1.0 / p)
    return clang.true_divide(a, clang.maximum(norm, eps))


@torchsymbol(_tfn("erf"), id="torch.special.erf")
def special_erf(a):
    return clang.erf(a)


@torchsymbol(_tfn("polar"))
def polar(abs_t, angle):
    real = clang.mul(abs_t, clang.cos(angle))
    imag = clang.mul(abs_t, clang.sin(angle))
    return real, imag


@torchsymbol(_tfn("sgn"), is_method=True)
def sgn(a):
    return clang.sign(a)


@torchsymbol(_tfn("square"), is_method=True)
def square(a):
    return clang.mul(a, a)


@torchsymbol(_tfn("nn", "functional", "glu"))
def glu(a, dim=-1):
    dim = utils.canonicalize_dim(a.ndim, dim)
    check(a.shape[dim] % 2 == 0, lambda: "glu: dim size must be even")
    x, g = clang.chunk(a, 2, dim)
    return clang.mul(x, sigmoid(g))


@torchsymbol(_tfn("lerp"), is_method=True)
def lerp(start, end, weight):
    return clang.add(start, clang.mul(clang.sub(end, start), weight))


@torchsymbol(_tfn("nn", "functional", "hardswish"))
def hardswish(a, inplace=False):
    return clang.mul(a, clang.true_divide(clang.clamp(clang.add(a, 3.0), 0.0, 6.0), 6.0))


@torchsymbol(_tfn("nn", "functional", "hardsigmoid"))
def hardsigmoid(a, inplace=False):
    return clang.true_divide(clang.clamp(clang.add(a, 3.0), 0.0, 6.0), 6.0)


@torchsymbol(_tfn("nn", "functional", "tanhshrink"))
def tanhshrink(a):
    return clang.sub(a, clang.tanh(a))


@torchsymbol(_tfn("nn", "functional", "elu"))
def elu(a, alpha=1.0, inplace=False):
    return clang.where(clang.gt(a, 0), a, clang.mul(alpha, clang.expm1(a)))


@torchsymbol(_tfn("nn", "functional", "selu"))
def selu(a, inplace=False):
    alpha = 1.6732632423543772
    scale = 1.0507009873554805
    return clang.mul(scale, clang.where(clang.gt(a, 0), a, clang.mul(alpha, clang.expm1(a))))


@torchsymbol(_tfn("nn", "functional", "celu"))
def celu(a, alpha=1.0, inplace=False):
    return clang.where(clang.gt(a, 0), a, clang.mul(alpha, clang.expm1(clang.true_divide(a, alpha))))


@torchsymbol(_tfn("nn", "functional", "hardtanh"))
def hardtanh(a, min_val=-1.0, max_val=1.0, inplace=False):
    return clang.clamp(a, min_val, max_val)


@torchsymbol(_tfn("nn", "functional", "logsigmoid"))
def logsigmoid(a):
    return clang.neg(softplus(clang.neg(a)))


@torchsymbol(_tfn("logsumexp"), is_method=True)
def logsumexp(a, dim, keepdim=False):
    computation_dtype = dtypes.float32 if dtypes.is_low_precision_dtype(a.dtype) else a.dtype
    af = clang.maybe_convert_to_dtype(a, computation_dtype)
    m = clang.amax(af, dim, True)
    # masked-out -inf rows: keep the max finite so exp(-inf - -inf) never NaNs
    m_safe = clang.where(clang.isfinite(m), m, 0.0)
    s = clang.sum(clang.exp(clang.sub(af, m_safe)), dim, keepdim)
    m_out = m if keepdim else clang.squeeze(m, (utils.canonicalize_dim(a.ndim, dim),))
    out = clang.add(clang.log(s), clang.where(clang.isfinite(m_out), m_out, 0.0))
    return clang.maybe_convert_to_dtype(out, a.dtype)


@torchsymbol(_tfn("logaddexp"), is_method=True)
def logaddexp(a, b):
    m = clang.maximum(a, b)
    stable = clang.add(m, clang.log1p(clang.exp(clang.neg(clang.abs(clang.sub(a, b))))))
    # equal infinities: a-b is NaN there, but the result is the infinity
    # itself (torch semantics: logaddexp(-inf, -inf) = -inf)
    inf_pair = logical_and(clang.isinf(a), clang.eq(a, b))
    return clang.where(inf_pair, a, stable)


@torchsymbol(_tfn("nan_to_num"), is_method=True)
def nan_to_num(a, nan=0.0, posinf=None, neginf=None):
    if dtypes.is_exact_dtype(a.dtype):
        return a
    big = float(jnp_finfo_max(a.dtype))
    out = clang.where(clang.isnan(a), nan if nan is not None else 0.0, a)
    out = clang.where(clang.eq(out, float("inf")), posinf if posinf is not None else big, out)
    out = clang.where(clang.eq(out, float("-inf")), neginf if neginf is not None else -big, out)
    return out


def jnp_finfo_max(dt):
    import jax.numpy as jnp

    return jnp.finfo(dtypes.to_jax_dtype(dt)).max


@torchsymbol(_tfn("cumprod"), is_method=True)
def cumprod(a, dim, *, dtype=None):
    # torch casts the INPUT before accumulating — the dtype kwarg exists to
    # buy accumulation precision, not to cast the result
    if dtype is not None:
        a = clang.maybe_convert_to_dtype(a, _to_thunder_dtype(dtype))
    return clang.cumprod(a, utils.canonicalize_dim(a.ndim, dim))


@torchsymbol(_tfn("heaviside"), is_method=True)
def heaviside(a, values):
    # NaN maps to 0 in torch (only exact zero selects `values`)
    return clang.where(clang.eq(a, 0), values, clang.where(clang.gt(a, 0), 1.0, 0.0))


@torchsymbol(_tfn("hypot"), is_method=True)
def hypot(a, b):
    # scale-safe (torch.hypot contract): factor out the larger magnitude so
    # squaring can neither overflow (~1e20 inputs) nor flush subnormals
    aa, ab = clang.abs(a), clang.abs(b)
    m = clang.maximum(aa, ab)
    n = clang.minimum(aa, ab)
    r = clang.true_divide(n, clang.where(clang.eq(m, 0.0), 1.0, m))
    return clang.mul(m, clang.sqrt(clang.add(1.0, clang.mul(r, r))))


@torchsymbol(_tfn("clamp_min"), is_method=True)
def clamp_min(a, min):
    return clang.maximum(a, min)


@torchsymbol(_tfn("clamp_max"), is_method=True)
def clamp_max(a, max):
    return clang.minimum(a, max)


@torchsymbol(_tfn("addcmul"), is_method=True)
def addcmul(a, t1, t2, *, value=1):
    return clang.add(a, clang.mul(clang.mul(t1, t2), value))


@torchsymbol(_tfn("addcdiv"), is_method=True)
def addcdiv(a, t1, t2, *, value=1):
    return clang.add(a, clang.mul(clang.true_divide(t1, t2), value))


@torchsymbol(_tfn("frac"), is_method=True)
def frac(a):
    return clang.sub(a, clang.trunc(a))


@torchsymbol(_tfn("norm"), is_method=True)
def norm(a, p=2, dim=None, keepdim=False):
    check(p in (1, 2, "fro", float("inf")), lambda: f"norm: order {p!r} is not supported yet")
    if p == 1:
        return clang.sum(clang.abs(a), dim, keepdim)
    if p == float("inf"):
        return clang.amax(clang.abs(a), dim, keepdim)
    # 2 / fro
    return clang.sqrt(clang.sum(clang.mul(a, a), dim, keepdim))


@torchsymbol(_tfn("nn", "functional", "softmin"))
def softmin(a, dim=-1, *, dtype=None, _stacklevel=3):
    return softmax(clang.neg(a), dim, dtype=dtype)


@torchsymbol(_tfn("nn", "functional", "softshrink"))
def softshrink(a, lambd=0.5):
    return clang.where(
        clang.gt(a, lambd), clang.sub(a, lambd), clang.where(clang.lt(a, -lambd), clang.add(a, lambd), 0.0)
    )


@torchsymbol(_tfn("nn", "functional", "hardshrink"))
def hardshrink(a, lambd=0.5):
    return clang.where(clang.gt(clang.abs(a), lambd), a, 0.0)


@torchsymbol(_tfn("nn", "functional", "threshold"))
def threshold(a, threshold, value, inplace=False):
    return clang.where(clang.gt(a, threshold), a, value)


@torchsymbol(_tfn("nn", "functional", "prelu"))
def prelu(a, weight):
    if weight.numel != 1:
        check(a.ndim >= 2, lambda: "prelu: per-channel weight needs a channel dim")
        check(weight.numel == a.shape[1], lambda: f"prelu: weight numel {weight.numel} != channels {a.shape[1]}")
        w = clang.reshape(weight, (1, weight.numel) + (1,) * (a.ndim - 2))
    else:
        w = clang.reshape(weight, (1,) * a.ndim)
    return clang.where(clang.ge(a, 0), a, clang.mul(w, a))


@torchsymbol(_tfn("nn", "functional", "cosine_similarity"))
def cosine_similarity(x1, x2, dim=1, eps=1e-8):
    dot = clang.sum(clang.mul(x1, x2), dim, False)
    n1 = clang.sqrt(clang.sum(clang.mul(x1, x1), dim, False))
    n2 = clang.sqrt(clang.sum(clang.mul(x2, x2), dim, False))
    return clang.true_divide(dot, clang.maximum(clang.mul(n1, n2), eps))


#
# einsum / extra linalg (reference: thunder/torch/__init__.py einsum via opt_einsum;
# here a single EINSUM prim lowers straight to XLA dot_general on the MXU)
#


@torchsymbol(_tfn("einsum"))
def einsum(equation, *operands):
    if len(operands) == 1 and isinstance(operands[0], (tuple, list)):
        operands = tuple(operands[0])
    check(isinstance(equation, str), lambda: "einsum: only the string-equation form is supported")
    return prims.einsum(equation, *operands)


@torchsymbol(_tfn("mv"), is_method=True)
def mv(a, b):
    check(a.ndim == 2 and b.ndim == 1, lambda: f"mv: expected (n,m) @ (m,), got {a.shape} @ {b.shape}")
    return clang.matmul(a, b)


@torchsymbol(_tfn("dot"), is_method=True)
def dot(a, b):
    check(a.ndim == 1 and b.ndim == 1, lambda: f"dot: expected 1D tensors, got {a.shape} and {b.shape}")
    return clang.sum(clang.mul(a, b), None, False)


@torchsymbol(_tfn("vdot"))
def vdot(a, b):
    return dot(a, b)


@torchsymbol(_tfn("baddbmm"), is_method=True)
def baddbmm(input, batch1, batch2, *, beta=1, alpha=1):
    out = clang.matmul(batch1, batch2)
    if alpha != 1:
        out = clang.mul(out, alpha)
    if beta == 0:
        return out
    return clang.add(out, clang.mul(input, beta) if beta != 1 else input)


@torchsymbol(_tfn("unbind"), is_method=True)
def unbind(a, dim=0):
    dim = utils.canonicalize_dim(a.ndim, dim)
    return tuple(clang.squeeze(clang.slice_in_dim(a, i, i + 1, dim=dim), (dim,)) for i in range(a.shape[dim]))


@torchsymbol(_tfn("diagonal"), is_method=True)
def diagonal(a, offset=0, dim1=0, dim2=1):
    dim1 = utils.canonicalize_dim(a.ndim, dim1)
    dim2 = utils.canonicalize_dim(a.ndim, dim2)
    check(a.ndim == 2 and (dim1, dim2) == (0, 1), lambda: "diagonal: only 2D (dim1=0, dim2=1) is supported yet")
    rows, cols = a.shape
    if offset >= 0:
        length = builtins.min(rows, cols - offset)
        start = offset
    else:
        length = builtins.min(rows + offset, cols)
        start = -offset * cols
    check(length > 0, lambda: f"diagonal: offset {offset} out of range for shape {a.shape}")
    flat = clang.reshape(a, (rows * cols,))
    idx = clang.arange(start, start + length * (cols + 1), cols + 1, device=a.device, dtype=dtypes.int32)
    return clang.take(flat, idx, 0)


_diagonal_op = diagonal


@torchsymbol(_tfn("diag"), is_method=True)
def diag(a, diagonal=0):
    check(a.ndim in (1, 2), lambda: f"diag: expected 1D or 2D, got {a.ndim}D")
    if a.ndim == 2:
        return _diagonal_op(a, diagonal)
    n = a.shape[0] + builtins.abs(diagonal)
    flat = zeros(n * n, device=a.device, dtype=a.dtype)
    start = diagonal if diagonal >= 0 else -diagonal * n
    idx = clang.arange(start, start + a.shape[0] * (n + 1), n + 1, device=a.device, dtype=dtypes.int32)
    flat = clang.index_put(flat, (idx,), a, False)
    return clang.reshape(flat, (n, n))


def _tile_impl(a, reps):
    shape = (1,) * (len(reps) - a.ndim) + tuple(a.shape)
    out = clang.reshape(a, shape)
    # (s0, s1, ...) tiled by (r0, r1, ...): expand to (r0, s0, r1, s1, ...) then merge pairs
    inter = []
    target = []
    final = []
    for r, s in zip(reps, shape):
        inter.extend([1, s])
        target.extend([r, s])
        final.append(r * s)
    out = clang.reshape(out, tuple(inter))
    out = clang.broadcast_in_dim(out, tuple(target), tuple(range(len(target))))
    return clang.reshape(out, tuple(final))


@torchsymbol(_tfn("tile"), is_method=True)
def tile(a, *reps):
    if len(reps) == 1 and isinstance(reps[0], (tuple, list)):
        reps = tuple(reps[0])
    # torch.tile left-pads reps with 1s when shorter than ndim
    if len(reps) < a.ndim:
        reps = (1,) * (a.ndim - len(reps)) + tuple(reps)
    return _tile_impl(a, tuple(reps))


@torchsymbol(method_name="repeat")
def repeat(a, *reps):
    if len(reps) == 1 and isinstance(reps[0], (tuple, list)):
        reps = tuple(reps[0])
    check(len(reps) >= a.ndim, lambda: f"repeat: needs at least {a.ndim} repeat dims, got {len(reps)}")
    return _tile_impl(a, tuple(reps))


#
# Pooling (REDUCE_WINDOW prim → XLA ReduceWindow; reference max_pool/avg_pool
# live in thunder/torch/__init__.py)
#


def _pool_args(n, kernel_size, stride, padding):
    k = (kernel_size,) * n if isinstance(kernel_size, int) else tuple(kernel_size)
    s = k if stride is None or stride == [] else ((stride,) * n if isinstance(stride, int) else tuple(stride))
    p = (padding,) * n if isinstance(padding, int) else tuple(padding)
    check(len(k) == n and len(s) == n and len(p) == n, lambda: "pool: kernel/stride/padding rank mismatch")
    for pi, ki in zip(p, k):
        check(pi <= ki // 2, lambda: f"pool: padding {pi} must be at most half the kernel {ki}")
    return k, s, tuple((pi, pi) for pi in p)


def _max_poolnd(a, n, kernel_size, stride, padding, dilation, ceil_mode, return_indices):
    check(dilation in (1, (1,) * n, [1] * n), lambda: "max_pool: dilation is not supported yet")
    check(not ceil_mode, lambda: "max_pool: ceil_mode is not supported yet")
    check(not return_indices, lambda: "max_pool: return_indices is not supported yet")
    k, s, p = _pool_args(n, kernel_size, stride, padding)
    return prims.reduce_window(a, "max", k, s, p)


@torchsymbol(_tfn("nn", "functional", "max_pool1d"))
def max_pool1d(a, kernel_size, stride=None, padding=0, dilation=1, ceil_mode=False, return_indices=False):
    return _max_poolnd(a, 1, kernel_size, stride, padding, dilation, ceil_mode, return_indices)


@torchsymbol(_tfn("nn", "functional", "max_pool2d"))
def max_pool2d(a, kernel_size, stride=None, padding=0, dilation=1, ceil_mode=False, return_indices=False):
    return _max_poolnd(a, 2, kernel_size, stride, padding, dilation, ceil_mode, return_indices)


@torchsymbol(_tfn("nn", "functional", "max_pool3d"))
def max_pool3d(a, kernel_size, stride=None, padding=0, dilation=1, ceil_mode=False, return_indices=False):
    return _max_poolnd(a, 3, kernel_size, stride, padding, dilation, ceil_mode, return_indices)


def _avg_poolnd(a, n, kernel_size, stride, padding, ceil_mode, count_include_pad, divisor_override):
    check(not ceil_mode, lambda: "avg_pool: ceil_mode is not supported yet")
    k, s, p = _pool_args(n, kernel_size, stride, padding)
    summed = prims.reduce_window(a, "add", k, s, p)
    if divisor_override is not None:
        return clang.true_divide(summed, divisor_override)
    if count_include_pad or all(lo == 0 and hi == 0 for lo, hi in p):
        div = 1
        for ki in k:
            div *= ki
        return clang.true_divide(summed, div)
    counts = prims.reduce_window(clang.full_like(a, 1.0), "add", k, s, p)
    return clang.true_divide(summed, counts)


@torchsymbol(_tfn("nn", "functional", "avg_pool1d"))
def avg_pool1d(a, kernel_size, stride=None, padding=0, ceil_mode=False, count_include_pad=True):
    return _avg_poolnd(a, 1, kernel_size, stride, padding, ceil_mode, count_include_pad, None)


@torchsymbol(_tfn("nn", "functional", "avg_pool2d"))
def avg_pool2d(a, kernel_size, stride=None, padding=0, ceil_mode=False, count_include_pad=True, divisor_override=None):
    return _avg_poolnd(a, 2, kernel_size, stride, padding, ceil_mode, count_include_pad, divisor_override)


@torchsymbol(_tfn("nn", "functional", "avg_pool3d"))
def avg_pool3d(a, kernel_size, stride=None, padding=0, ceil_mode=False, count_include_pad=True, divisor_override=None):
    return _avg_poolnd(a, 3, kernel_size, stride, padding, ceil_mode, count_include_pad, divisor_override)


def _adaptive_avg_poolnd(a, n, output_size):
    out = (output_size,) * n if isinstance(output_size, int) else tuple(output_size)
    check(len(out) == n, lambda: f"adaptive_avg_pool{n}d: output_size rank mismatch")
    spatial = a.shape[a.ndim - n :]
    k = []
    for i, (inp, o) in enumerate(zip(spatial, out)):
        check(o >= 1, lambda: "adaptive_avg_pool: output_size must be positive")
        check(inp % o == 0, lambda: f"adaptive_avg_pool: input {inp} not divisible by output {o} (general case unsupported)")
        k.append(inp // o)
    summed = prims.reduce_window(a, "add", tuple(k), tuple(k), ((0, 0),) * n)
    return clang.true_divide(summed, math.prod(k))


@torchsymbol(_tfn("nn", "functional", "adaptive_avg_pool1d"))
def adaptive_avg_pool1d(a, output_size):
    return _adaptive_avg_poolnd(a, 1, output_size)


@torchsymbol(_tfn("nn", "functional", "adaptive_avg_pool2d"))
def adaptive_avg_pool2d(a, output_size):
    return _adaptive_avg_poolnd(a, 2, output_size)


@torchsymbol(_tfn("nn", "functional", "interpolate"))
def interpolate(a, size=None, scale_factor=None, mode="nearest", align_corners=None, recompute_scale_factor=None, antialias=False):
    """Reference: thunder/torch/__init__.py interpolate.  nearest matches the
    torch floor-index rule exactly via static gathers; linear modes lower to
    the RESIZE prim (half-pixel centers == torch align_corners=False)."""
    check(a.ndim >= 3, lambda: f"interpolate: expected (N, C, spatial...), got {a.ndim}D")
    check(not antialias, lambda: "interpolate: antialias is not supported yet")
    n = a.ndim - 2
    spatial = a.shape[2:]
    sf = None
    if size is not None:
        check(scale_factor is None, lambda: "interpolate: size and scale_factor are mutually exclusive")
        out = (size,) * n if isinstance(size, int) else tuple(size)
    else:
        check(scale_factor is not None, lambda: "interpolate: one of size/scale_factor is required")
        sf = (scale_factor,) * n if isinstance(scale_factor, (int, float)) else tuple(scale_factor)
        out = tuple(int(s * f) for s, f in zip(spatial, sf))
        if recompute_scale_factor:
            sf = None  # torch recomputes the scale from the integer sizes
    check(len(out) == n, lambda: "interpolate: size rank mismatch")
    if mode == "nearest":
        res = a
        for i, (inp, o) in enumerate(zip(spatial, out)):
            if o == inp:
                continue
            if sf is not None:
                # torch keeps the user scale (recompute_scale_factor=False
                # semantics): src = floor(dst / scale_factor)
                frac = clang.true_divide(
                    clang.arange(0, o, device=a.device, dtype=dtypes.float32), float(sf[i])
                )
                idx = clang.maybe_convert_to_dtype(clang.floor(frac), dtypes.int32)
                idx = clang.minimum(idx, inp - 1)
            else:
                # size= path: src = floor(dst * in / out)
                idx = clang.floor_divide(clang.mul(clang.arange(0, o, device=a.device, dtype=dtypes.int32), inp), o)
            res = clang.take(res, idx, 2 + i)
        return res
    check(align_corners is not True, lambda: "interpolate: align_corners=True is not supported yet")
    check(mode in ("linear", "bilinear", "trilinear", "bicubic"), lambda: f"interpolate: unknown mode {mode!r}")
    if sf is not None:
        # the RESIZE prim derives its scale from the shapes; that only equals
        # the torch coordinate map when out == in·sf exactly
        for s, o, f in zip(spatial, out, sf):
            check(
                builtins.abs(s * f - o) < 1e-9,
                lambda: "interpolate: fractional scale_factor with linear modes needs "
                "recompute_scale_factor=True (or pass size=) — shape-derived and "
                "user scales diverge otherwise",
            )
    return prims.resize(a, tuple(a.shape[:2]) + out, mode)


#
# size/shape introspection helpers (trace-time)
#


def size(a, dim=None):
    if dim is None:
        return a.shape
    return a.shape[utils.canonicalize_dim(a.ndim, dim)]


_torch_ctx.register_method("size", size)
_torch_ctx.register_method("dim", lambda a: a.ndim)
_torch_ctx.register_method("numel", lambda a: a.numel)


def manual_seed(seed: int) -> None:
    """Sets the global RNG seed for compiled programs (threefry base key)."""
    from thunder_tpu.core import rng

    rng.manual_seed(seed)


# torch.Tensor methods that map through __torch_function__
if _torch is not None:
    _method_map = {
        _torch.Tensor.add: getattr(_this_module, "add"),
        _torch.Tensor.mul: getattr(_this_module, "mul"),
        _torch.Tensor.sub: getattr(_this_module, "sub"),
        _torch.Tensor.div: getattr(_this_module, "true_divide"),
        _torch.Tensor.matmul: matmul,
        _torch.Tensor.sum: getattr(_this_module, "sum"),
        _torch.Tensor.mean: getattr(_this_module, "mean"),
        _torch.Tensor.reshape: reshape,
        _torch.Tensor.view: view,
        _torch.Tensor.permute: permute,
        _torch.Tensor.transpose: transpose,
        _torch.Tensor.softmax: softmax,
        _torch.Tensor.to: to,
        _torch.Tensor.float: float_,
        _torch.Tensor.contiguous: contiguous,
    }
    _torch_to_thunder_function_map.update({k: v for k, v in _method_map.items() if k is not None})


# torch-like dtype aliases (reference: torch.float32 etc. used throughout user code)
bool_ = dtypes.bool8
uint8 = dtypes.uint8
int8 = dtypes.int8
int16 = dtypes.int16
int32 = dtypes.int32
int64 = dtypes.int64
long = dtypes.int64
bfloat16 = dtypes.bfloat16
float16 = dtypes.float16
half = dtypes.float16
float32 = dtypes.float32
float64 = dtypes.float64
double = dtypes.float64
complex64 = dtypes.complex64
complex128 = dtypes.complex128
