"""Int8 quantization executor: the TransformerEngine-FP8 analog for TPU.

Capability analog of the reference's ``thunder/executors/transformer_engineex.py``
(:183-331 — functional fwd/bwd symbols that claim ``prims.linear`` and run it
in FP8 with dynamic scaling).  TPU v5e's MXU executes int8×int8→int32 at twice
the bf16 rate, so the TPU-native equivalent is dynamic **int8** quantization:

- activations are quantized per row (per token) with absmax scaling,
- weights per output channel with absmax scaling,
- the matmul accumulates in int32 (``preferred_element_type``), and the
  product of the two scales dequantizes the result.

The executor is **opt-in** (not a default executor): put ``quant_ex`` ahead of
the defaults in ``jit(..., executors=[quant_ex, *defaults])`` and it claims
``prims.linear`` / ``prims.matmul`` sites whose contraction is large enough
for quantization error to amortize (``min_k``, default 64).

Error model: absmax int8 keeps ~2 decimal digits; expect ~1e-2 relative error
on well-conditioned layers — the same contract TE's fp8 recipe offers.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from thunder_tpu.core.proxies import TensorProxy
from thunder_tpu.core import dtypes
from thunder_tpu.core.prims import PrimIDs, prim_lookup
from thunder_tpu.extend import OperatorExecutor, register_executor

__all__ = ["ex", "quant_ex", "int8_linear", "int8_matmul"]

ex = OperatorExecutor("quant_int8", version="0.1")
quant_ex = ex
register_executor(ex)

# claim threshold on the contraction dim: tiny K has nothing to amortize the
# quantize/dequantize traffic (and error) against
min_k = 64


def _quantize_lastdim(x):
    """absmax int8 over the last dim; returns (q, scale) with scale shaped to
    broadcast against the dot result."""
    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=-1, keepdims=True)
    scale = jnp.where(amax == 0.0, 1.0, amax / 127.0)
    q = jnp.clip(jnp.round(xf / scale), -127, 127).astype(jnp.int8)
    return q, scale


def int8_linear(a, w, bias=None):
    """``a @ w.T (+ bias)`` with both operands dynamically int8-quantized.

    a: (..., K); w: (N, K) — torch linear layout.  int32 accumulation on the
    MXU, float32 dequant, result cast back to ``a.dtype``.
    """
    qa, sa = _quantize_lastdim(a)  # (..., K), (..., 1)
    qw, sw = _quantize_lastdim(w)  # (N, K), (N, 1)
    acc = jax.lax.dot_general(
        qa, qw, (((qa.ndim - 1,), (1,)), ((), ())), preferred_element_type=jnp.int32
    )  # (..., N)
    out = acc.astype(jnp.float32) * sa * sw.reshape((1,) * (acc.ndim - 1) + (-1,))
    if bias is not None:
        out = out + bias.astype(jnp.float32)
    return out.astype(a.dtype)


def int8_matmul(a, b):
    """``a @ b`` with dynamic int8 quantization (2D/batched, torch matmul
    layout: contraction is a's last dim × b's second-to-last dim)."""
    if a.ndim == 1 or b.ndim == 1:  # matvec paths gain nothing; stay exact
        return jnp.matmul(a, b)
    qa, sa = _quantize_lastdim(a)  # scale (..., M, 1)
    # quantize b per output column: absmax over its contraction dim (-2)
    bf = jnp.swapaxes(b.astype(jnp.float32), -1, -2)  # (..., N, K)
    qb, sb = _quantize_lastdim(bf)  # (..., N, K), (..., N, 1)
    qb = jnp.swapaxes(qb, -1, -2)  # (..., K, N)
    acc = jnp.matmul(qa, qb, preferred_element_type=jnp.int32)  # (..., M, N)
    out = acc.astype(jnp.float32) * sa * jnp.swapaxes(sb, -1, -2)  # (...,1,N)
    return out.astype(a.dtype)


def _linear_checker(a, w, bias=None):
    if not isinstance(a, TensorProxy) or not isinstance(w, TensorProxy):
        return False
    if not (dtypes.is_float_dtype(a.dtype) and dtypes.is_float_dtype(w.dtype)):
        return False
    return w.shape[-1] >= min_k


def _matmul_checker(a, b):
    if not isinstance(a, TensorProxy) or not isinstance(b, TensorProxy):
        return False
    if not (dtypes.is_float_dtype(a.dtype) and dtypes.is_float_dtype(b.dtype)):
        return False
    if a.ndim < 2 or b.ndim < 2:
        return False
    return a.shape[-1] >= min_k


_linear_op = ex.register_operator("int8_linear", like=prim_lookup[PrimIDs.LINEAR], fn=int8_linear)
_matmul_op = ex.register_operator("int8_matmul", like=prim_lookup[PrimIDs.MATMUL], fn=int8_matmul)
ex.register_implementation(PrimIDs.LINEAR, _linear_op, checker=_linear_checker)
ex.register_implementation(PrimIDs.MATMUL, _matmul_op, checker=_matmul_checker)
# the claiming pass consults executors before a composite is decomposed (and
# before the XLA fusion executor preserves it), so the torch-surface symbols
# must be claimable directly — same signatures as the prims they wrap
ex.register_implementation("torch.linear", _linear_op, checker=_linear_checker)
ex.register_implementation("torch.matmul", _matmul_op, checker=_matmul_checker)
ex.register_implementation("torch.mm", _matmul_op, checker=_matmul_checker)
ex.register_implementation("torch.bmm", _matmul_op, checker=_matmul_checker)
