"""Int8 quantization executor: the TransformerEngine-FP8 analog for TPU.

Capability analog of the reference's ``thunder/executors/transformer_engineex.py``
(:183-331 — functional fwd/bwd symbols that claim ``prims.linear`` and run it
in FP8 with dynamic scaling).  TPU v5e's MXU executes int8×int8→int32 at twice
the bf16 rate, so the TPU-native equivalent is dynamic **int8** quantization:

- activations are quantized per row (per token) with absmax scaling,
- weights per output channel with absmax scaling,
- the matmul accumulates in int32 (``preferred_element_type``), and the
  product of the two scales dequantizes the result.

The executor is **opt-in** (not a default executor): put ``quant_ex`` ahead of
the defaults in ``jit(..., executors=[quant_ex, *defaults])`` and it claims
``prims.linear`` / ``prims.matmul`` sites whose contraction is large enough
for quantization error to amortize (``min_k``, default 64).

Error model: absmax int8 keeps ~2 decimal digits; expect ~1e-2 relative error
on well-conditioned layers — the same contract TE's fp8 recipe offers.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from thunder_tpu.core.proxies import TensorProxy
from thunder_tpu.core import dtypes
from thunder_tpu.core.prims import PrimIDs, prim_lookup
from thunder_tpu.extend import OperatorExecutor, register_executor

__all__ = ["ex", "quant_ex", "fp8_ex", "int8_linear", "int8_matmul", "fp8_linear", "fp8_matmul"]

ex = OperatorExecutor("quant_int8", version="0.1")
quant_ex = ex
register_executor(ex)

# claim threshold on the contraction dim: tiny K has nothing to amortize the
# quantize/dequantize traffic (and error) against
min_k = 64


def _make_quant_ops(quantize_fn, accum_dtype):
    """Builds the (linear, matmul) pair for one quantization format.

    ``quantize_fn(x) -> (q, scale)`` quantizes over the last dim with absmax
    scaling; ``accum_dtype`` is the dot's preferred_element_type (int32 on
    the int8 MXU path, float32 for e4m3).  Shared by the int8 and fp8
    executors so the scale handling can never drift between them.
    """

    def q_linear(a, w, bias=None):
        qa, sa = quantize_fn(a)  # (..., K), (..., 1)
        qw, sw = quantize_fn(w)  # (N, K), (N, 1)
        acc = jax.lax.dot_general(
            qa, qw, (((qa.ndim - 1,), (1,)), ((), ())), preferred_element_type=accum_dtype
        )  # (..., N)
        out = acc.astype(jnp.float32) * sa * sw.reshape((1,) * (acc.ndim - 1) + (-1,))
        if bias is not None:
            out = out + bias.astype(jnp.float32)
        return out.astype(a.dtype)

    def q_matmul(a, b):
        if a.ndim == 1 or b.ndim == 1:  # matvec paths gain nothing; stay exact
            return jnp.matmul(a, b)
        qa, sa = quantize_fn(a)  # scale (..., M, 1)
        # quantize b per output column: absmax over its contraction dim (-2)
        bf = jnp.swapaxes(b.astype(jnp.float32), -1, -2)  # (..., N, K)
        qb, sb = quantize_fn(bf)  # (..., N, K), (..., N, 1)
        qb = jnp.swapaxes(qb, -1, -2)  # (..., K, N)
        acc = jnp.matmul(qa, qb, preferred_element_type=accum_dtype)  # (..., M, N)
        out = acc.astype(jnp.float32) * sa * jnp.swapaxes(sb, -1, -2)  # (...,1,N)
        return out.astype(a.dtype)

    return q_linear, q_matmul


def _quantize_lastdim(x):
    """absmax int8 over the last dim; returns (q, scale) with scale shaped to
    broadcast against the dot result."""
    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=-1, keepdims=True)
    scale = jnp.where(amax == 0.0, 1.0, amax / 127.0)
    q = jnp.clip(jnp.round(xf / scale), -127, 127).astype(jnp.int8)
    return q, scale


# int8: a @ w.T (+ bias) / a @ b with per-token activations, per-output-
# channel weights, int32 accumulation on the MXU, float32 dequant
int8_linear, int8_matmul = _make_quant_ops(_quantize_lastdim, jnp.int32)


def _linear_checker(a, w, bias=None):
    if not isinstance(a, TensorProxy) or not isinstance(w, TensorProxy):
        return False
    if not (dtypes.is_float_dtype(a.dtype) and dtypes.is_float_dtype(w.dtype)):
        return False
    return w.shape[-1] >= min_k


def _matmul_checker(a, b):
    if not isinstance(a, TensorProxy) or not isinstance(b, TensorProxy):
        return False
    if not (dtypes.is_float_dtype(a.dtype) and dtypes.is_float_dtype(b.dtype)):
        return False
    if a.ndim < 2 or b.ndim < 2:
        return False
    return a.shape[-1] >= min_k


def _register_quant(executor, prefix, q_linear, q_matmul):
    linear_op = executor.register_operator(f"{prefix}_linear", like=prim_lookup[PrimIDs.LINEAR], fn=q_linear)
    matmul_op = executor.register_operator(f"{prefix}_matmul", like=prim_lookup[PrimIDs.MATMUL], fn=q_matmul)
    executor.register_implementation(PrimIDs.LINEAR, linear_op, checker=_linear_checker)
    executor.register_implementation(PrimIDs.MATMUL, matmul_op, checker=_matmul_checker)
    # the claiming pass consults executors before a composite is decomposed
    # (and before the XLA fusion executor preserves it), so the torch-surface
    # symbols must be claimable directly — same signatures as the prims
    executor.register_implementation("torch.linear", linear_op, checker=_linear_checker)
    executor.register_implementation("torch.matmul", matmul_op, checker=_matmul_checker)
    executor.register_implementation("torch.mm", matmul_op, checker=_matmul_checker)
    executor.register_implementation("torch.bmm", matmul_op, checker=_matmul_checker)


_register_quant(ex, "int8", int8_linear, int8_matmul)


#
# FP8 (e4m3) executor — the TransformerEngine-class capability (reference
# transformer_engineex.py:183-336 runs forward GEMMs in e4m3).  Scaling here
# is dynamic per-ROW absmax (per-token activations, per-output-channel
# weights, like the int8 path) — finer-grained than TE's per-tensor
# amax-history recipe, so numerics are at least as tight but NOT bit-matched
# to TE.  thunder_tpu's fp8 dtypes (core/dtypes.py:199-202) execute through
# here.  On TPU generations without fp8 matmul units the cast runs on the
# VPU and the dot accumulates from the dequantized operands; int8 remains
# the v5e-native fast path.
#

_E4M3_MAX = 448.0


def _quantize_fp8_lastdim(x):
    """absmax scaling into float8_e4m3; returns (q, scale) like the int8
    variant (scale broadcastable against the dot result)."""
    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=-1, keepdims=True)
    scale = jnp.where(amax == 0.0, 1.0, amax / _E4M3_MAX)
    q = (xf / scale).astype(jnp.float8_e4m3fn)
    return q, scale


fp8_linear, fp8_matmul = _make_quant_ops(_quantize_fp8_lastdim, jnp.float32)

fp8_ex = OperatorExecutor("quant_fp8", version="0.1")
register_executor(fp8_ex)
_register_quant(fp8_ex, "fp8", fp8_linear, fp8_matmul)
