"""Pallas TPU kernel executor: hand-written flash attention.

Capability analog of the reference's fused-attention executors
(``thunder/executors/sdpaex.py:240``, ``cudnnex.py:380`` — explicit fwd/bwd
operator symbols with checkers and a grad transform), re-designed for TPU:

- the kernels are blockwise **flash attention** over a sequential Pallas grid
  (TPU grids execute in order, so VMEM scratch accumulators carry the online
  softmax state across KV blocks — the TPU-idiomatic replacement for CUDA
  thread-block reductions);
- the backward consumes ``(q, k, v, out, lse, delta)`` and recomputes scores
  blockwise, so saved residuals stay O(T) instead of the O(T²) probability
  matrix — this is what lets long sequences train in HBM;
- registration is twofold: an ``OperatorExecutor`` that claims
  ``PrimIDs.SDPA``/``SDPA_BACKWARD`` in the executor pipeline, plus fast-path
  hooks installed into ``jaxex`` so XLA fusion regions and the distributed
  TrainStep's trace evaluation dispatch to the same kernels.

On non-TPU backends the kernels can run via the Pallas interpreter
(``THUNDER_TPU_PALLAS_INTERPRET=1``) for testing; otherwise dispatch falls
back to the jnp reference implementation.
"""
from __future__ import annotations

import contextlib
import contextvars
import functools
import os
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

try:  # pltpu compiles only where the TPU plugin exists; interpret mode doesn't need it
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

from thunder_tpu.core.prims import PrimIDs, prim_lookup
from thunder_tpu.extend import OperatorExecutor, add_default_executor, register_executor

__all__ = [
    "ex", "pallas_ex", "flash_sdpa", "flash_sdpa_backward",
    "paged_attn_decode", "paged_token_write", "paged_available",
]

# exp(MASK_VALUE - lse) underflows to 0 without the inf-inf NaN hazard of -inf
_MASK_VALUE = -0.7 * float(np.finfo(np.float32).max)


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


# Sharded dispatch: a bare pallas_call has no SPMD partitioning rule, so
# GSPMD would replicate it inside a multi-device pjit (all-gathering sharded
# q/k/v onto every chip).  Multi-device program builders (distributed.
# TrainStep) publish their Mesh here, and the dispatchers wrap the kernels in
# ``jax.shard_map`` over the mesh's batch/head axes — heads and batch are
# embarrassingly parallel for attention, so the per-shard kernel is exactly
# the single-device kernel on the local shard.
_mesh_var = contextvars.ContextVar("pallas_mesh", default=None)


@contextlib.contextmanager
def mesh_context(mesh):
    """Activates ``mesh`` for Pallas SPMD dispatch during tracing."""
    tok = _mesh_var.set(mesh)
    try:
        yield
    finally:
        _mesh_var.reset(tok)


# dispatch counters (trace-time): how often the kernels were claimed, and via
# which path — introspection for tests and examine()
stats = {"direct": 0, "sharded": 0}


def _pallas_available() -> bool:
    if os.environ.get("THUNDER_TPU_DISABLE_PALLAS", "") == "1":
        return False
    if jax.default_backend() == "tpu":
        return True
    return os.environ.get("THUNDER_TPU_PALLAS_INTERPRET", "") == "1"


def _enabled() -> bool:
    return _pallas_available()


def _block(T: int, which: str = "") -> int:
    """Largest supported block size dividing ``T``.

    ``THUNDER_TPU_FLASH_BQ`` / ``THUNDER_TPU_FLASH_BK`` override the choice
    for the q/kv axis (tuning knob; ignored when it does not divide T).
    Overrides are read at trace time — call ``jax.clear_caches()`` after
    changing them.
    """
    if which:
        env = os.environ.get(f"THUNDER_TPU_FLASH_B{which}")
        if env:
            try:
                b = int(env)
            except ValueError:
                b = 0
            if b > 0 and T % b == 0:
                return b
    for b in (512, 256, 128):
        if T % b == 0:
            return b
    return 0


def _pad128(hs: int) -> int:
    return -(-hs // 128) * 128


def _gqa_rep(q_shape, k_shape) -> int | None:
    """Heads-per-KV-group, or None if the shapes aren't kernel-compatible.

    1 = plain MHA.  GQA (q ``(..., H, Tq, hs)``, k/v ``(..., G, Tk, hs)``)
    is handled natively: the kernels' K/V BlockSpec index maps gather the
    group's block for each q head, so K/V are never expanded in HBM —
    the H/G× KV-bandwidth saving is the point of GQA (reference leans on
    aten's enable_gqa, sdpaex.py:240)."""
    if q_shape[:-2] == k_shape[:-2]:
        return 1
    if len(q_shape) < 3 or q_shape[:-3] != k_shape[:-3]:
        return None
    H, G = q_shape[-3], k_shape[-3]
    if G <= 0 or H % G != 0:
        return None
    return H // G


def _canon_mask(mask_shape, q_shape, k_shape):
    """Classify an additive mask for blockwise loading.

    Returns ``(mode, mq)`` — mode names how the mask's (flattened) leading
    dim indexes against the kernel's flat batch×head grid axis — or None if
    the layout isn't expressible as a BlockSpec index map:

    - ``shared``: broadcast over all batch dims (e.g. a (Tq, Tk) ALiBi bias)
    - ``batch``: per-batch, head-broadcast — the HF padding-mask layout
      (B, 1, 1|Tq, Tk); index = flat // H
    - ``head``: per-head, batch-broadcast (1, H, ., .); index = flat % H
    - ``full``: every batch×head has its own slice; index = flat

    ``mq`` is 1 (row-broadcast: the whole mask is O(Tk) per batch — padding
    masks stay O(T) in HBM) or Tq.
    """
    *qb, Tq, _ = q_shape
    Tk = k_shape[-2]
    if len(mask_shape) > len(qb) + 2:
        return None
    ms = (1,) * (len(qb) + 2 - len(mask_shape)) + tuple(mask_shape)
    if ms[-1] != Tk:
        return None
    mq = ms[-2]
    if mq not in (1, Tq):
        return None
    mb = ms[:-2]
    for md, qd in zip(mb, qb):
        if md not in (1, qd):
            return None
    if all(md == 1 for md in mb):
        return ("shared", mq)
    if all(md == qd for md, qd in zip(mb, qb)):
        return ("full", mq)
    if len(mb) == 2 and mb[1] == 1:
        return ("batch", mq)
    if len(mb) == 2 and mb[0] == 1:
        return ("head", mq)
    return None


def _supported(q_shape, k_shape, v_shape, dtype, causal, mask_shape=None, window=None) -> bool:
    if window is not None and (not causal or int(window) <= 0):
        return False
    *_, Tq, hs = q_shape
    Tk = k_shape[-2]
    if v_shape[-1] != hs:  # kernels assume one head dim for q/k/v
        return False
    if _gqa_rep(q_shape, k_shape) is None:
        return False
    if k_shape[:-2] != v_shape[:-2]:
        return False
    # head sizes that aren't lane-aligned (e.g. 64) run zero-padded to 128
    if _pad128(hs) > 512:
        return False
    if _block(Tq) == 0 or _block(Tk) == 0:
        return False
    # causal with Tq != Tk uses top-left alignment (torch/aten convention):
    # the kernels index rows/cols globally, so no extra restriction
    # full K and V blocks + f32 accumulators must fit VMEM comfortably
    if str(dtype) not in ("bfloat16", "float32"):
        return False
    if mask_shape is not None and _canon_mask(mask_shape, q_shape, k_shape) is None:
        return False
    return True


#
# Forward kernel
#


def _fwd_kernel(*refs, BQ, BK, causal, scale, has_mask, window):
    if has_mask:
        q_ref, k_ref, v_ref, mask_ref, o_ref, lse_ref, m_s, l_s, acc_s = refs
    else:
        q_ref, k_ref, v_ref, o_ref, lse_ref, m_s, l_s, acc_s = refs
        mask_ref = None
    i = pl.program_id(1)
    j = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(j == 0)
    def _init():
        m_s[...] = jnp.full_like(m_s, _MASK_VALUE)
        l_s[...] = jnp.zeros_like(l_s)
        acc_s[...] = jnp.zeros_like(acc_s)

    # causal: skip KV blocks strictly above the diagonal; sliding window
    # additionally skips blocks entirely below the band (col <= row - window)
    run = (j * BK <= i * BQ + BQ - 1) if causal else True
    if window is not None:
        run = jnp.logical_and(run, j * BK + BK - 1 > i * BQ - window)

    @pl.when(run)
    def _compute():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale  # (BQ, BK)
        if has_mask:
            s = s + mask_ref[0].astype(jnp.float32)  # (1|BQ, BK) broadcasts
        if causal:
            row = i * BQ + jax.lax.broadcasted_iota(jnp.int32, (BQ, BK), 0)
            col = j * BK + jax.lax.broadcasted_iota(jnp.int32, (BQ, BK), 1)
            keep = row >= col
            if window is not None:
                keep = jnp.logical_and(keep, col > row - window)
            s = jnp.where(keep, s, _MASK_VALUE)
        m_prev = m_s[...]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m_prev - m_new)
        l_s[...] = l_s[...] * corr + jnp.sum(p, axis=1, keepdims=True)
        m_s[...] = m_new
        acc_s[...] = acc_s[...] * corr + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    @pl.when(j == nk - 1)
    def _finalize():
        o_ref[0] = (acc_s[...] / l_s[...]).astype(o_ref.dtype)
        lse_ref[0] = m_s[...] + jnp.log(l_s[...])


def _kv_index(H: int, G: int):
    """K/V BlockSpec head gather: flat q index ``b*H + h`` reads KV group
    ``h // (H//G)`` — GQA without expanding K/V in HBM (rep=1 ⇒ identity)."""
    rep = H // G

    def index(b, i, j):
        return ((b // H) * G + (b % H) // rep, j, 0)

    return index


def _mask_index(mode: str, H: int, mq_blocked: bool):
    """Mask BlockSpec index map for the canonical (M, mq, Tk) layout."""

    def index(b, i, j):
        m = {"shared": 0, "batch": b // H, "head": b % H, "full": b}[mode]
        return (m, i if mq_blocked else 0, j)

    return index


def _mask_spec(mode: str, mq: int, H: int, BQ: int, BK: int):
    blk = (1, BQ if mq > 1 else 1, BK)
    return pl.BlockSpec(blk, _mask_index(mode, H, mq > 1))


@functools.partial(jax.jit, static_argnames=("causal", "scale", "H", "G", "mode", "mq", "window"))
def _flash_fwd(q, k, v, mask, causal: bool, scale: float, H: int, G: int, mode: str | None, mq: int,
               window: int | None = None):
    """q (BH, Tq, hs), k/v (BG, Tk, hs), mask (M, mq, Tk) f32 or None
    -> out (BH, Tq, hs), lse (BH, Tq, 1) f32.  ``H``/``G`` are the per-shard
    q/KV head counts (the flat-batch gather key for GQA); ``mode``/``mq``
    classify the mask layout (see _canon_mask)."""
    BH, Tq, hs = q.shape
    Tk = k.shape[1]
    BQ, BK = _block(Tq, "Q"), _block(Tk, "K")
    grid = (BH, Tq // BQ, Tk // BK)
    has_mask = mask is not None

    kernel = functools.partial(
        _fwd_kernel, BQ=BQ, BK=BK, causal=causal, scale=scale, has_mask=has_mask, window=window
    )
    params = {}
    if pltpu is not None and not _interpret():
        params["compiler_params"] = pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")
        )
    in_specs = [
        pl.BlockSpec((1, BQ, hs), lambda b, i, j: (b, i, 0)),
        pl.BlockSpec((1, BK, hs), _kv_index(H, G)),
        pl.BlockSpec((1, BK, hs), _kv_index(H, G)),
    ]
    operands = [q, k, v]
    if has_mask:
        in_specs.append(_mask_spec(mode, mq, H, BQ, BK))
        operands.append(mask)
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, BQ, hs), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, BQ, 1), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, Tq, hs), q.dtype),
            jax.ShapeDtypeStruct((BH, Tq, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((BQ, 1), jnp.float32) if pltpu is not None else None,
            pltpu.VMEM((BQ, 1), jnp.float32) if pltpu is not None else None,
            pltpu.VMEM((BQ, hs), jnp.float32) if pltpu is not None else None,
        ],
        interpret=_interpret(),
        **params,
    )(*operands)


#
# Backward kernels
#


def _bwd_dq_kernel(*refs, BQ, BK, causal, scale, has_mask, window):
    if has_mask:
        g_ref, q_ref, k_ref, v_ref, lse_ref, delta_ref, mask_ref, dq_ref, dq_s = refs
    else:
        g_ref, q_ref, k_ref, v_ref, lse_ref, delta_ref, dq_ref, dq_s = refs
        mask_ref = None
    i = pl.program_id(1)
    j = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(j == 0)
    def _init():
        dq_s[...] = jnp.zeros_like(dq_s)

    run = (j * BK <= i * BQ + BQ - 1) if causal else True
    if window is not None:
        run = jnp.logical_and(run, j * BK + BK - 1 > i * BQ - window)

    @pl.when(run)
    def _compute():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        g = g_ref[0]
        lse = lse_ref[0]  # (BQ, 1) f32
        delta = delta_ref[0]  # (BQ, 1) f32
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale
        if has_mask:
            s = s + mask_ref[0].astype(jnp.float32)
        p = jnp.exp(s - lse)  # (BQ, BK)
        if causal:
            row = i * BQ + jax.lax.broadcasted_iota(jnp.int32, (BQ, BK), 0)
            col = j * BK + jax.lax.broadcasted_iota(jnp.int32, (BQ, BK), 1)
            keep = row >= col
            if window is not None:
                keep = jnp.logical_and(keep, col > row - window)
            p = jnp.where(keep, p, 0.0)
        dp = jax.lax.dot_general(
            g, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # (BQ, BK)
        ds = p * (dp - delta)
        dq_s[...] += scale * jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    @pl.when(j == nk - 1)
    def _finalize():
        dq_ref[0] = dq_s[...].astype(dq_ref.dtype)


def _bwd_dkv_kernel(*refs, BQ, BK, causal, scale, has_mask, window):
    if has_mask:
        g_ref, q_ref, k_ref, v_ref, lse_ref, delta_ref, mask_ref, dk_ref, dv_ref, dk_s, dv_s = refs
    else:
        g_ref, q_ref, k_ref, v_ref, lse_ref, delta_ref, dk_ref, dv_ref, dk_s, dv_s = refs
        mask_ref = None
    jk = pl.program_id(1)
    iq = pl.program_id(2)
    nq = pl.num_programs(2)

    @pl.when(iq == 0)
    def _init():
        dk_s[...] = jnp.zeros_like(dk_s)
        dv_s[...] = jnp.zeros_like(dv_s)

    run = (iq * BQ + BQ - 1 >= jk * BK) if causal else True
    if window is not None:
        run = jnp.logical_and(run, jk * BK + BK - 1 > iq * BQ - window)

    @pl.when(run)
    def _compute():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        g = g_ref[0]
        lse = lse_ref[0]
        delta = delta_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale  # (BQ, BK)
        if has_mask:
            s = s + mask_ref[0].astype(jnp.float32)
        p = jnp.exp(s - lse)
        if causal:
            row = iq * BQ + jax.lax.broadcasted_iota(jnp.int32, (BQ, BK), 0)
            col = jk * BK + jax.lax.broadcasted_iota(jnp.int32, (BQ, BK), 1)
            keep = row >= col
            if window is not None:
                keep = jnp.logical_and(keep, col > row - window)
            p = jnp.where(keep, p, 0.0)
        # dv += p^T @ g   (contract over q rows)
        dv_s[...] += jax.lax.dot_general(
            p.astype(g.dtype), g, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        dp = jax.lax.dot_general(
            g, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # (BQ, BK)
        ds = p * (dp - delta)  # (BQ, BK)
        dk_s[...] += scale * jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    @pl.when(iq == nq - 1)
    def _finalize():
        dk_ref[0] = dk_s[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_s[...].astype(dv_ref.dtype)


@functools.partial(jax.jit, static_argnames=("causal", "scale", "H", "G", "mode", "mq", "window"))
def _flash_bwd(g, q, k, v, out, lse, mask, causal: bool, scale: float, H: int, G: int, mode: str | None, mq: int,
               window: int | None = None):
    """g/q/out (BH, Tq, hs), k/v (BG, Tk, hs), lse (BH, Tq, 1);
    returns (dq (BH,...), dk, dv (BG,...)).

    GQA: the kernels run over the expanded (BH) grid with K/V gathered by
    index map; dk/dv come out per-q-head and are reduced over each group's
    ``rep`` heads by XLA afterwards (one cheap (BG, rep) sum — the scores
    recompute itself stays group-shared-K/V, which is the bandwidth win)."""
    BH, Tq, hs = q.shape
    BG, Tk, _ = k.shape
    BQ, BK = _block(Tq, "Q"), _block(Tk, "K")
    rep = H // G
    has_mask = mask is not None
    delta = jnp.sum(g.astype(jnp.float32) * out.astype(jnp.float32), axis=-1, keepdims=True)

    params = {}
    if pltpu is not None and not _interpret():
        params["compiler_params"] = pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")
        )

    dq_in_specs = [
        pl.BlockSpec((1, BQ, hs), lambda b, i, j: (b, i, 0)),  # g
        pl.BlockSpec((1, BQ, hs), lambda b, i, j: (b, i, 0)),  # q
        pl.BlockSpec((1, BK, hs), _kv_index(H, G)),  # k
        pl.BlockSpec((1, BK, hs), _kv_index(H, G)),  # v
        pl.BlockSpec((1, BQ, 1), lambda b, i, j: (b, i, 0)),  # lse
        pl.BlockSpec((1, BQ, 1), lambda b, i, j: (b, i, 0)),  # delta
    ]
    dq_operands = [g, q, k, v, lse, delta]
    if has_mask:
        dq_in_specs.append(_mask_spec(mode, mq, H, BQ, BK))
        dq_operands.append(mask)

    dq = pl.pallas_call(
        functools.partial(
            _bwd_dq_kernel, BQ=BQ, BK=BK, causal=causal, scale=scale, has_mask=has_mask, window=window
        ),
        grid=(BH, Tq // BQ, Tk // BK),
        in_specs=dq_in_specs,
        out_specs=pl.BlockSpec((1, BQ, hs), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((BH, Tq, hs), q.dtype),
        scratch_shapes=[pltpu.VMEM((BQ, hs), jnp.float32) if pltpu is not None else None],
        interpret=_interpret(),
        **params,
    )(*dq_operands)

    # the dkv grid swaps (i, j): index-map arg order is (b, j, i)
    kv_idx = _kv_index(H, G)
    dkv_in_specs = [
        pl.BlockSpec((1, BQ, hs), lambda b, j, i: (b, i, 0)),  # g
        pl.BlockSpec((1, BQ, hs), lambda b, j, i: (b, i, 0)),  # q
        pl.BlockSpec((1, BK, hs), lambda b, j, i: kv_idx(b, i, j)),  # k
        pl.BlockSpec((1, BK, hs), lambda b, j, i: kv_idx(b, i, j)),  # v
        pl.BlockSpec((1, BQ, 1), lambda b, j, i: (b, i, 0)),  # lse
        pl.BlockSpec((1, BQ, 1), lambda b, j, i: (b, i, 0)),  # delta
    ]
    dkv_operands = [g, q, k, v, lse, delta]
    if has_mask:
        midx = _mask_index(mode, H, mq > 1)
        dkv_in_specs.append(
            pl.BlockSpec((1, BQ if mq > 1 else 1, BK), lambda b, j, i: midx(b, i, j))
        )
        dkv_operands.append(mask)

    dk, dv = pl.pallas_call(
        functools.partial(
            _bwd_dkv_kernel, BQ=BQ, BK=BK, causal=causal, scale=scale, has_mask=has_mask, window=window
        ),
        grid=(BH, Tk // BK, Tq // BQ),
        in_specs=dkv_in_specs,
        out_specs=[
            pl.BlockSpec((1, BK, hs), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, BK, hs), lambda b, j, i: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, Tk, hs), k.dtype),
            jax.ShapeDtypeStruct((BH, Tk, hs), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((BK, hs), jnp.float32) if pltpu is not None else None,
            pltpu.VMEM((BK, hs), jnp.float32) if pltpu is not None else None,
        ],
        interpret=_interpret(),
        **params,
    )(*dkv_operands)
    if rep > 1:
        # flat q-head order is (b, g, r): fold rep into the group dim and sum
        dk = dk.reshape(BG, rep, Tk, hs).astype(jnp.float32).sum(axis=1).astype(k.dtype)
        dv = dv.reshape(BG, rep, Tk, hs).astype(jnp.float32).sum(axis=1).astype(v.dtype)
    return dq, dk, dv


#
# Dispatchers (shape-polymorphic over leading batch dims)
#


def _pad_hs(x, hs, hp):
    if hs == hp:
        return x
    widths = [(0, 0)] * (x.ndim - 1) + [(0, hp - hs)]
    return jnp.pad(x, widths)


def _local_geometry(q_shape, k_shape):
    """(BH, BG, H, G) for the flat-batch kernel grid, from LOCAL (per-shard)
    shapes — so head counts stay correct under tp sharding inside shard_map."""
    *qb, _, _ = q_shape
    *kb, _, _ = k_shape
    BH = 1
    for b in qb:
        BH *= b
    BG = 1
    for b in kb:
        BG *= b
    H = q_shape[-3] if len(q_shape) >= 3 else 1
    G = k_shape[-3] if len(k_shape) >= 3 else 1
    return BH, BG, H, G


def _canon_mask_operand(mask, q_shape, k_shape):
    """Canonicalize an additive mask to the kernels' (M, mq, Tk) f32 layout.
    Returns (mask3, mode, mq); (None, None, 1) when mask is None."""
    if mask is None:
        return None, None, 1
    mode, mq = _canon_mask(mask.shape, q_shape, k_shape)
    Tk = k_shape[-2]
    # broadcast dims are all 1, so the canonical form is a plain reshape
    return mask.reshape(-1, mq, Tk).astype(jnp.float32), mode, mq


def _fwd_local(q, k, v, mask, causal: bool, scale: float, window: int | None = None):
    """Single-device forward on concrete arrays: flatten batch, pad hs, run.
    ``mask`` is the original-rank additive mask or None."""
    *batch, Tq, hs = q.shape
    Tk = k.shape[-2]
    hp = _pad128(hs)
    BH, BG, H, G = _local_geometry(q.shape, k.shape)
    mask3, mode, mq = _canon_mask_operand(mask, q.shape, k.shape)
    out, lse = _flash_fwd(
        _pad_hs(q.reshape(BH, Tq, hs), hs, hp),
        _pad_hs(k.reshape(BG, Tk, hs), hs, hp),
        _pad_hs(v.reshape(BG, Tk, hs), hs, hp),
        mask3,
        bool(causal), float(scale), H, G, mode, mq,
        window=None if window is None else int(window),
    )
    return out[..., :hs].reshape(*batch, Tq, hs), lse.reshape(*batch, Tq)


def _bwd_local(g, q, k, v, out, lse, mask, causal: bool, scale: float, window: int | None = None):
    *batch, Tq, hs = q.shape
    Tk = k.shape[-2]
    hp = _pad128(hs)
    BH, BG, H, G = _local_geometry(q.shape, k.shape)
    mask3, mode, mq = _canon_mask_operand(mask, q.shape, k.shape)
    r3 = lambda x, T, n: _pad_hs(x.reshape(n, T, hs), hs, hp)
    dq, dk, dv = _flash_bwd(
        r3(g, Tq, BH), r3(q, Tq, BH), r3(k, Tk, BG), r3(v, Tk, BG), r3(out, Tq, BH),
        lse.reshape(BH, Tq, 1).astype(jnp.float32),
        mask3,
        bool(causal), float(scale), H, G, mode, mq,
        window=None if window is None else int(window),
    )
    return (
        dq[..., :hs].reshape(q.shape),
        dk[..., :hs].reshape(k.shape),
        dv[..., :hs].reshape(v.shape),
    )


def _qkv_spec(mesh, q_shape, k_shape):
    """PartitionSpec for (*batch, T, hs) operands: batch dim over the data
    axes, head dim over tp, T/hs replicated (sharding either is a kernel
    restructuring — ring attention — not a blockwise-local op)."""
    import math

    from jax.sharding import PartitionSpec as P

    rank = len(q_shape)
    spec = [None] * rank
    nbatch = rank - 2
    data_axes = tuple(a for a in ("dp", "fsdp") if a in mesh.axis_names and mesh.shape[a] > 1)
    if nbatch >= 1 and data_axes:
        kdiv = math.prod(mesh.shape[a] for a in data_axes)
        if q_shape[0] % kdiv == 0 and k_shape[0] % kdiv == 0:
            spec[0] = data_axes if len(data_axes) > 1 else data_axes[0]
    if nbatch >= 2 and "tp" in mesh.axis_names and mesh.shape["tp"] > 1:
        tp = mesh.shape["tp"]
        if q_shape[1] % tp == 0 and k_shape[1] % tp == 0:
            spec[1] = "tp"
    return P(*spec)


def _concrete_multi_device(x) -> bool:
    """True iff ``x`` is a concrete array sharded across >1 device: a bare
    pallas_call on it would be GSPMD-replicated (all-gather + redundant
    compute; round-1 ADVICE), so dispatch declines outside a mesh context."""
    try:
        sh = getattr(x, "sharding", None)
        return sh is not None and len(sh.device_set) > 1
    except Exception:
        return False


def _dispatch(local_fn, operands, specs):
    """Shared dispatch policy for fwd/bwd.

    Inside a ``mesh_context`` with a multi-device mesh: run under
    ``jax.shard_map`` partitioned over batch (dp/fsdp) and head (tp) axes —
    distributed TrainSteps keep the flash kernels instead of falling back to
    the O(T²) reference (round-1 VERDICT weak #3).  If no dim is divisible
    by the mesh axes, decline (None): the jnp fallback shards as plain
    einsums, which beats replicating the kernel on every device.
    """
    mesh = _mesh_var.get()
    if mesh is not None and mesh.devices.size > 1:
        in_specs, out_specs = specs
        if not any(s is not None for spec in in_specs for s in tuple(spec)):
            return None
        stats["sharded"] += 1
        from thunder_tpu.distributed.prims import shard_map_compat

        return shard_map_compat(
            local_fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs
        )(*operands)
    if any(_concrete_multi_device(x) for x in operands):
        return None
    stats["direct"] += 1
    return local_fn(*operands)


def _mask_shard_spec(mask, q_shape, k_shape, qkv_spec):
    """PartitionSpec for the mask under a sharded dispatch, or ``False`` when
    the mask layout can't ride the mesh (per-head masks against tp-sharded
    heads): the caller then declines and the jnp fallback shards as einsums."""
    from jax.sharding import PartitionSpec as P

    if mask is None:
        return None
    mode, _ = _canon_mask(mask.shape, q_shape, k_shape)
    if mode == "shared":
        return P(*(None,) * mask.ndim)
    if mode == "batch" and mask.ndim == 4 and len(tuple(qkv_spec)) > 0:
        # HF padding-mask layout (B, 1, 1|Tq, Tk): shard B like q's batch dim
        return P(tuple(qkv_spec)[0], None, None, None)
    return False


def flash_sdpa(q, k, v, mask, causal, scale, window=None):
    """Returns (out, lse) via the flash kernels, or None if unsupported."""
    if not _enabled() or not _supported(
        q.shape, k.shape, v.shape, q.dtype, causal,
        mask.shape if mask is not None else None, window,
    ):
        return None
    from jax.sharding import PartitionSpec as P

    mesh = _mesh_var.get()
    spec = _qkv_spec(mesh, q.shape, k.shape) if mesh is not None else P()
    lse_spec = P(*tuple(spec)[:-1])
    if mask is None:
        return _dispatch(
            lambda q, k, v: _fwd_local(q, k, v, None, bool(causal), float(scale), window),
            (q, k, v),
            (((spec,) * 3), (spec, lse_spec)),
        )
    mspec = _mask_shard_spec(mask, q.shape, k.shape, spec)
    if mspec is False and mesh is not None and mesh.devices.size > 1:
        return None
    return _dispatch(
        lambda q, k, v, m: _fwd_local(q, k, v, m, bool(causal), float(scale), window),
        (q, k, v, mask),
        ((spec, spec, spec, mspec), (spec, lse_spec)),
    )


def flash_sdpa_backward(g, q, k, v, out, lse, mask, causal, scale, window=None):
    """Returns (dq, dk, dv) via the flash kernels, or None if unsupported."""
    if not _enabled() or not _supported(
        q.shape, k.shape, v.shape, q.dtype, causal,
        mask.shape if mask is not None else None, window,
    ):
        return None
    from jax.sharding import PartitionSpec as P

    mesh = _mesh_var.get()
    spec = _qkv_spec(mesh, q.shape, k.shape) if mesh is not None else P()
    lse_spec = P(*tuple(spec)[:-1])
    if mask is None:
        return _dispatch(
            lambda g, q, k, v, out, lse: _bwd_local(
                g, q, k, v, out, lse, None, bool(causal), float(scale), window),
            (g, q, k, v, out, lse),
            ((spec, spec, spec, spec, spec, lse_spec), (spec, spec, spec)),
        )
    mspec = _mask_shard_spec(mask, q.shape, k.shape, spec)
    if mspec is False and mesh is not None and mesh.devices.size > 1:
        return None
    return _dispatch(
        lambda g, q, k, v, out, lse, m: _bwd_local(
            g, q, k, v, out, lse, m, bool(causal), float(scale), window),
        (g, q, k, v, out, lse, mask),
        ((spec, spec, spec, spec, spec, lse_spec, mspec), (spec, spec, spec)),
    )


#
# Executor registration + jaxex fast-path hooks
#


def _sdpa_full(q, k, v, mask, causal, scale, window=None):
    res = flash_sdpa(q, k, v, mask, causal, scale, window)
    if res is None:  # checker raced with env change: stay correct
        from thunder_tpu.executors.jaxex import _sdpa_reference

        return _sdpa_reference(q, k, v, mask, causal, scale, window)
    return res


def _sdpa_backward_full(g, q, k, v, out, lse, mask, causal, scale, window=None):
    res = flash_sdpa_backward(g, q, k, v, out, lse, mask, causal, scale, window)
    if res is None:
        from thunder_tpu.executors.jaxex import _sdpa_backward_reference

        return _sdpa_backward_reference(g, q, k, v, out, lse, mask, causal, scale, window)
    return res


ex = OperatorExecutor("pallas", version=jax.__version__)
register_executor(ex)

_sdpa_op = ex.register_operator("pallas_sdpa", like=prim_lookup[PrimIDs.SDPA], fn=_sdpa_full)
_sdpa_bwd_op = ex.register_operator(
    "pallas_sdpa_backward", like=prim_lookup[PrimIDs.SDPA_BACKWARD], fn=_sdpa_backward_full
)


def _sdpa_checker(q, k, v, mask, causal, scale, window=None):
    return _enabled() and _supported(
        q.shape, k.shape, v.shape, q.dtype, causal,
        mask.shape if mask is not None else None, window,
    )


def _sdpa_bwd_checker(g, q, k, v, out, lse, mask, causal, scale, window=None):
    return _enabled() and _supported(
        q.shape, k.shape, v.shape, q.dtype, causal,
        mask.shape if mask is not None else None, window,
    )


ex.register_implementation(PrimIDs.SDPA, _sdpa_op, checker=_sdpa_checker)
ex.register_implementation(PrimIDs.SDPA_BACKWARD, _sdpa_bwd_op, checker=_sdpa_bwd_checker)

pallas_ex = ex
add_default_executor(ex)  # ahead of xla so the claiming pass prefers the kernels

#
# Fused cross-entropy kernel (the apex/triton-CE analog,
# reference apex_entropyex.py:15, triton_crossentropy_impl.py:18).
#
# One pass over the logits: the vocab dim is tiled along a sequential grid
# axis and VMEM scratch carries the online-logsumexp state (running max,
# rescaled sum) plus the picked target logit — so the (N, V) matrix is read
# from HBM exactly once and no (N, V) log-prob intermediate exists.
#


def _ce_kernel(logits_ref, tgt_ref, loss_ref, lse_ref, m_s, s_s, p_s, *, BN, BV):
    j = pl.program_id(1)
    nv = pl.num_programs(1)

    @pl.when(j == 0)
    def _init():
        m_s[...] = jnp.full_like(m_s, _MASK_VALUE)
        s_s[...] = jnp.zeros_like(s_s)
        p_s[...] = jnp.zeros_like(p_s)

    x = logits_ref[...].astype(jnp.float32)  # (BN, BV)
    t = tgt_ref[...]  # (BN, 1) int32

    m_prev = m_s[...]
    m_new = jnp.maximum(m_prev, jnp.max(x, axis=1, keepdims=True))
    corr = jnp.exp(m_prev - m_new)
    s_s[...] = s_s[...] * corr + jnp.sum(jnp.exp(x - m_new), axis=1, keepdims=True)
    m_s[...] = m_new

    # the target logit: exactly one column hits across the whole vocab sweep;
    # accumulated in raw (unshifted) logit space so no rescaling is needed
    col = j * BV + jax.lax.broadcasted_iota(jnp.int32, (BN, BV), 1)
    hit = col == t
    p_s[...] = p_s[...] + jnp.sum(jnp.where(hit, x, 0.0), axis=1, keepdims=True)

    @pl.when(j == nv - 1)
    def _finalize():
        lse = m_s[...] + jnp.log(s_s[...])
        lse_ref[...] = lse
        loss_ref[...] = lse - p_s[...]


@functools.lru_cache(maxsize=1)
def _tuning() -> dict:
    """Measured kernel tuning, committed by tools/kernel_tune.py from a real
    TPU run (VERDICT r3 #2: a kernel that loses to XLA must win or yield).
    Keys: ``ce.bn`` / ``ce.bv_cap`` (block geometry), ``ce.claim`` (default
    **False** — the checker defers to the XLA lowering until a measurement
    says otherwise)."""
    import json

    path = os.environ.get(
        "THUNDER_TPU_PALLAS_TUNING",
        os.path.join(os.path.dirname(os.path.abspath(__file__)), "pallas_tuning.json"),
    )
    try:
        with open(path) as f:
            return json.load(f)
    except Exception:
        return {}


def _ce_blocks(n: int, v: int) -> tuple[int, int] | None:
    tuned = _tuning().get("ce", {})
    bn = next((b for b in (tuned.get("bn", 256), 256, 128, 64, 32, 16, 8)
               if isinstance(b, int) and b > 0 and n % b == 0), None)
    if bn is None:
        return None
    # Widest lane-aligned (×128) divisor of v under a VMEM budget: wider
    # vocab tiles mean fewer grid steps and longer DMA bursts.  Round 3 lost
    # 3% to XLA at V=32000 because the old power-of-two divisor list picked
    # BV=256; 32000 = 128·250 admits BV=3200 under the same budget.
    bv_cap = int(tuned.get("bv_cap", 4096))
    budget = 4 * 1024 * 1024  # f32 block bytes; pallas double-buffers on top
    bv = None
    for k in range(min(v, bv_cap) // 128, 0, -1):
        b = k * 128
        if v % b == 0 and bn * b * 4 <= budget:
            bv = b
            break
    if bv is None:
        # no lane-aligned divisor: decline so the checker yields to XLA —
        # a sub-lane (64-wide) tile is structurally likely to lose, the
        # exact regression class the win-or-yield rule exists to prevent
        return None
    return bn, bv


@functools.partial(jax.jit, static_argnames=())
def _flash_ce(logits, target):
    """logits (N, V) float, target (N,) int -> (losses, lse), both (N,) f32."""
    N, V = logits.shape
    BN, BV = _ce_blocks(N, V)
    kernel = functools.partial(_ce_kernel, BN=BN, BV=BV)
    params = {}
    if pltpu is not None and not _interpret():
        params["compiler_params"] = pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary")
        )
    losses, lse = pl.pallas_call(
        kernel,
        grid=(N // BN, V // BV),
        in_specs=[
            pl.BlockSpec((BN, BV), lambda i, j: (i, j)),
            pl.BlockSpec((BN, 1), lambda i, j: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((BN, 1), lambda i, j: (i, 0)),
            pl.BlockSpec((BN, 1), lambda i, j: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((N, 1), jnp.float32),
            jax.ShapeDtypeStruct((N, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((BN, 1), jnp.float32) if pltpu is not None else None,
            pltpu.VMEM((BN, 1), jnp.float32) if pltpu is not None else None,
            pltpu.VMEM((BN, 1), jnp.float32) if pltpu is not None else None,
        ],
        interpret=_interpret(),
        **params,
    )(logits, target.astype(jnp.int32).reshape(N, 1))
    return losses[:, 0], lse[:, 0]


def _ce_supported(logits_shape, target_shape, logits_dtype) -> bool:
    if len(logits_shape) != 2 or len(target_shape) != 1:
        return False
    try:
        if jnp.dtype(logits_dtype) not in (jnp.dtype(jnp.float32), jnp.dtype(jnp.bfloat16)):
            return False
    except TypeError:
        return False
    return _ce_blocks(int(logits_shape[0]), int(logits_shape[1])) is not None


def _ce_local(logits, target):
    """Per-shard CE: the kernel when the local shape tiles, else the jnp
    reference (still avoids cross-shard traffic under shard_map)."""
    if _ce_blocks(int(logits.shape[0]), int(logits.shape[1])) is None:
        from thunder_tpu.executors.jaxex import _cross_entropy_fwd_reference

        return _cross_entropy_fwd_reference(logits, target)
    return _flash_ce(logits, target)


def _ce_spec(mesh, n_rows: int):
    """Row-sharding spec over the data axes (rows are batch×time — locally
    independent, so CE shards embarrassingly)."""
    import math

    from jax.sharding import PartitionSpec as P

    data_axes = tuple(a for a in ("dp", "fsdp") if a in mesh.axis_names and mesh.shape[a] > 1)
    if not data_axes:
        return None
    kdiv = math.prod(mesh.shape[a] for a in data_axes)
    if n_rows % kdiv != 0:
        return None
    return data_axes if len(data_axes) > 1 else data_axes[0]


def flash_cross_entropy(logits, target):
    """Returns (losses, lse) via the fused kernel, or None if unsupported.

    Under a ``mesh_context`` with a multi-device mesh the kernel runs
    shard_map-partitioned over the row (batch×time) dim — a bare pallas_call
    has no SPMD rule and would be GSPMD-replicated (every chip all-gathering
    the full (N, V) logits)."""
    if not _enabled() or not _ce_supported(logits.shape, target.shape, logits.dtype):
        return None
    from jax.sharding import PartitionSpec as P

    mesh = _mesh_var.get()
    row = _ce_spec(mesh, int(logits.shape[0])) if mesh is not None else None
    return _dispatch(
        _ce_local,
        (logits, target),
        ((P(row, None), P(row)), (P(row), P(row))),
    )


def _ce_full(logits, target):
    res = flash_cross_entropy(logits, target)
    if res is None:
        from thunder_tpu.executors.jaxex import _cross_entropy_fwd_reference

        return _cross_entropy_fwd_reference(logits, target)
    return res


_ce_op = ex.register_operator(
    "pallas_cross_entropy", like=prim_lookup[PrimIDs.CROSS_ENTROPY_FWD], fn=_ce_full
)


def _ce_checker(logits, target):
    # Default is YIELD: the kernel was last *measured* losing to XLA on the
    # default geometry, and win-or-yield says an unmeasured claim is a
    # regression risk.  A fresh TPU measurement (tools/kernel_tune.py)
    # writes ``ce.claim: true`` into pallas_tuning.json to re-arm it.
    if not _tuning().get("ce", {}).get("claim", False):
        return False
    try:
        from thunder_tpu.core import dtypes as _dt

        jdt = _dt.to_jax_dtype(logits.dtype)
    except Exception:
        return False
    return _enabled() and _ce_supported(tuple(logits.shape), tuple(target.shape), jdt)


ex.register_implementation(PrimIDs.CROSS_ENTROPY_FWD, _ce_op, checker=_ce_checker)

# ---------------------------------------------------------------------------
# Paged-attention decode: flash-decoding over the serving KV block arena.
#
# The serving engine's decode step historically paid gather_dense/scatter —
# one full-cache copy per token per request — to reassemble the paged arena
# into the dense layout forward_with_cache wants.  These two kernels read and
# write the arena *in place*:
#
# - ``paged_attn_decode``: grid (request, kv-group, kv-block); the block
#   table and positions ride in as **scalar-prefetch** operands so the
#   BlockSpec index maps fetch each request's physical arena blocks directly
#   (no gather primitive anywhere in the program).  Online softmax
#   accumulates across blocks in VMEM scratch; the positional keep-mask
#   (strictly-older slots, optional sliding window) and the int8/fp8 dequant
#   from the scale arenas are fused in-kernel; GQA is native (q reshaped to
#   (B, ng, rep, hs), one grid step per KV group).  The *fresh* token's K/V
#   (this step's projection, at the cache compute dtype — exactly what the
#   dense path would have written before attending) joins as the final
#   online-softmax term, so every row has at least one kept key and the
#   quantized path attends the diagonal at full precision, matching
#   quantize-on-scatter semantics bit-for-bit.
# - ``paged_token_write``: the scatter_token replacement — one grid step per
#   request lands the fresh K/V (or its quantization scale) in its
#   ``table[pos // bs]``/``pos % bs`` arena slot via an aliased output
#   (``input_output_aliases``), so the update is in place and the decode
#   program stays scatter-free.
#
# Both run under the Pallas interpreter off-TPU, so CPU tier-1 tests execute
# the real kernels (``tt.serve(..., attn="paged")``).
# ---------------------------------------------------------------------------


def paged_available() -> bool:
    """Whether the paged decode kernels can run here: Pallas enabled (TPU, or
    interpret mode opted in) and the TPU lowering package imports (scalar
    prefetch and VMEM scratch come from ``pallas.tpu`` even when
    interpreted)."""
    return _pallas_available() and pltpu is not None


def _paged_kernel(tab_ref, pos_ref, nb_ref, q_ref, k_ref, v_ref, *rest, bs,
                  window, quantized, cdtype, sm):
    del nb_ref  # raggedness lives in the BlockSpec index maps
    if quantized:
        ks_ref, vs_ref, fk_ref, fv_ref, o_ref, m_ref, l_ref, acc_ref = rest
    else:
        ks_ref = vs_ref = None
        fk_ref, fv_ref, o_ref, m_ref, l_ref, acc_ref = rest
    i, j = pl.program_id(0), pl.program_id(2)
    nb = pl.num_programs(2)
    p_i = pos_ref[i]

    @pl.when(j == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, _MASK_VALUE)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    def _dequant(x_ref, s_ref, dt):
        x = x_ref[0, 0, 0]                                 # (bs, hs) storage dtype
        if s_ref is not None:
            x = (x.astype(jnp.float32) * s_ref[0, 0, 0][:, None]).astype(cdtype)
        return x.astype(dt)

    def _online(s, v, dt):
        # one online-softmax step: fold scores ``s`` (rep, n) / values ``v``
        # (n, hs) into the running (m, l, acc) scratch
        m_prev, l_prev = m_ref[...], l_ref[...]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m_prev - m_new)
        m_ref[...] = m_new
        l_ref[...] = l_prev * corr + jnp.sum(p, axis=1, keepdims=True)
        acc_ref[...] = acc_ref[...] * corr + jax.lax.dot_general(
            p.astype(dt), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    # skip blocks with no kept slot: entirely future (sink-padded table
    # entries included), or entirely beyond the sliding window.  Every block
    # that *does* run keeps >= 1 slot, so exp() never sees an all-masked row.
    run = (j * bs) < p_i
    if window is not None:
        run = jnp.logical_and(run, (j * bs + bs - 1) > (p_i - window))

    @pl.when(run)
    def _block():
        q = q_ref[0, 0]                                    # (rep, hs)
        k = _dequant(k_ref, ks_ref, q.dtype)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) / sm                                             # (rep, bs)
        posn = j * bs + jax.lax.broadcasted_iota(jnp.int32, (1, bs), 1)
        keep = posn < p_i                                  # strictly older: the
        if window is not None:                             # fresh token is the
            keep = jnp.logical_and(keep, posn > p_i - window)  # final term below
        s = jnp.where(keep, s, _MASK_VALUE)
        _online(s, _dequant(v_ref, vs_ref, q.dtype), q.dtype)

    @pl.when(j == nb - 1)
    def _finalize():
        q = q_ref[0, 0]
        fk = fk_ref[0, 0].astype(q.dtype)                  # (hs,) at cdtype
        fv = fv_ref[0, 0].astype(q.dtype)
        s_f = jax.lax.dot_general(
            q, fk[None, :], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) / sm                                             # (rep, 1), never masked
        _online(s_f, fv[None, :], q.dtype)
        o_ref[0, 0] = (acc_ref[...] / l_ref[...]).astype(o_ref.dtype)


def _ragged_step(i, j, p, nb, *, bs, window):
    """Ragged block walk: clamp grid step ``j`` into request ``i``'s live
    block range.  Out-of-range steps (bucket padding past the request's last
    real block, or — under a sliding window — blocks that slid out) re-map to
    the nearest live block, so consecutive grid steps hand the pipeline the
    *same* arena indices and it skips re-issuing the DMA: a short request
    stops paying its bucket without the grid (program identity) changing.
    The compute for those steps was already ``pl.when``-skipped; this clamps
    the *fetch*."""
    hi = jnp.maximum(nb[i], 1) - 1
    jj = jnp.minimum(j, hi)
    if window is not None:
        lo = jnp.minimum(jnp.maximum(p[i] - (window - 1), 0) // bs, hi)
        jj = jnp.maximum(jj, lo)
    return jj


def paged_attn_decode(q, k_arena, v_arena, fresh_k, fresh_v, tables, pos, *,
                      layer, k_scale=None, v_scale=None, window=None,
                      n_blocks=None):
    """Single-token attention straight off the KV block arena, one layer.

    ``q``: (B, nh, hs) queries at the compute dtype; ``k_arena``/``v_arena``:
    the FULL (num_blocks, L, ng, bs, hs) serving-pool arenas (storage dtype;
    int8/fp8 when quantized) — ``layer`` picks the layer *inside the BlockSpec
    index map*, so no per-layer arena slice (a full-arena copy) ever
    materializes; ``fresh_k``/``fresh_v``: (B, ng, hs) this step's projected
    K/V at the cache compute dtype (NOT yet in the arena — the caller lands
    them with :func:`paged_token_write` afterwards); ``tables``: (B, nbb)
    int32 sink-padded block tables; ``pos``: (B,) int32 global positions;
    ``k_scale``/``v_scale``: (num_blocks, L, ng, bs) float32 dequant scales
    (both or neither); ``window``: ``cfg.sliding_window``; ``n_blocks``:
    (B,) int32 per-request live block counts (derived from ``pos`` when
    omitted) — the ragged-walk prefetch vector (see :func:`_ragged_step`).
    Returns (B, nh, hs) attention outputs at ``q.dtype``.
    """
    B, nh, hs = q.shape
    num_blocks, _L, ng, bs, _ = k_arena.shape
    nbb = int(tables.shape[1])
    rep = nh // ng
    assert rep * ng == nh, (nh, ng)
    quantized = k_scale is not None
    q4 = q.reshape(B, ng, rep, hs)
    if n_blocks is None:
        n_blocks = (pos + (bs - 1)) // bs
    n_blocks = n_blocks.astype(jnp.int32)
    step = functools.partial(_ragged_step, bs=bs, window=window)

    arena_spec = pl.BlockSpec(
        (1, 1, 1, bs, hs),
        lambda i, g, j, tab, p, nb: (tab[i, step(i, j, p, nb)], layer, g, 0, 0))
    scale_spec = pl.BlockSpec(
        (1, 1, 1, bs),
        lambda i, g, j, tab, p, nb: (tab[i, step(i, j, p, nb)], layer, g, 0))
    fresh_spec = pl.BlockSpec((1, 1, hs), lambda i, g, j, tab, p, nb: (i, g, 0))
    q_spec = pl.BlockSpec((1, 1, rep, hs), lambda i, g, j, tab, p, nb: (i, g, 0, 0))

    in_specs = [q_spec, arena_spec, arena_spec]
    args = [q4, k_arena, v_arena]
    if quantized:
        in_specs += [scale_spec, scale_spec]
        args += [k_scale, v_scale]
    in_specs += [fresh_spec, fresh_spec]
    args += [fresh_k, fresh_v]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(B, ng, nbb),
        in_specs=in_specs,
        out_specs=q_spec,
        scratch_shapes=[
            pltpu.VMEM((rep, 1), jnp.float32),
            pltpu.VMEM((rep, 1), jnp.float32),
            pltpu.VMEM((rep, hs), jnp.float32),
        ],
    )
    kwargs = {}
    if not _interpret():
        kwargs["compiler_params"] = pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"))
    out = pl.pallas_call(
        functools.partial(
            _paged_kernel, bs=bs, window=window, quantized=quantized,
            cdtype=fresh_k.dtype, sm=float(np.sqrt(hs)),
        ),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, ng, rep, hs), q.dtype),
        interpret=_interpret(),
        **kwargs,
    )(tables, pos, n_blocks, *args)
    return out.reshape(B, nh, hs)


def _paged_write_kernel(tab_ref, pos_ref, a_ref, v_ref, o_ref, *, rank5):
    del tab_ref, pos_ref, a_ref  # routing happens in the BlockSpec index maps
    if rank5:
        o_ref[0, :, :, 0, :] = v_ref[0]
    else:
        o_ref[0, :, :, 0] = v_ref[0]


def paged_token_write(arena, vals, tables, pos, *, block_size):
    """In-place single-token arena write (the scatter_token replacement).

    ``arena``: (num_blocks, L, ng, bs, hs) K/V arena — or (num_blocks, L, ng,
    bs) scale arena; ``vals``: (B, L, ng, hs) (or (B, L, ng)) at the arena
    dtype — quantize *before* calling (``quant.quantize_kv``), so the stored
    values match scatter_token_q exactly.  Each request's destination block
    and slot (``tables[i, pos[i] // bs]``, ``pos[i] % bs``) are computed in
    the BlockSpec index map; the arena aliases the output, so untouched
    blocks keep their bytes and no scatter primitive appears in the program.
    Padding rows (all-sink tables, pos 0) land in sink block 0, whose
    contents are never attended.
    """
    bs = block_size
    B = vals.shape[0]
    if arena.ndim == 5:
        _, L, ng, _, hs = arena.shape
        a_spec = pl.BlockSpec(
            (1, L, ng, 1, hs),
            lambda i, tab, p: (tab[i, p[i] // bs], 0, 0, p[i] % bs, 0))
        v_spec = pl.BlockSpec((1, L, ng, hs), lambda i, tab, p: (i, 0, 0, 0))
    else:
        _, L, ng, _ = arena.shape
        a_spec = pl.BlockSpec(
            (1, L, ng, 1),
            lambda i, tab, p: (tab[i, p[i] // bs], 0, 0, p[i] % bs))
        v_spec = pl.BlockSpec((1, L, ng), lambda i, tab, p: (i, 0, 0))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B,),
        in_specs=[a_spec, v_spec],
        out_specs=a_spec,
    )
    kwargs = {}
    if not _interpret():
        kwargs["compiler_params"] = pltpu.CompilerParams(
            dimension_semantics=("arbitrary",))
    return pl.pallas_call(
        functools.partial(_paged_write_kernel, rank5=arena.ndim == 5),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(arena.shape, arena.dtype),
        input_output_aliases={2: 0},   # arena in == arena out (in-place)
        interpret=_interpret(),
        **kwargs,
    )(tables, pos, arena, vals)


def _paged_verify_kernel(tab_ref, pos_ref, nb_ref, q_ref, k_ref, v_ref, *rest,
                         bs, T, quantized, cdtype, sm):
    """Multi-token-query variant of ``_paged_kernel`` for the speculative
    verify step — and, at T = chunk width, the chunked-prefill attention
    kernel (:func:`paged_attn_verify` docstring): T chunk queries per request
    share one pass over the arena blocks, with the causal intra-chunk mask
    folded into the final online-softmax term.  Queries ride flattened as
    (rep*T, hs) rows so the arena phase is the single-token kernel's math at
    a wider row count."""
    del nb_ref  # raggedness lives in the BlockSpec index maps
    if quantized:
        ks_ref, vs_ref, fk_ref, fv_ref, o_ref, m_ref, l_ref, acc_ref = rest
    else:
        ks_ref = vs_ref = None
        fk_ref, fv_ref, o_ref, m_ref, l_ref, acc_ref = rest
    i, j = pl.program_id(0), pl.program_id(2)
    nb = pl.num_programs(2)
    p_i = pos_ref[i]

    @pl.when(j == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, _MASK_VALUE)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    def _dequant(x_ref, s_ref, dt):
        x = x_ref[0, 0, 0]                                 # (bs, hs) storage dtype
        if s_ref is not None:
            x = (x.astype(jnp.float32) * s_ref[0, 0, 0][:, None]).astype(cdtype)
        return x.astype(dt)

    def _online(s, v, dt):
        m_prev, l_prev = m_ref[...], l_ref[...]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m_prev - m_new)
        m_ref[...] = m_new
        l_ref[...] = l_prev * corr + jnp.sum(p, axis=1, keepdims=True)
        acc_ref[...] = acc_ref[...] * corr + jax.lax.dot_general(
            p.astype(dt), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    # arena phase: the arena holds only the committed strictly-older prefix
    # (rejected speculative slots are never written), so every chunk query —
    # at positions p_i .. p_i+T-1 — may see all slots < p_i and the keep-mask
    # is query-independent, exactly the single-token kernel's
    run = (j * bs) < p_i

    @pl.when(run)
    def _block():
        q = q_ref[0, 0]                                    # (rep*T, hs)
        k = _dequant(k_ref, ks_ref, q.dtype)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) / sm                                             # (rep*T, bs)
        posn = j * bs + jax.lax.broadcasted_iota(jnp.int32, (1, bs), 1)
        s = jnp.where(posn < p_i, s, _MASK_VALUE)
        _online(s, _dequant(v_ref, vs_ref, q.dtype), q.dtype)

    @pl.when(j == nb - 1)
    def _finalize():
        q = q_ref[0, 0]
        rows = q.shape[0]                                  # rep * T
        fk = fk_ref[0, 0].astype(q.dtype)                  # (T, hs) at cdtype
        fv = fv_ref[0, 0].astype(q.dtype)
        s_f = jax.lax.dot_general(
            q, fk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) / sm                                             # (rep*T, T)
        # causal intra-chunk mask: flattened row r is the query at chunk
        # offset t = r % T and sees fresh keys at offsets <= t; the diagonal
        # is always kept, so no row is ever all-masked
        t_of = jax.lax.broadcasted_iota(jnp.int32, (rows, T), 0) % T
        col = jax.lax.broadcasted_iota(jnp.int32, (rows, T), 1)
        s_f = jnp.where(col <= t_of, s_f, _MASK_VALUE)
        _online(s_f, fv, q.dtype)
        o_ref[0, 0] = (acc_ref[...] / l_ref[...]).astype(o_ref.dtype)


def paged_attn_verify(q, k_arena, v_arena, fresh_k, fresh_v, tables, pos, *,
                      layer, k_scale=None, v_scale=None, n_blocks=None):
    """Multi-token-query attention off the KV block arena, one layer — the
    speculative verify step's kernel (T = K+1) and, generalized to T = the
    chunk width, the chunked-prefill attention kernel (the arena keep-mask
    is query-independent either way: the arena holds only the committed
    strictly-older prefix, and the chunk's own keys fold in causally as the
    final online-softmax term).

    ``q``: (B, nh, T, hs) chunk queries at global positions
    ``[pos, pos+T)``; ``fresh_k``/``fresh_v``: (B, ng, T, hs) the chunk's own
    projected K/V at the cache compute dtype (not yet in the arena — the
    caller commits the accepted prefix with :func:`paged_token_write_masked`,
    or the whole chunk with :func:`paged_chunk_write`, afterwards).
    Arena/scale/table/pos/``n_blocks`` arguments as
    :func:`paged_attn_decode`.  Sliding-window models are rejected upstream
    (speculation needs full caches; the chunked-prefill resolution falls
    back to gather).  Returns (B, nh, T, hs) at ``q.dtype``.
    """
    B, nh, T, hs = q.shape
    num_blocks, _L, ng, bs, _ = k_arena.shape
    nbb = int(tables.shape[1])
    rep = nh // ng
    assert rep * ng == nh, (nh, ng)
    quantized = k_scale is not None
    # (B, nh, T, hs) -> (B, ng, rep*T, hs): nh splits as (ng, rep), then the
    # adjacent (rep, T) dims fold — row r = rep_idx*T + t
    qf = q.reshape(B, ng, rep * T, hs)
    if n_blocks is None:
        n_blocks = (pos + (bs - 1)) // bs
    n_blocks = n_blocks.astype(jnp.int32)
    step = functools.partial(_ragged_step, bs=bs, window=None)

    arena_spec = pl.BlockSpec(
        (1, 1, 1, bs, hs),
        lambda i, g, j, tab, p, nb: (tab[i, step(i, j, p, nb)], layer, g, 0, 0))
    scale_spec = pl.BlockSpec(
        (1, 1, 1, bs),
        lambda i, g, j, tab, p, nb: (tab[i, step(i, j, p, nb)], layer, g, 0))
    fresh_spec = pl.BlockSpec((1, 1, T, hs), lambda i, g, j, tab, p, nb: (i, g, 0, 0))
    q_spec = pl.BlockSpec((1, 1, rep * T, hs), lambda i, g, j, tab, p, nb: (i, g, 0, 0))

    in_specs = [q_spec, arena_spec, arena_spec]
    args = [qf, k_arena, v_arena]
    if quantized:
        in_specs += [scale_spec, scale_spec]
        args += [k_scale, v_scale]
    in_specs += [fresh_spec, fresh_spec]
    args += [fresh_k, fresh_v]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(B, ng, nbb),
        in_specs=in_specs,
        out_specs=q_spec,
        scratch_shapes=[
            pltpu.VMEM((rep * T, 1), jnp.float32),
            pltpu.VMEM((rep * T, 1), jnp.float32),
            pltpu.VMEM((rep * T, hs), jnp.float32),
        ],
    )
    kwargs = {}
    if not _interpret():
        kwargs["compiler_params"] = pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"))
    out = pl.pallas_call(
        functools.partial(
            _paged_verify_kernel, bs=bs, T=T, quantized=quantized,
            cdtype=fresh_k.dtype, sm=float(np.sqrt(hs)),
        ),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, ng, rep * T, hs), q.dtype),
        interpret=_interpret(),
        **kwargs,
    )(tables, pos, n_blocks, *args)
    return out.reshape(B, nh, T, hs)


def _paged_write_masked_kernel(tab_ref, pos_ref, ne_ref, a_ref, v_ref, o_ref, *, rank5):
    del tab_ref, pos_ref, ne_ref, a_ref  # routing happens in the index maps
    if rank5:
        o_ref[0, :, :, 0, :] = v_ref[0]
    else:
        o_ref[0, :, :, 0] = v_ref[0]


def paged_token_write_masked(arena, vals, tables, pos, n_emit, offset, *, block_size):
    """Keep-masked arena write for the speculative verify commit — and,
    at ``offset=0``, the per-row liveness write of multi-step decode.

    Request ``i`` lands ``vals[i]`` — the K/V (or scale) of chunk offset
    ``offset`` — at arena slot ``pos[i] + offset`` iff ``offset <
    n_emit[i]``; rejected offsets route to sink block 0 slot 0 (whose bytes
    are never attended), so rejected-draft KV stays invisible without a
    scatter primitive in the program.  ``offset`` is static (one call per
    chunk position); ``n_emit`` rides as a scalar-prefetch operand so the
    routing happens in the BlockSpec index map.

    Multi-step decode liveness contract (``write_fresh_kv_live``): with
    ``offset=0`` and ``n_emit = live ∈ {0, 1}`` the predicate *is* the
    per-row liveness mask — a live row commits exactly like the unmasked
    single-step ``paged_token_write`` (bit-identical stored bytes), a row
    that finished earlier in the scan sinks every remaining iteration's
    write, so the N-step program stays static-shape with zero scatters.
    """
    bs = block_size
    B = vals.shape[0]
    k = offset
    if arena.ndim == 5:
        _, L, ng, _, hs = arena.shape
        a_spec = pl.BlockSpec(
            (1, L, ng, 1, hs),
            lambda i, tab, p, ne: (
                jnp.where(k < ne[i], tab[i, (p[i] + k) // bs], 0), 0, 0,
                jnp.where(k < ne[i], (p[i] + k) % bs, 0), 0))
        v_spec = pl.BlockSpec((1, L, ng, hs), lambda i, tab, p, ne: (i, 0, 0, 0))
    else:
        _, L, ng, _ = arena.shape
        a_spec = pl.BlockSpec(
            (1, L, ng, 1),
            lambda i, tab, p, ne: (
                jnp.where(k < ne[i], tab[i, (p[i] + k) // bs], 0), 0, 0,
                jnp.where(k < ne[i], (p[i] + k) % bs, 0)))
        v_spec = pl.BlockSpec((1, L, ng), lambda i, tab, p, ne: (i, 0, 0))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(B,),
        in_specs=[a_spec, v_spec],
        out_specs=a_spec,
    )
    kwargs = {}
    if not _interpret():
        kwargs["compiler_params"] = pltpu.CompilerParams(
            dimension_semantics=("arbitrary",))
    return pl.pallas_call(
        functools.partial(_paged_write_masked_kernel, rank5=arena.ndim == 5),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(arena.shape, arena.dtype),
        input_output_aliases={3: 0},   # arena in == arena out (in-place)
        interpret=_interpret(),
        **kwargs,
    )(tables, pos, n_emit.astype(jnp.int32), arena, vals)


def _chunk_dest(c, dest_ref, pos_ref, *, bs):
    """Chunk-writer routing: grid step ``c`` writes the chunk's ``c``-th
    block, i.e. dest entry ``pos // bs + c``.  Entries past the table width
    (bucket padding spilling beyond the leased table) route to physical
    block 0 — the sink, whose bytes are never attended."""
    nbb = dest_ref.shape[0]
    idx = pos_ref[0] // bs + c
    return jnp.where(idx < nbb, dest_ref[jnp.minimum(idx, nbb - 1)], 0)


def _paged_chunk_write_kernel(dest_ref, pos_ref, a_ref, v_ref, o_ref):
    del dest_ref, pos_ref, a_ref  # routing happens in the BlockSpec index maps
    o_ref[0] = v_ref[0]


def paged_chunk_write(arena, vals, dest, pos, *, block_size):
    """In-place block-granule chunk write — the chunked-prefill
    ``scatter_blocks`` replacement.

    ``arena``: (num_blocks, L, ng, bs, hs) K/V arena; ``vals``: (nc, L, ng,
    bs, hs) the chunk's fresh K (or V) at the arena dtype, pre-folded to
    block granules (a pure reshape/transpose of the (1, L, ng, T, hs)
    forward output — no gather); ``dest``: (nbb,) int32 scatter table from
    :func:`serving.kv_pool.chunk_tables` (sink entries absorb everything
    outside the chunk's own block range); ``pos``: (1,) int32 chunk start
    (block-aligned — the paged chunk resolution guarantees it).  One grid
    step per chunk block lands a whole (L, ng, bs, hs) slab at
    ``dest[pos // bs + c]`` via the aliased output, so untouched blocks keep
    their bytes and no scatter primitive appears in the program.  Trailing
    bucket-padding slots write garbage exactly like the gather path's
    ``scatter_blocks`` — sunk, never attended, or overwritten before use.
    """
    bs = block_size
    nc, L, ng, _bs, hs = vals.shape
    assert _bs == arena.shape[3] == bs, (vals.shape, arena.shape, bs)
    route = functools.partial(_chunk_dest, bs=bs)
    a_spec = pl.BlockSpec(
        (1, L, ng, bs, hs), lambda c, dest, p: (route(c, dest, p), 0, 0, 0, 0))
    v_spec = pl.BlockSpec((1, L, ng, bs, hs), lambda c, dest, p: (c, 0, 0, 0, 0))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(nc,),
        in_specs=[a_spec, v_spec],
        out_specs=a_spec,
    )
    kwargs = {}
    if not _interpret():
        kwargs["compiler_params"] = pltpu.CompilerParams(
            dimension_semantics=("arbitrary",))
    return pl.pallas_call(
        _paged_chunk_write_kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(arena.shape, arena.dtype),
        input_output_aliases={2: 0},   # arena in == arena out (in-place)
        interpret=_interpret(),
        **kwargs,
    )(dest, pos, arena, vals)


def _absmax_quant(x, qmax, storage):
    """The exact :func:`serving.quant.quantize_kv` math, in-kernel: float32
    absmax over the last (hs) dim, scale 1.0 for all-zero rows, int8
    round-and-clip / fp8 cast.  Same ops in the same order, so the stored
    bytes are bit-identical to the unfused quantize-then-write path."""
    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=-1)
    scale = jnp.where(amax == 0.0, 1.0, amax / qmax)
    y = xf / scale[..., None]
    if jnp.dtype(storage) == jnp.dtype(jnp.int8):
        q = jnp.clip(jnp.round(y), -qmax, qmax).astype(storage)
    else:
        q = y.astype(storage)
    return q, scale, xf


def _paged_chunk_write_fused_kernel(dest_ref, pos_ref, a_ref, s_ref, v_ref,
                                    oa_ref, os_ref, oe_ref, *, bs, qmax):
    del a_ref, s_ref  # aliased outputs; routing happens in the index maps
    c = pl.program_id(0)
    q, scale, xf = _absmax_quant(v_ref[0], qmax, oa_ref.dtype)
    oa_ref[0] = q
    os_ref[0] = scale
    # masked quantization-error sums behind the serving.kv_quant.rel_err
    # gauge: only blocks actually written (non-sink dest) count, matching
    # scatter_blocks_q's mask
    nbb = dest_ref.shape[0]
    idx = pos_ref[0] // bs + c
    live = jnp.logical_and(idx < nbb, dest_ref[jnp.minimum(idx, nbb - 1)] != 0)
    m = live.astype(jnp.float32)
    dq = q.astype(jnp.float32) * scale[..., None]
    err = jnp.zeros((8, 128), jnp.float32)
    err = err.at[0, 0].set(jnp.sum(jnp.abs(dq - xf)) * m)
    err = err.at[0, 1].set(jnp.sum(jnp.abs(xf)) * m)
    oe_ref[0] = err


def paged_chunk_write_fused(arena, scale_arena, vals, dest, pos, *, block_size):
    """Quantizing twin of :func:`paged_chunk_write` with the absmax
    quantize-on-write folded in (the Liger-style fused epilogue): ``vals``
    arrive at the *compute* dtype, the kernel computes the per-slot-head
    absmax scale and stores value + scale through two aliased outputs in ONE
    pallas_call — no standalone quantize op in the program.

    Returns ``(arena, scale_arena, err)`` where ``err`` is (nc, 8, 128)
    float32 with per-block masked error sums at ``[c, 0, 0]`` (|dq - x|) and
    ``[c, 0, 1]`` (|x|) — combine as ``sum / (sum + 1e-30)`` for the same
    rel_err figure ``scatter_blocks_q`` reports."""
    bs = block_size
    nc, L, ng, _bs, hs = vals.shape
    qmax = 127.0 if arena.dtype == jnp.dtype(jnp.int8) else float(jnp.finfo(arena.dtype).max)
    route = functools.partial(_chunk_dest, bs=bs)
    a_spec = pl.BlockSpec(
        (1, L, ng, bs, hs), lambda c, dest, p: (route(c, dest, p), 0, 0, 0, 0))
    s_spec = pl.BlockSpec(
        (1, L, ng, bs), lambda c, dest, p: (route(c, dest, p), 0, 0, 0))
    v_spec = pl.BlockSpec((1, L, ng, bs, hs), lambda c, dest, p: (c, 0, 0, 0, 0))
    e_spec = pl.BlockSpec((1, 8, 128), lambda c, dest, p: (c, 0, 0))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(nc,),
        in_specs=[a_spec, s_spec, v_spec],
        out_specs=[a_spec, s_spec, e_spec],
    )
    kwargs = {}
    if not _interpret():
        kwargs["compiler_params"] = pltpu.CompilerParams(
            dimension_semantics=("arbitrary",))
    return pl.pallas_call(
        functools.partial(_paged_chunk_write_fused_kernel, bs=bs, qmax=qmax),
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct(arena.shape, arena.dtype),
            jax.ShapeDtypeStruct(scale_arena.shape, scale_arena.dtype),
            jax.ShapeDtypeStruct((nc, 8, 128), jnp.float32),
        ],
        input_output_aliases={2: 0, 3: 1},   # value + scale arenas in-place
        interpret=_interpret(),
        **kwargs,
    )(dest, pos, arena, scale_arena, vals)


def _paged_token_write_fused_kernel(tab_ref, pos_ref, *rest, qmax):
    # rest = (ne_ref?, a_ref, s_ref, v_ref, oa_ref, os_ref) — the masked
    # variant prepends its n_emit prefetch ref; all routing (including the
    # emit predicate) happens in the BlockSpec index maps
    del tab_ref, pos_ref
    v_ref, oa_ref, os_ref = rest[-3:]
    q, scale, _ = _absmax_quant(v_ref[0], qmax, oa_ref.dtype)
    oa_ref[0, :, :, 0, :] = q
    os_ref[0, :, :, 0] = scale


def paged_token_write_fused(arena, scale_arena, vals, tables, pos, *,
                            block_size, n_emit=None, offset=0):
    """Quantizing twin of :func:`paged_token_write` (and, with ``n_emit``,
    of :func:`paged_token_write_masked`): ``vals`` (B, L, ng, hs) arrive at
    the compute dtype; the kernel runs the exact ``quantize_kv`` absmax math
    and lands value + scale through two aliased outputs in one pallas_call —
    the decode program's quantize-on-write with no standalone quantize op.
    Returns ``(arena, scale_arena)``."""
    bs = block_size
    B = vals.shape[0]
    _, L, ng, _, hs = arena.shape
    qmax = 127.0 if arena.dtype == jnp.dtype(jnp.int8) else float(jnp.finfo(arena.dtype).max)
    k = offset
    if n_emit is None:
        a_spec = pl.BlockSpec(
            (1, L, ng, 1, hs),
            lambda i, tab, p: (tab[i, p[i] // bs], 0, 0, p[i] % bs, 0))
        s_spec = pl.BlockSpec(
            (1, L, ng, 1),
            lambda i, tab, p: (tab[i, p[i] // bs], 0, 0, p[i] % bs))
        v_spec = pl.BlockSpec((1, L, ng, hs), lambda i, tab, p: (i, 0, 0, 0))
        num_prefetch, prefetch = 2, (tables, pos)
    else:
        a_spec = pl.BlockSpec(
            (1, L, ng, 1, hs),
            lambda i, tab, p, ne: (
                jnp.where(k < ne[i], tab[i, (p[i] + k) // bs], 0), 0, 0,
                jnp.where(k < ne[i], (p[i] + k) % bs, 0), 0))
        s_spec = pl.BlockSpec(
            (1, L, ng, 1),
            lambda i, tab, p, ne: (
                jnp.where(k < ne[i], tab[i, (p[i] + k) // bs], 0), 0, 0,
                jnp.where(k < ne[i], (p[i] + k) % bs, 0)))
        v_spec = pl.BlockSpec((1, L, ng, hs), lambda i, tab, p, ne: (i, 0, 0, 0))
        num_prefetch, prefetch = 3, (tables, pos, n_emit.astype(jnp.int32))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=num_prefetch,
        grid=(B,),
        in_specs=[a_spec, s_spec, v_spec],
        out_specs=[a_spec, s_spec],
    )
    kwargs = {}
    if not _interpret():
        kwargs["compiler_params"] = pltpu.CompilerParams(
            dimension_semantics=("arbitrary",))
    na = num_prefetch  # arena arg index right after the prefetch operands
    return pl.pallas_call(
        functools.partial(_paged_token_write_fused_kernel, qmax=qmax),
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct(arena.shape, arena.dtype),
            jax.ShapeDtypeStruct(scale_arena.shape, scale_arena.dtype),
        ],
        input_output_aliases={na: 0, na + 1: 1},
        interpret=_interpret(),
        **kwargs,
    )(*prefetch, arena, scale_arena, vals)


def _lora_delta_kernel(x_ref, a_ref, b_ref, o_ref, *, scaling):
    x = x_ref[0]                                       # (T, C)
    a = a_ref[0].astype(x.dtype)                       # (r, C)
    b = b_ref[0].astype(x.dtype)                       # (fout, r)
    d = jax.lax.dot_general(x, a, (((1,), (1,)), ((), ())))
    o_ref[0] = (jax.lax.dot_general(d, b, (((1,), (1,)), ((), ()))) * scaling
                ).astype(o_ref.dtype)


def lora_delta_fused(x, a, b, scaling):
    """Fused per-request LoRA delta ``scaling * B(A(x))`` — one kernel call
    per target instead of two standalone HLO einsums (the Liger fused-
    epilogue pattern applied to the adapter path).  ``x``: (B, T, fin);
    ``a``: (B, r, fin); ``b``: (B, fout, r) → (B, T, fout), same dtype flow
    as ``models.generate._lora_delta`` (factors cast to ``x.dtype``, default
    accumulation), so the delta is bit-identical to the unfused twin.  Used
    by the meshless kernel path only — under a mesh the unfused einsums stay
    (a bare pallas_call has no SPMD rule)."""
    B, T, C = x.shape
    _, r, _ = a.shape
    _, fout, _ = b.shape
    kwargs = {}
    if not _interpret():
        kwargs["compiler_params"] = pltpu.CompilerParams(
            dimension_semantics=("parallel",))
    return pl.pallas_call(
        functools.partial(_lora_delta_kernel, scaling=scaling),
        grid=(B,),
        in_specs=[
            pl.BlockSpec((1, T, C), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, r, C), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, fout, r), lambda i: (i, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, T, fout), lambda i: (i, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((B, T, fout), x.dtype),
        interpret=_interpret(),
        **kwargs,
    )(x, a, b)


# install the fast paths so XLA fusion regions and TrainStep trace evaluation
# reach the same kernels
from thunder_tpu.executors import jaxex as _jaxex

_jaxex._sdpa_fast_path = flash_sdpa
_jaxex._sdpa_bwd_fast_path = flash_sdpa_backward
_jaxex._ce_fast_path = flash_cross_entropy
