"""Executor claiming and lifetime passes.

Analog of the reference's ``thunder/executors/passes.py``:
``transform_for_execution`` (dce → operator claiming in priority order →
fusion passes → always-executor sweep) and ``del_last_used``.
"""
from __future__ import annotations

import time
from typing import Any, Sequence

from thunder_tpu.core.prims import OpTags, PrimIDs
from thunder_tpu.core.proxies import Proxy, variableify
from thunder_tpu.core.symbol import BoundSymbol, provenance_inherited
from thunder_tpu.core.trace import TraceCtx, TraceProvenance, from_trace, tracectx
from thunder_tpu.core.transform_common import dce
from thunder_tpu.extend import Executor, FusionExecutor, OperatorExecutor
from thunder_tpu.core.pytree import tree_flatten
from thunder_tpu.observability.events import span as _phase_span

__all__ = ["transform_for_execution", "del_last_used", "annotate_donations"]

_PASSTHROUGH_IDS = {
    PrimIDs.RETURN,
    PrimIDs.DEL,
    PrimIDs.COMMENT,
    PrimIDs.PRINT,
    PrimIDs.UNPACK_TRIVIAL,
    PrimIDs.UNPACK_FLATTEN,
    PrimIDs.UNPACK_GETITEM,
    PrimIDs.UNPACK_ATTR,
}


def _is_passthrough(bsym: BoundSymbol) -> bool:
    if bsym.sym.id in _PASSTHROUGH_IDS:
        return True
    tags = set(bsym.sym.tags)
    return OpTags.CHECK_OP in tags or OpTags.UNPACK_OP in tags


def _is_identity(bsym: BoundSymbol) -> bool:
    """A recorded no-op: its output proxies *are* its input proxies (e.g.
    ``a.to(a.dtype)``).  Safe to elide — the names already bind."""
    outs = list(bsym.flat_proxy_outs)
    if not outs or bsym.subsymbols:
        return False
    in_names = {p.name for p in bsym.flat_proxy_args}
    return all(p.name in in_names for p in outs)


def _claimable_inside(
    bsym: BoundSymbol, op_executors: Sequence[Executor], memo: dict | None = None
) -> bool:
    """True if any *descendant* bsym is claimable by one of ``op_executors`` —
    a fusion executor must not swallow a composite whose insides a
    higher-priority operator executor (pallas kernels, int8) wants.

    Memoized per (bsym, executor-prefix length): the trace is immutable
    during claiming, and deep composites would otherwise pay a quadratic
    re-walk per fusion-candidacy test."""
    if memo is None:
        memo = {}
    key = (id(bsym), len(op_executors))
    hit = memo.get(key)
    if hit is not None:
        return hit

    result = False
    for sub in bsym.subsymbols:
        for ex in op_executors:
            impl = ex.get_impl(sub.sym.id)
            if impl is not None:
                if impl.checker is None:
                    result = True
                    break
                try:
                    if impl.checker(*sub.args, **sub.kwargs):
                        result = True
                        break
                except Exception:
                    pass
        if result:
            break
        if sub.subsymbols and _claimable_inside(sub, op_executors, memo):
            result = True
            break
    memo[key] = result
    return result


def _claim_bsym(trace: TraceCtx, bsym: BoundSymbol, executors: Sequence[Executor], memo: dict | None = None) -> list[BoundSymbol]:
    if memo is None:
        memo = {}
    if _is_passthrough(bsym):
        return [bsym]
    if _is_identity(bsym):
        return []

    higher_ops: list[Executor] = []
    for ex in executors:
        if isinstance(ex, FusionExecutor):
            if ex.can_fuse(bsym) and not _claimable_inside(bsym, higher_ops, memo):
                # preserved as-is; the executor's fusion pass will absorb it
                # (unless a higher-priority operator executor wants something
                # inside, in which case we fall through and decompose)
                return [bsym]
        elif isinstance(ex, OperatorExecutor):
            higher_ops.append(ex)
            impl = ex.get_impl(bsym.sym.id)
            if impl is None:
                continue
            if impl.checker is not None:
                try:
                    if not impl.checker(*bsym.args, **bsym.kwargs):
                        continue
                except Exception:
                    continue
            if impl.execution_transform is not None:
                return _apply_execution_transform(trace, bsym, impl.execution_transform)
            if impl.symbol is not None:
                return [bsym.from_bsym(sym=impl.symbol, subsymbols=())]
            return [bsym]

    # no executor claims it: decompose
    if bsym.subsymbols:
        out: list[BoundSymbol] = []
        for sub in bsym.subsymbols:
            out.extend(_claim_bsym(trace, sub, executors, memo))
        return out
    return [bsym]


def _apply_execution_transform(trace: TraceCtx, bsym: BoundSymbol, transform) -> list[BoundSymbol]:
    """Re-traces ``bsym`` through an executor's execution_transform, swapping
    the transform's outputs back to the original output proxies.  The
    replacement bsyms inherit the original's source provenance (the stack
    here is all framework frames)."""
    with tracectx(trace):
        with trace.push_scope() as scope, provenance_inherited(bsym):
            result = transform(*bsym.args, **bsym.kwargs)

    flat_old, _ = tree_flatten(bsym.output)
    flat_new, _ = tree_flatten(result)
    swap_map = {}
    for old, new in zip(flat_old, flat_new):
        if isinstance(old, Proxy) and isinstance(new, Proxy) and old.name != new.name:
            swap_map[variableify(new)] = old
    return [b.from_bsym_swap_proxies(swap_map) for b in scope]


@_phase_span("lower")
def transform_for_execution(trace: TraceCtx, executors: Sequence[Executor]) -> TraceCtx:
    """The claiming pass (reference passes.py:131)."""
    start = time.perf_counter_ns()
    trace = dce(trace)

    new_bsyms: list[BoundSymbol] = []
    claim_memo: dict = {}
    for bsym in trace.bound_symbols:
        new_bsyms.extend(_claim_bsym(trace, bsym, executors, claim_memo))

    extrace = from_trace(trace)
    extrace.bound_symbols = new_bsyms

    # fusion passes, in priority order
    for ex in executors:
        if isinstance(ex, FusionExecutor):
            extrace = ex.fusion_pass(extrace)

    # always-executor sweep for anything left unclaimed
    from thunder_tpu.extend import get_always_executors

    always = get_always_executors()
    swept: list[BoundSymbol] = []
    for bsym in extrace.bound_symbols:
        if bsym.sym.is_fusion or bsym.sym.executor is not None or _is_passthrough(bsym):
            swept.append(bsym)
            continue
        if _is_identity(bsym):
            continue
        claimed = None
        for ex in always:
            impl = ex.get_impl(bsym.sym.id)
            if impl is not None and (impl.checker is None or impl.checker(*bsym.args, **bsym.kwargs)):
                if impl.execution_transform is not None:
                    claimed = _apply_execution_transform(extrace, bsym, impl.execution_transform)
                elif impl.symbol is not None:
                    claimed = [bsym.from_bsym(sym=impl.symbol, subsymbols=())]
                else:
                    claimed = [bsym]
                break
        if claimed is None:
            if bsym.subsymbols:
                claimed = []
                for sub in bsym.subsymbols:
                    for c in _claim_bsym(extrace, sub, always):
                        if c.sym.executor is None and c.sym.python_impl is None and not _is_passthrough(c):
                            raise RuntimeError(f"No executor can run {c.sym.name} (id={c.sym.id})")
                        claimed.append(c)
            elif bsym.sym.python_impl is not None:
                claimed = [bsym]
            else:
                raise RuntimeError(f"No executor can run {bsym.sym.name} (id={bsym.sym.id})")
        swept.extend(claimed)

    extrace.bound_symbols = swept
    elapsed = (time.perf_counter_ns() - start) // 1000000
    extrace.set_provenance(TraceProvenance(f"Transform for execution (took {elapsed} milliseconds)"))
    return extrace


@_phase_span("lower:del_last_used")
def del_last_used(trace: TraceCtx, *, clear_collections: bool = False) -> TraceCtx:
    """Inserts ``del`` statements after each proxy's last use so the generated
    program drops references to dead jax buffers promptly (reference
    passes.py:232) — important on TPU where HBM is the bottleneck."""
    start = time.perf_counter_ns()
    from thunder_tpu.core.prims import python_del

    # proxies that must outlive the program
    from thunder_tpu.executors.utils import trace_return_names

    protected: set[str] = trace_return_names(trace)

    new_reversed: list[BoundSymbol] = []
    seen: set[str] = set()
    for bsym in reversed(trace.bound_symbols):
        if bsym.sym.id in (PrimIDs.RETURN, PrimIDs.DEL, PrimIDs.COMMENT):
            new_reversed.append(bsym)
            continue
        dead: list[Proxy] = []
        for p in list(bsym.flat_proxy_outs) + list(bsym.flat_proxy_args):
            if p.name not in seen and p.name not in protected:
                from thunder_tpu.core.proxies import TensorProxy

                if isinstance(p, TensorProxy) and not any(d.name == p.name for d in dead):
                    dead.append(p)
            seen.add(p.name)
        if dead:
            new_reversed.append(python_del.bind(*dead, output=None))
        new_reversed.append(bsym)

    ntrace = from_trace(trace)
    ntrace.bound_symbols = list(reversed(new_reversed))
    elapsed = (time.perf_counter_ns() - start) // 1000000
    ntrace.set_provenance(TraceProvenance(f"Delete Last Used (took {elapsed} milliseconds)"))
    return ntrace


@_phase_span("lower:donation")
def annotate_donations(
    trace: TraceCtx,
    *,
    candidate_names: set | None = None,
    strict: bool = False,
    which: str = "forward",
):
    """Del-aware buffer donation pass: runs AFTER ``del_last_used`` (it needs
    the explicit ``DEL`` placement as its liveness proof) and arms each XLA
    fusion region with the inputs that are provably safe to donate.  Returns
    ``(annotated_trace, DonationReport)`` — see
    ``thunder_tpu.executors.donation`` for the safety contract."""
    from thunder_tpu.executors.donation import apply_donation

    return apply_donation(
        trace, candidate_names=candidate_names, strict=strict, which=which
    )
