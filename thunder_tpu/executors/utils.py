"""Executor utilities: regions and trace evaluation over concrete values.

``Region`` is the analog of the reference's ``thunder/executors/utils.py:29``;
``eval_bsyms`` re-executes a list of bound symbols over concrete (JAX) values
and is the engine behind XLA fusion callables (the analog of the reference's
``eval_trace``-based ``torch_compile.py:44`` region compilation).
"""
from __future__ import annotations

from numbers import Number
from typing import Any, Callable, Sequence

from thunder_tpu.core.proxies import AnyProxy, NumberProxy, Proxy, StringProxy, TensorProxy, variableify
from thunder_tpu.core.pytree import tree_flatten, tree_unflatten
from thunder_tpu.core.symbol import BoundSymbol
from thunder_tpu.core.utils import OrderedSet, consumers, producers

__all__ = ["Region", "eval_bsyms", "resolve_impl", "resolve_args", "trace_return_names"]


def trace_return_names(trace) -> set[str]:
    """Names of every proxy the trace returns — the buffers that must outlive
    the program.  Shared by ``del_last_used`` (they are never deleted) and
    the donation pass (they are never donated)."""
    from thunder_tpu.core.prims import PrimIDs

    out: set[str] = set()
    for bsym in trace.bound_symbols:
        if bsym.sym.id == PrimIDs.RETURN:
            for p in bsym.flat_proxy_args:
                out.add(p.name)
    return out


class Region:
    """Computes the proxy inputs and outputs of a group of bound symbols."""

    def __init__(self, producers_map, consumers_map, bsyms: Sequence[BoundSymbol]):
        self.bsyms = list(bsyms)

        produced: OrderedSet = OrderedSet()
        consumed: OrderedSet = OrderedSet()

        def visit(bsym: BoundSymbol) -> None:
            # walk the WHOLE composite tree: a proxy consumed only by a
            # subsymbol (e.g. the implicit rng_key inside dropout's uniform)
            # is still a region input — evaluation descends into subsymbols,
            # so the top-level arg list alone under-reports consumption
            for out in bsym.flat_proxy_outs:
                produced.add(variableify(out))
            for arg in bsym.flat_proxy_args:
                consumed.add(variableify(arg))
            for sub in bsym.subsymbols:
                visit(sub)

        for bsym in self.bsyms:
            visit(bsym)

        self.inputs = OrderedSet(v for v in consumed if v not in produced)

        # outputs: produced proxies consumed by bsyms outside the region
        in_region = set(id(b) for b in self.bsyms)
        outputs: OrderedSet = OrderedSet()
        for bsym in self.bsyms:
            for out in bsym.flat_proxy_outs:
                v = variableify(out)
                cons = consumers_map.get(out, ())
                for c in cons:
                    if id(c) not in in_region:
                        outputs.add(v)
                        break
        self.outputs = outputs


def resolve_impl(bsym: BoundSymbol) -> Callable | None:
    """Finds a concrete callable for a bound symbol."""
    if bsym.sym.fn is not None:
        return bsym.sym.fn
    from thunder_tpu.executors.jaxex import prim_impls

    fn = prim_impls.get(bsym.sym.id)
    if fn is not None:
        return fn
    if bsym.sym.python_impl is not None:
        return bsym.sym.python_impl
    return None


def resolve_args(env: dict[str, Any], args, kwargs):
    """Substitutes proxies with concrete values from ``env``."""

    def sub(x):
        if isinstance(x, (NumberProxy, StringProxy, AnyProxy)):
            if x.value is not None:
                return x.value
            # unknown at trace time (e.g. an item() result): runtime value
            if x.name in env:
                return env[x.name]
            raise RuntimeError(f"Number proxy {x.name} has no static or runtime value")
        if isinstance(x, Proxy):
            if x.name not in env:
                raise RuntimeError(f"Proxy {x.name} has no value during evaluation")
            return env[x.name]
        return x

    flat, spec = tree_flatten((tuple(args), dict(kwargs)))
    flat = [sub(x) for x in flat]
    return tree_unflatten(flat, spec)


def bind_outputs(env: dict[str, Any], output, result) -> None:
    flat_out, _ = tree_flatten(output)
    proxies = [o for o in flat_out if isinstance(o, Proxy)]
    if len(proxies) == 0:
        return
    if len(proxies) == 1 and not isinstance(result, (tuple, list)):
        env[proxies[0].name] = result
        return
    flat_res, _ = tree_flatten(result)
    vals = []
    ri = 0
    for o in flat_out:
        if isinstance(o, Proxy):
            env[o.name] = flat_res[ri]
        ri += 1


def eval_bsyms(bsyms: Sequence[BoundSymbol], env: dict[str, Any]) -> None:
    """Executes bound symbols over concrete values, updating ``env`` in place.

    Composites without a concrete implementation are evaluated through their
    subsymbols, so any trace level is executable.
    """
    from thunder_tpu.core.prims import PrimIDs

    for bsym in bsyms:
        if bsym.sym.id in (PrimIDs.DEL, PrimIDs.RETURN, PrimIDs.COMMENT):
            continue
        fn = resolve_impl(bsym)
        if fn is None:
            if bsym.subsymbols:
                eval_bsyms(bsym.subsymbols, env)
                continue
            raise RuntimeError(f"No implementation found for {bsym.sym.name} ({bsym.sym.id})")
        args, kwargs = resolve_args(env, bsym.args, bsym.kwargs)
        result = fn(*args, **kwargs)
        bind_outputs(env, bsym.output, result)
