"""The default JAX operator executor: every prim → a jax.numpy/lax call.

Capability analog of the reference's ``thunder/executors/torchex.py`` (the
always-on operator executor mapping prims to ``torch.*``); here prims map to
JAX ops, which also serve as the single source of truth for the XLA fusion
executor's region evaluation (``thunder_tpu/executors/xlaex.py``).
"""
from __future__ import annotations

import functools
from numbers import Number
from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp

from thunder_tpu.core import dtypes
from thunder_tpu.core.devices import Device, to_jax_device
from thunder_tpu.core.prims import PrimIDs, prim_lookup
from thunder_tpu.extend import OperatorExecutor, add_always_executor, add_default_executor, register_executor

__all__ = ["ex", "jax_ex", "get_prim_impl", "prim_impls"]


def _jd(d) -> Any:
    """thunder dtype → jax dtype."""
    return dtypes.to_jax_dtype(d)


def _key_for(key, offset: int):
    return jax.random.fold_in(key, offset)


#
# Implementations, keyed by PrimIDs.  Signatures match the prim metas exactly.
#

prim_impls: dict[PrimIDs, Callable] = {}


def impl(pid: PrimIDs):
    def deco(fn):
        prim_impls[pid] = fn
        return fn

    return deco


# Elementwise unary
_unary_jax = {
    PrimIDs.ABS: jnp.abs,
    PrimIDs.ACOS: jnp.arccos,
    PrimIDs.ACOSH: jnp.arccosh,
    PrimIDs.ASIN: jnp.arcsin,
    PrimIDs.ASINH: jnp.arcsinh,
    PrimIDs.ATAN: jnp.arctan,
    PrimIDs.ATANH: jnp.arctanh,
    PrimIDs.BITWISE_NOT: jnp.bitwise_not,
    PrimIDs.CEIL: jnp.ceil,
    PrimIDs.COS: jnp.cos,
    PrimIDs.COSH: jnp.cosh,
    PrimIDs.ERF: jax.lax.erf,
    PrimIDs.ERFC: jax.lax.erfc,
    PrimIDs.ERFINV: jax.lax.erf_inv,
    PrimIDs.EXP: jnp.exp,
    PrimIDs.EXP2: jnp.exp2,
    PrimIDs.EXPM1: jnp.expm1,
    PrimIDs.FLOOR: jnp.floor,
    PrimIDs.ISFINITE: jnp.isfinite,
    PrimIDs.ISINF: jnp.isinf,
    PrimIDs.ISNAN: jnp.isnan,
    PrimIDs.LOG: jnp.log,
    PrimIDs.LOG10: jnp.log10,
    PrimIDs.LOG1P: jnp.log1p,
    PrimIDs.LOG2: jnp.log2,
    PrimIDs.NEG: jnp.negative,
    PrimIDs.ROUND: jnp.round,
    PrimIDs.RSQRT: jax.lax.rsqrt,
    PrimIDs.SIGN: jnp.sign,
    PrimIDs.SIGNBIT: jnp.signbit,
    PrimIDs.SIN: jnp.sin,
    PrimIDs.SINH: jnp.sinh,
    PrimIDs.SQRT: jnp.sqrt,
    PrimIDs.TAN: jnp.tan,
    PrimIDs.TANH: jnp.tanh,
    PrimIDs.TRUNC: jnp.trunc,
    PrimIDs.REAL: jnp.real,
    PrimIDs.IMAG: jnp.imag,
}
for _pid, _fn in _unary_jax.items():
    prim_impls[_pid] = _fn


@impl(PrimIDs.DIGAMMA)
def _digamma_impl(a):
    from jax.scipy.special import digamma

    return digamma(a)


@impl(PrimIDs.LGAMMA)
def _lgamma_impl(a):
    from jax.scipy.special import gammaln

    return gammaln(a)


@impl(PrimIDs.RECIPROCAL)
def _reciprocal_impl(a):
    return jnp.reciprocal(a)


# Elementwise binary
_binary_jax = {
    PrimIDs.ADD: jnp.add,
    PrimIDs.ATAN2: jnp.arctan2,
    PrimIDs.BITWISE_AND: jnp.bitwise_and,
    PrimIDs.BITWISE_OR: jnp.bitwise_or,
    PrimIDs.BITWISE_XOR: jnp.bitwise_xor,
    PrimIDs.SHIFT_LEFT: jnp.left_shift,
    PrimIDs.SHIFT_RIGHT: jnp.right_shift,
    PrimIDs.COPYSIGN: jnp.copysign,
    PrimIDs.EQ: jnp.equal,
    PrimIDs.FMOD: jnp.fmod,
    PrimIDs.GE: jnp.greater_equal,
    PrimIDs.GT: jnp.greater,
    PrimIDs.LE: jnp.less_equal,
    PrimIDs.LT: jnp.less,
    PrimIDs.MAXIMUM: jnp.maximum,
    PrimIDs.MINIMUM: jnp.minimum,
    PrimIDs.MUL: jnp.multiply,
    PrimIDs.NE: jnp.not_equal,
    PrimIDs.NEXTAFTER: jnp.nextafter,
    PrimIDs.POW: jnp.power,
    PrimIDs.REMAINDER: jnp.remainder,
    PrimIDs.SUB: jnp.subtract,
}
for _pid, _fn in _binary_jax.items():
    prim_impls[_pid] = _fn


@impl(PrimIDs.DIV)
def _div_impl(a, b):
    if jnp.issubdtype(jnp.result_type(a), jnp.integer) or jnp.issubdtype(jnp.result_type(a), jnp.bool_):
        # C-style truncation division for exact types (matches reference prims.div)
        return jax.lax.div(a, b)
    return jnp.true_divide(a, b)


@impl(PrimIDs.WHERE)
def _where_impl(pred, a, b):
    return jnp.where(pred, a, b)


@impl(PrimIDs.CLAMP)
def _clamp_impl(a, min, max):
    return jnp.clip(a, min, max)


# Data movement
@impl(PrimIDs.CONVERT_ELEMENT_TYPE)
def _convert_element_type_impl(a, dtype):
    return a.astype(_jd(dtype))


@impl(PrimIDs.DEVICE_PUT)
def _device_put_impl(a, device):
    return jax.device_put(a, to_jax_device(device))


@impl(PrimIDs.ITEM)
def _item_impl(a):
    return a.reshape(()).item() if not isinstance(a, jax.core.Tracer) else a.reshape(())


@impl(PrimIDs.COPY_)
def _copy__impl(a, b):
    return jnp.asarray(b, dtype=a.dtype)


# Creation
@impl(PrimIDs.FULL)
def _full_impl(shape, fill_value, *, device, dtype):
    return jnp.full(tuple(int(s) for s in shape), fill_value, dtype=_jd(dtype))


@impl(PrimIDs.IOTA)
def _iota_impl(length, *, start, step, device, dtype):
    return start + step * jnp.arange(int(length), dtype=_jd(dtype))


@impl(PrimIDs.UNIFORM)
def _uniform_impl(shape, minval, maxval, *, device, dtype, key, offset):
    return jax.random.uniform(
        _key_for(key, offset), tuple(int(s) for s in shape), dtype=_jd(dtype), minval=minval, maxval=maxval
    )


@impl(PrimIDs.RANDN)
def _randn_impl(shape, *, device, dtype, key, offset):
    return jax.random.normal(_key_for(key, offset), tuple(int(s) for s in shape), dtype=_jd(dtype))


@impl(PrimIDs.RANDINT)
def _randint_impl(shape, low, high, *, device, dtype, key, offset):
    return jax.random.randint(_key_for(key, offset), tuple(int(s) for s in shape), low, high, dtype=_jd(dtype))


@impl(PrimIDs.MULTINOMIAL)
def _multinomial_impl(a, num_samples, replacement, *, key, offset):
    k = _key_for(key, offset)
    logits = jnp.log(a)
    if a.ndim == 1:
        return jax.random.categorical(k, logits, shape=(num_samples,)).astype(jnp.int32)
    return jax.random.categorical(k, logits[:, None, :], axis=-1, shape=(a.shape[0], num_samples)).astype(jnp.int32)


# Shape
@impl(PrimIDs.BROADCAST_IN_DIM)
def _broadcast_in_dim_impl(a, shape, broadcast_dimensions):
    return jax.lax.broadcast_in_dim(a, tuple(int(s) for s in shape), tuple(int(d) for d in broadcast_dimensions))


@impl(PrimIDs.CAT)
def _cat_impl(tensors, dim):
    return jnp.concatenate(list(tensors), axis=int(dim))


@impl(PrimIDs.FLIP)
def _flip_impl(a, dims):
    return jnp.flip(a, axis=tuple(int(d) for d in dims))


@impl(PrimIDs.RESHAPE)
def _reshape_impl(a, shape):
    return jnp.reshape(a, tuple(int(s) for s in shape))


@impl(PrimIDs.SLICE)
def _slice_impl(a, start_indices, end_indices, strides=None):
    if strides is None:
        strides = [1] * a.ndim
    return jax.lax.slice(
        a, tuple(int(s) for s in start_indices), tuple(int(e) for e in end_indices), tuple(int(s) for s in strides)
    )


@impl(PrimIDs.SQUEEZE)
def _squeeze_impl(a, dims):
    return jnp.squeeze(a, axis=tuple(int(d) for d in dims))


@impl(PrimIDs.TRANSPOSE)
def _transpose_impl(a, permutation):
    return jnp.transpose(a, tuple(int(p) for p in permutation))


@impl(PrimIDs.UNFOLD)
def _unfold_impl(a, dim, size, step):
    dim, size, step = int(dim), int(size), int(step)
    n_windows = (a.shape[dim] - size) // step + 1
    idx = jnp.arange(n_windows)[:, None] * step + jnp.arange(size)[None, :]
    out = jnp.take(a, idx, axis=dim)  # (..., n_windows, size, ...) at dim
    return jnp.moveaxis(out, dim + 1, -1)


@impl(PrimIDs.PAD)
def _pad_impl(a, padding_value, padding_config):
    pv = jnp.asarray(padding_value, dtype=a.dtype)
    return jax.lax.pad(a, pv, [(int(lo), int(hi), int(i)) for lo, hi, i in padding_config])


# Reductions
@impl(PrimIDs.AMAX)
def _amax_impl(a, dims):
    return jnp.max(a, axis=tuple(int(d) for d in dims))


@impl(PrimIDs.AMIN)
def _amin_impl(a, dims):
    return jnp.min(a, axis=tuple(int(d) for d in dims))


@impl(PrimIDs.PROD)
def _prod_impl(a, dims):
    return jnp.prod(a, axis=tuple(int(d) for d in dims))


@impl(PrimIDs.SUM)
def _sum_impl(a, dims):
    return jnp.sum(a, axis=tuple(int(d) for d in dims))


@impl(PrimIDs.VAR)
def _var_impl(a, dims, *, correction):
    return jnp.var(a, axis=tuple(int(d) for d in dims), ddof=correction)


@impl(PrimIDs.VAR_MEAN)
def _var_mean_impl(a, dims, *, correction):
    axis = tuple(int(d) for d in dims)
    return jnp.var(a, axis=axis, ddof=correction), jnp.mean(a, axis=axis)


@impl(PrimIDs.ARGMAX)
def _argmax_impl(a, dim):
    return jnp.argmax(a, axis=None if dim is None else int(dim)).astype(jnp.int32)


@impl(PrimIDs.ARGMIN)
def _argmin_impl(a, dim):
    return jnp.argmin(a, axis=None if dim is None else int(dim)).astype(jnp.int32)


@impl(PrimIDs.TOPK)
def _topk_impl(a, k, dim, largest, sorted):
    dim = int(dim)
    moved = jnp.moveaxis(a, dim, -1)
    if not largest:
        values, indices = jax.lax.top_k(-moved, int(k))
        values = -values
    else:
        values, indices = jax.lax.top_k(moved, int(k))
    return jnp.moveaxis(values, -1, dim), jnp.moveaxis(indices.astype(jnp.int32), -1, dim)


@impl(PrimIDs.SORT)
def _sort_impl(a, dim, descending):
    dim = int(dim)
    key = -a if descending else a
    indices = jnp.argsort(key, axis=dim).astype(jnp.int32)
    values = jnp.take_along_axis(a, indices, axis=dim)
    return values, indices


@impl(PrimIDs.ARGSORT)
def _argsort_impl(a, dim, descending):
    key = -a if descending else a
    return jnp.argsort(key, axis=int(dim)).astype(jnp.int32)


@impl(PrimIDs.CUMSUM)
def _cumsum_impl(a, dim):
    return jnp.cumsum(a, axis=int(dim))


@impl(PrimIDs.CUMPROD)
def _cumprod_impl(a, dim):
    return jnp.cumprod(a, axis=int(dim))


# Scatter/gather
@impl(PrimIDs.TAKE)
def _take_impl(a, indices, dim):
    return jnp.take(a, indices, axis=int(dim))


@impl(PrimIDs.TAKE_ALONG_AXIS)
def _take_along_axis_impl(a, indices, dim):
    return jnp.take_along_axis(a, indices, axis=int(dim))


@impl(PrimIDs.GATHER)
def _gather_impl(a, indices, dim):
    return jnp.take_along_axis(a, indices, axis=int(dim))


@impl(PrimIDs.INDEX_ADD)
def _index_add_impl(a, indices, value, dim):
    dim = int(dim)
    idx = tuple(indices if i == dim else slice(None) for i in range(a.ndim))
    return a.at[idx].add(value)


@impl(PrimIDs.INDEX_PUT)
def _index_put_impl(a, indices, values, accumulate):
    idx = tuple(indices)
    if accumulate:
        return a.at[idx].add(values)
    return a.at[idx].set(values)


@impl(PrimIDs.SCATTER_ADD)
def _scatter_add_impl(a, indices, value, dim):
    dim = int(dim)
    grids = jnp.meshgrid(*[jnp.arange(s) for s in indices.shape], indexing="ij")
    grids[dim] = indices
    v = value
    if v.shape != indices.shape:
        v = v[tuple(slice(0, s) for s in indices.shape)]
    return a.at[tuple(grids)].add(v)


# Linear algebra / NN
@impl(PrimIDs.MATMUL)
def _matmul_impl(a, b):
    return jnp.matmul(a, b)


@impl(PrimIDs.LINEAR)
def _linear_impl(a, w, bias):
    out = jax.lax.dot_general(a, w, (((a.ndim - 1,), (1,)), ((), ())))
    if bias is not None:
        out = out + bias
    return out


@impl(PrimIDs.EMBEDDING)
def _embedding_impl(indices, weight, *, padding_idx=None):
    return jnp.take(weight, indices, axis=0)


@impl(PrimIDs.EMBEDDING_BACKWARD)
def _embedding_backward_impl(grad, indices, num_weights, padding_idx):
    num_weights = int(num_weights)
    flat_idx = indices.reshape(-1)
    flat_grad = grad.reshape(-1, grad.shape[-1])
    from thunder_tpu.executors.pallasex import _mesh_var, _tuning

    def onehot_matmul():
        oh = (flat_idx[:, None] == jnp.arange(num_weights)[None, :])
        return jax.lax.dot_general(
            oh.astype(flat_grad.dtype), flat_grad,
            (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32,
        ).astype(grad.dtype)

    mesh = _mesh_var.get()
    if mesh is not None and mesh.size > 1:
        # One-hot matmul instead of scatter-add under a multi-device mesh:
        # the (V, N)·(N, C) contraction partitions like any other matmul
        # (data-sharded N → grad all-reduce) and rides the MXU.  XLA's
        # scatter partitioner on this pattern either replicates the whole
        # (N, C) update matrix (spmd_partitioner.cc:652 "involuntary full
        # rematerialization" when the vocab dim is sharded) or produces a
        # numerically WRONG sum (measured 5e-2 vs an f64 reference when the
        # embd dim is sharded).
        out = onehot_matmul()
    elif _tuning().get("embedding_bwd", {}).get("single_device_winner") == "onehot":
        # single device is a measured choice (tools/kernel_tune.py): the
        # matmul costs 2·N·V·C real FLOPs but rides the MXU, the scatter is
        # bandwidth+serialization — whichever won on hardware is recorded
        out = onehot_matmul()
    else:
        out = jnp.zeros((num_weights, grad.shape[-1]), dtype=grad.dtype)
        out = out.at[flat_idx].add(flat_grad)
    if padding_idx is not None and padding_idx >= 0:
        out = out.at[int(padding_idx)].set(0)
    return out


@impl(PrimIDs.ONE_HOT)
def _one_hot_impl(indices, num_classes):
    return jax.nn.one_hot(indices, int(num_classes), dtype=jnp.int32)


@impl(PrimIDs.EINSUM)
def _einsum_impl(spec, *operands):
    return jnp.einsum(spec, *operands)


@impl(PrimIDs.REDUCE_WINDOW)
def _reduce_window_impl(a, kind, window, strides, padding):
    n = len(window)
    lead = a.ndim - n
    window_dims = (1,) * lead + tuple(int(w) for w in window)
    window_strides = (1,) * lead + tuple(int(s) for s in strides)
    pads = [(0, 0)] * lead + [(int(lo), int(hi)) for lo, hi in padding]
    # plain-scalar inits keep lax on the monoid (reduce_window_max/sum) path,
    # which is the differentiable one
    if kind == "max":
        init = -float("inf") if jnp.issubdtype(a.dtype, jnp.floating) else int(jnp.iinfo(a.dtype).min)
        return jax.lax.reduce_window(a, init, jax.lax.max, window_dims, window_strides, pads)
    return jax.lax.reduce_window(a, 0 if jnp.issubdtype(a.dtype, jnp.integer) else 0.0, jax.lax.add, window_dims, window_strides, pads)


@impl(PrimIDs.RESIZE)
def _resize_impl(a, shape, method):
    _method = {"bilinear": "linear", "trilinear": "linear", "bicubic": "cubic"}.get(method, method)
    return jax.image.resize(a, tuple(int(s) for s in shape), method=_method, antialias=False)


@impl(PrimIDs.CONVOLUTION)
def _convolution_impl(a, weight, bias, stride, padding, dilation, transposed, output_padding, groups):
    ndim = a.ndim - 2
    dn = jax.lax.conv_dimension_numbers(
        a.shape,
        weight.shape,
        (
            ("NCHW"[: 2 + ndim] if ndim <= 2 else "NCDHW"),
            ("OIHW"[: 2 + ndim] if ndim <= 2 else "OIDHW"),
            ("NCHW"[: 2 + ndim] if ndim <= 2 else "NCDHW"),
        ),
    )
    out = jax.lax.conv_general_dilated(
        a,
        weight,
        window_strides=tuple(int(s) for s in stride),
        padding=[(int(p), int(p)) for p in padding],
        rhs_dilation=tuple(int(d) for d in dilation),
        dimension_numbers=dn,
        feature_group_count=int(groups),
    )
    if bias is not None:
        out = out + bias.reshape((1, -1) + (1,) * ndim)
    return out


# Fused attention.  The reference impls below are the jnp decomposition
# (numerically the flash algorithm's result, materializing the score matrix);
# the Pallas executor (pallasex.py) installs blockwise flash kernels into
# these hooks so every execution path — claimed traces, XLA fusion regions,
# and the distributed TrainStep's trace evaluation — dispatches to them when
# the shapes/backend qualify.
_sdpa_fast_path: Callable | None = None  # (q, k, v, mask, causal, scale) -> (out, lse) or None
_sdpa_bwd_fast_path: Callable | None = None


def _gqa_expand(q, k, v):
    """Expand grouped K/V heads to q's head count for the decomposed path
    (the fused kernels index groups natively instead — pallasex.py)."""
    if q.shape[:-2] == k.shape[:-2]:
        return k, v, 1
    rep = q.shape[-3] // k.shape[-3]
    return jnp.repeat(k, rep, axis=-3), jnp.repeat(v, rep, axis=-3), rep


def _band(Tq, Tk, window):
    """Causal(+sliding-window) boolean mask: row i attends cols in
    (i-window, i] — top-left aligned like the torch decomposition."""
    cm = jnp.tril(jnp.ones((Tq, Tk), dtype=bool))
    if window is not None:
        row = jnp.arange(Tq)[:, None]
        col = jnp.arange(Tk)[None, :]
        cm = cm & (col > row - window)
    return cm


def _sdpa_reference(q, k, v, mask, causal, scale, window=None):
    k, v, _ = _gqa_expand(q, k, v)
    s = jnp.einsum("...qd,...kd->...qk", q, k, preferred_element_type=jnp.float32)
    s = s * scale
    if mask is not None:
        s = s + mask.astype(jnp.float32)
    if causal:
        s = jnp.where(_band(q.shape[-2], k.shape[-2], window), s, -jnp.inf)
    lse = jax.nn.logsumexp(s, axis=-1)
    p = jnp.exp(s - lse[..., None])
    out = jnp.einsum("...qk,...kd->...qd", p.astype(v.dtype), v)
    return out.astype(q.dtype), lse


@impl(PrimIDs.SDPA)
def _sdpa_impl(q, k, v, mask, causal, scale, window=None):
    if _sdpa_fast_path is not None:
        res = _sdpa_fast_path(q, k, v, mask, causal, scale, window)
        if res is not None:
            return res
    return _sdpa_reference(q, k, v, mask, causal, scale, window)


def _sdpa_backward_reference(g, q, k, v, out, lse, mask, causal, scale, window=None):
    kx, vx, rep = _gqa_expand(q, k, v)
    s = jnp.einsum("...qd,...kd->...qk", q, kx, preferred_element_type=jnp.float32) * scale
    if mask is not None:
        s = s + mask.astype(jnp.float32)
    if causal:
        s = jnp.where(_band(q.shape[-2], kx.shape[-2], window), s, -jnp.inf)
    p = jnp.exp(s - lse[..., None])  # (..., Tq, Tk) f32
    dv = jnp.einsum("...qk,...qd->...kd", p, g.astype(jnp.float32))
    dp = jnp.einsum("...qd,...kd->...qk", g, vx, preferred_element_type=jnp.float32)
    delta = jnp.sum(g.astype(jnp.float32) * out.astype(jnp.float32), axis=-1, keepdims=True)
    ds = p * (dp - delta) * scale
    dq = jnp.einsum("...qk,...kd->...qd", ds, kx.astype(jnp.float32))
    dk = jnp.einsum("...qk,...qd->...kd", ds, q.astype(jnp.float32))
    if rep > 1:  # sum the expanded-head grads back onto the shared KV groups
        G = k.shape[-3]
        dk = dk.reshape(*dk.shape[:-3], G, rep, *dk.shape[-2:]).sum(axis=-3)
        dv = dv.reshape(*dv.shape[:-3], G, rep, *dv.shape[-2:]).sum(axis=-3)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


@impl(PrimIDs.SDPA_BACKWARD)
def _sdpa_backward_impl(g, q, k, v, out, lse, mask, causal, scale, window=None):
    if _sdpa_bwd_fast_path is not None:
        res = _sdpa_bwd_fast_path(g, q, k, v, out, lse, mask, causal, scale, window)
        if res is not None:
            return res
    return _sdpa_backward_reference(g, q, k, v, out, lse, mask, causal, scale, window)


_ce_fast_path: Callable | None = None  # installed by pallasex (fused CE kernel)


def _cross_entropy_fwd_reference(logits, target):
    lg = logits.astype(jnp.float32)
    lse = jax.nn.logsumexp(lg, axis=-1)
    picked = jnp.take_along_axis(lg, target[:, None].astype(jnp.int32), axis=-1)[:, 0]
    return lse - picked, lse


@impl(PrimIDs.CROSS_ENTROPY_FWD)
def _cross_entropy_fwd_impl(logits, target):
    if _ce_fast_path is not None:
        res = _ce_fast_path(logits, target)
        if res is not None:
            return res
    return _cross_entropy_fwd_reference(logits, target)


def _flce_chunk(V: int, desired: int = 8192) -> int:
    """Vocab chunk for the fused linear+CE scan: the largest MXU-friendly
    slab ≤ ``desired`` that DIVIDES ``V`` — divisibility is load-bearing, a
    non-divisor would silently drop the tail vocab rows from the softmax."""
    for c in (8192, 4096, 2048, 1024, 512, 256, 128, 64, 32, 16, 8, 4, 2, 1):
        if c <= desired and V % c == 0:
            return c
    return V


def _flce_partials(h, w, tgt, global_off, CH):
    """Online-logsumexp partials of ``h @ w.T`` scanned over vocab chunks of
    size ``CH`` (must divide ``w.shape[0]``).  ``global_off`` is ``w``'s
    offset in the full vocab (nonzero for a vocab shard, see
    distributed/vocab_parallel.py).  Returns float32 (N,) ``(m, s, tl)``:
    running max, normalizer at ``m``, and the target logit (0 when the
    target id falls outside this ``w``)."""
    N = h.shape[0]
    V = w.shape[0]
    n_chunks = V // CH

    def body(carry, c):
        m, s, tl = carry
        off = c * CH
        wc = jax.lax.dynamic_slice_in_dim(w, off, CH, axis=0)
        lg = jax.lax.dot_general(h, wc, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)  # (N, CH)
        m_new = jnp.maximum(m, jnp.max(lg, axis=-1))
        s = s * jnp.exp(m - m_new) + jnp.sum(jnp.exp(lg - m_new[:, None]), axis=-1)
        gcol = global_off + off
        in_chunk = jnp.logical_and(tgt >= gcol, tgt < gcol + CH)
        idx = jnp.clip(tgt - gcol, 0, CH - 1)
        cand = jnp.take_along_axis(lg, idx[:, None], axis=1)[:, 0]
        tl = jnp.where(in_chunk, cand, tl)
        return (m_new, s, tl), None

    init = (
        jnp.full((N,), -jnp.inf, dtype=jnp.float32),
        jnp.zeros((N,), dtype=jnp.float32),
        jnp.zeros((N,), dtype=jnp.float32),
    )
    (m, s, tl), _ = jax.lax.scan(body, init, jnp.arange(n_chunks))
    return m, s, tl


@impl(PrimIDs.FUSED_LINEAR_CE)
def _fused_linear_ce_impl(h, w, target, ignore_index=-100):
    """Online-logsumexp CE over vocab chunks of ``h @ w.T`` — the (N, V)
    logits never exist in HBM; peak extra memory is one (N, CH) slab."""
    V = w.shape[0]
    tgt = target.astype(jnp.int32)
    m, s, tl = _flce_partials(h, w, tgt, 0, _flce_chunk(V))
    lse = m + jnp.log(s)
    losses = jnp.where(tgt != ignore_index, lse - tl, 0.0)
    return losses, lse


@impl(PrimIDs.FUSED_LINEAR_CE_BACKWARD)
def _fused_linear_ce_backward_impl(g, h, w, target, lse, ignore_index=-100):
    """dh/dw from chunked softmax recompute: ds_c = (p_c - onehot_c) * g."""
    N, C = h.shape
    V = w.shape[0]
    CH = _flce_chunk(V)
    n_chunks = V // CH
    tgt = target.astype(jnp.int32)
    gg = jnp.where(tgt != ignore_index, g.astype(jnp.float32), 0.0)

    def body(dh, c):
        off = c * CH
        wc = jax.lax.dynamic_slice_in_dim(w, off, CH, axis=0)
        lg = jax.lax.dot_general(h, wc, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        p = jnp.exp(lg - lse[:, None])  # (N, CH)
        col = off + jnp.arange(CH)
        oh = (tgt[:, None] == col[None, :]).astype(jnp.float32)
        ds = (p - oh) * gg[:, None]
        dh = dh + jax.lax.dot_general(ds, wc.astype(jnp.float32), (((1,), (0,)), ((), ())))
        dwc = jax.lax.dot_general(ds, h.astype(jnp.float32), (((0,), (0,)), ((), ())))
        return dh, dwc.astype(w.dtype)

    dh, dwcs = jax.lax.scan(body, jnp.zeros((N, C), dtype=jnp.float32), jnp.arange(n_chunks))
    dw = dwcs.reshape(V, C)
    return dh.astype(h.dtype), dw


def get_prim_impl(pid: PrimIDs) -> Callable | None:
    return prim_impls.get(pid)


#
# The executor object: registers an eager implementation for every prim above.
# These claimed symbols are also fusible by the XLA fusion executor (they are
# pure jax-traceable callables), marked via _xla_fusible.
#

ex = OperatorExecutor("jax", version=jax.__version__)
register_executor(ex)

for _pid, _impl_fn in list(prim_impls.items()):
    _prim_sym = prim_lookup[_pid]
    _op = ex.register_operator(f"jax_{_prim_sym.name}", like=_prim_sym, fn=_impl_fn)
    _op._xla_fusible = True
    _op._prim_id = _pid
    ex.register_implementation(_pid, _op)

jax_ex = ex

add_default_executor(ex)
add_always_executor(ex)
