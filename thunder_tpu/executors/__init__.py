"""Built-in executors.

Importing this package registers the default executor stack:
``xla`` (fusion, highest priority) ≻ ``pallas`` (hand-written TPU kernels)
≻ ``jax`` (eager operator executor, also the always-executor).
"""
from thunder_tpu.executors import jaxex  # noqa: F401  (registers "jax", default+always)
from thunder_tpu.executors import xlaex  # noqa: F401  (registers "xla", default)
from thunder_tpu.executors import pallasex  # noqa: F401  (registers "pallas", default, highest priority)

from thunder_tpu.executors.jaxex import jax_ex
from thunder_tpu.executors.pallasex import pallas_ex
from thunder_tpu.executors.xlaex import xla_ex

__all__ = ["jax_ex", "pallas_ex", "xla_ex"]
