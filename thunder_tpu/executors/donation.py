"""Del-aware buffer donation analysis for lowered traces.

On TPU the batch size and step time of the programs this framework emits are
bound by peak HBM and copy bandwidth, not FLOPs.  Every XLA fusion region is
a separate ``jax.jit`` program, and without ``donate_argnums`` XLA must keep
each region input alive across the call even when the lowered trace provably
kills it immediately afterwards (``del_last_used`` already computes exactly
that).  This module closes the gap: :func:`analyze_trace_donations` proves,
from the lowered trace alone, which region inputs are safe to donate, and
:func:`apply_donation` re-arms each region's :class:`FusionCallable` with the
proven ``donate_argnums`` (plus shape/dtype-compatible input→output alias
hints — the ``copy_``/optimizer-update pattern, where the new value can land
in the dead old value's buffer).

Safety contract — an input of fusion region R is donatable iff:

- its last (non-``del``) consumer is R: a ``DEL`` of it follows R in the
  lowered trace and no later bound symbol reads it (this also covers "input
  to a later region");
- it is not a trace output (``RETURN`` operand — the caller receives it);
- it is not an endpoint of an eagerly-executed view-class op
  (``SHAPE_OP``-tagged bsyms outside fusion regions may alias buffers at the
  XLA runtime's discretion, so donating one endpoint could invalidate the
  other).

Every rejection is counted per reason in the ``donation.*`` metrics
(``thunder_tpu.observability``) so "why wasn't this donated?" is always
answerable from a snapshot.

The "Some donated buffers were not usable" warning handling (CPU has no
donation; XLA may also decline a donation it cannot use) is centralized in
:func:`suppress_unusable_donation_warnings`, shared with the decode loops in
``models/generate.py`` / ``models/speculative.py`` and ``TrainStep``.
"""
from __future__ import annotations

import contextlib
import warnings
from dataclasses import dataclass, field

from thunder_tpu.core.prims import OpTags, PrimIDs
from thunder_tpu.core.proxies import TensorProxy
from thunder_tpu.core.symbol import BoundSymbol, gather_provenance
from thunder_tpu.core.trace import TraceCtx, TraceProvenance, from_trace

__all__ = [
    "DonationError",
    "DonationReport",
    "RegionDonation",
    "analyze_trace_donations",
    "apply_donation",
    "donation_summary",
    "suppress_unusable_donation_warnings",
    "REJECT_TRACE_OUTPUT",
    "REJECT_LATER_USE",
    "REJECT_ALIASED_VIEW",
    "REJECT_NO_DEL",
]

# jax emits this (module jax._src.interpreters.mlir / pxla depending on
# version) once per compile/execute when a donated buffer cannot be used —
# e.g. the CPU backend, or an input XLA found no aliasing opportunity for.
# Donation is still correct there (it degrades to a no-op), so the framework
# silences exactly this message wherever it donates on purpose.
_UNUSABLE_DONATION_MSG = "Some donated buffers were not usable"


@contextlib.contextmanager
def suppress_unusable_donation_warnings():
    """Scoped filter for jax's "donated buffers were not usable" note.

    The ONE place this warning is handled: ``FusionCallable`` wraps donated
    region calls in it, ``TrainStep`` wraps its donated step, and the decode
    loops in ``models/generate.py`` / ``models/speculative.py`` use it around
    their cache-donating programs."""
    with warnings.catch_warnings():
        warnings.filterwarnings("ignore", message=_UNUSABLE_DONATION_MSG)
        yield


class DonationError(RuntimeError):
    """An explicitly requested donation is provably unsafe.

    Raised by :func:`apply_donation` in strict mode (``tt.jit(fn,
    donate=(argnums,))``): the user asserted an input's buffer may be
    consumed, but the lowered trace shows it escaping — the message names
    the proxy, the rejection reason, and the source provenance of the
    blocking use so the fix is one hop away."""


REJECT_TRACE_OUTPUT = "trace_output"
REJECT_LATER_USE = "later_use"
REJECT_ALIASED_VIEW = "aliased_view"
REJECT_NO_DEL = "no_del"
# strict-mode only: the candidate never reached any fusion region (the trace
# has none, or only eager symbols consume it) — there is nowhere to donate it
REJECT_UNFUSED = "unfused"


@dataclass
class RegionDonation:
    """Donation decision for one fusion region."""

    name: str                                   # fusion symbol name (XLA0, ...)
    index: int                                  # position in trace.bound_symbols
    bsym: BoundSymbol
    donated: list = field(default_factory=list)       # [(arg_pos, TensorProxy)]
    aliases: dict = field(default_factory=dict)       # input name -> output name
    rejected: dict = field(default_factory=dict)      # input name -> (reason, blocking_bsym|None)
    donated_bytes: int = 0


@dataclass
class DonationReport:
    """The full analysis result for one lowered trace."""

    regions: list = field(default_factory=list)        # [RegionDonation]
    protected_names: frozenset = frozenset()           # RETURN operands
    view_names: frozenset = frozenset()                # endpoints of eager view-class ops

    @property
    def donated_buffers(self) -> int:
        return sum(len(r.donated) for r in self.regions)

    @property
    def donated_bytes(self) -> int:
        return sum(r.donated_bytes for r in self.regions)

    def rejections(self) -> dict:
        out: dict[str, int] = {}
        for r in self.regions:
            for reason, _ in r.rejected.values():
                out[reason] = out.get(reason, 0) + 1
        return out


def _proxy_nbytes(p) -> int:
    from thunder_tpu.observability.memory import tensor_nbytes

    return tensor_nbytes(p)


def analyze_trace_donations(
    trace: TraceCtx, *, candidate_names: set | None = None
) -> DonationReport:
    """Proves which fusion-region inputs are safe to donate, from the lowered
    trace alone (requires ``del_last_used`` to have run so buffer death is
    explicit as ``DEL`` bound symbols).

    ``candidate_names`` restricts the candidate set (the ``donate=argnums``
    form); ``None`` considers every tensor input of every region.  Inputs
    outside the candidate set are skipped silently — they are neither donated
    nor counted as rejections."""
    from thunder_tpu.executors.utils import trace_return_names

    bsyms = trace.bound_symbols
    protected: set[str] = trace_return_names(trace)

    # last non-del, non-return read and first del AFTER each position
    last_use: dict[str, int] = {}
    del_index: dict[str, int] = {}
    view_names: set[str] = set()
    for i, bsym in enumerate(bsyms):
        if bsym.sym.id == PrimIDs.DEL:
            for p in bsym.flat_proxy_args:
                del_index[p.name] = i
            continue
        if bsym.sym.id == PrimIDs.RETURN:
            continue
        for p in bsym.flat_proxy_args:
            last_use[p.name] = i
        # an eagerly-executed (unfused) view-class op may alias its operand's
        # buffer at runtime; both endpoints are unsafe to donate anywhere
        if not bsym.sym.is_fusion and bsym.sym.tags and OpTags.SHAPE_OP in bsym.sym.tags:
            for p in list(bsym.flat_proxy_args) + list(bsym.flat_proxy_outs):
                if isinstance(p, TensorProxy):
                    view_names.add(p.name)

    report = DonationReport(
        protected_names=frozenset(protected), view_names=frozenset(view_names)
    )

    for i, bsym in enumerate(bsyms):
        if not bsym.sym.is_fusion:
            continue
        region = RegionDonation(name=bsym.sym.name, index=i, bsym=bsym)
        for pos, p in enumerate(bsym.args):
            if not isinstance(p, TensorProxy):
                continue
            name = p.name
            if candidate_names is not None and name not in candidate_names:
                continue
            if name in protected:
                region.rejected[name] = (REJECT_TRACE_OUTPUT, None)
            elif last_use.get(name, -1) > i:
                region.rejected[name] = (REJECT_LATER_USE, bsyms[last_use[name]])
            elif name in view_names:
                region.rejected[name] = (REJECT_ALIASED_VIEW, None)
            elif del_index.get(name, -1) <= i:
                # no DEL after the region: liveness was not (or could not be)
                # established — without the proof, keep the buffer
                region.rejected[name] = (REJECT_NO_DEL, None)
            else:
                region.donated.append((pos, p))
                region.donated_bytes += _proxy_nbytes(p)
        _match_aliases(region)
        report.regions.append(region)
    return report


def _match_aliases(region: RegionDonation) -> None:
    """Greedy input→output alias hints: each donated dead input is paired
    with the first unclaimed region output of identical shape/dtype — the
    ``copy_``/optimizer-update pattern, where XLA can write the new value
    straight into the donated buffer.  Purely informational (XLA performs
    the actual aliasing through ``donate_argnums``): the hints feed the
    donation metrics and the memory timeline's reuse accounting."""
    outs = [o for o in region.bsym.flat_proxy_outs if isinstance(o, TensorProxy)]
    claimed: set[str] = set()
    for _, p in region.donated:
        for o in outs:
            if o.name in claimed:
                continue
            if tuple(o.shape) == tuple(p.shape) and o.dtype == p.dtype:
                region.aliases[p.name] = o.name
                claimed.add(o.name)
                break


def _format_provenance(bsym: BoundSymbol | None) -> str:
    if bsym is None:
        return ""
    entries = gather_provenance(bsym)
    if not entries:
        return ""
    fname, pos = entries[0]
    lineno = getattr(pos, "lineno", pos)
    return f" (blocking use: {bsym.sym.name} from {fname}:{lineno})"


def apply_donation(
    trace: TraceCtx,
    *,
    candidate_names: set | None = None,
    strict: bool = False,
    which: str = "forward",
) -> tuple[TraceCtx, DonationReport]:
    """Runs the analysis and arms the trace's fusion callables.

    Returns a new trace (provenance-stamped, fusion bsyms annotated with a
    ``_donation`` record and a codegen header comment) plus the report.
    Publishes the ``donation.*`` metrics.  In strict mode (explicit
    ``donate=argnums``), a rejected candidate raises :class:`DonationError`
    instead of being skipped."""
    from thunder_tpu.observability.metrics import registry

    report = analyze_trace_donations(trace, candidate_names=candidate_names)

    if strict:
        # a candidate rejected at one region may legally donate at a LATER
        # region (its true last consumer); only a nowhere-donated candidate
        # violates the user's explicit assertion.  Report the most specific
        # rejection (anything beats later_use, which only says "not here").
        donated_names = {p.name for r in report.regions for _, p in r.donated}
        worst: dict[str, tuple] = {}
        for region in report.regions:
            for name, (reason, blocker) in region.rejected.items():
                if name in donated_names:
                    continue
                if name not in worst or worst[name][0] == REJECT_LATER_USE:
                    worst[name] = (reason, blocker, region)
        # a candidate no fusion region consumes is rejected nowhere above —
        # classify it here (trace output / aliased view / simply unfused) and
        # point at its last reader so the error still lands on a source line
        for name in sorted(candidate_names or ()):
            if name in donated_names or name in worst:
                continue
            blocker = None
            for b in trace.bound_symbols:
                if b.sym.id == PrimIDs.DEL:
                    continue
                if any(p.name == name for p in b.flat_proxy_args):
                    blocker = b
            if name in report.protected_names:
                worst[name] = (REJECT_TRACE_OUTPUT, blocker, None)
            elif name in report.view_names:
                worst[name] = (REJECT_ALIASED_VIEW, blocker, None)
            else:
                worst[name] = (REJECT_UNFUSED, blocker, None)
        for name, (reason, blocker, region) in worst.items():
            at = f" at fusion region {region.name}" if region is not None else ""
            raise DonationError(
                f"donation of {name!r} was requested explicitly but is unsafe: "
                f"{reason}{at}"
                f"{_format_provenance(blocker) or (_format_provenance(region.bsym) if region is not None else '')} — "
                f"drop it from donate= or stop reusing the buffer"
            )

    reg = registry()
    annotated: dict[int, BoundSymbol] = {}
    total_aliases = 0
    for region in report.regions:
        for reason, _ in region.rejected.values():
            reg.counter(f"donation.rejected.{reason}").inc()
        if not region.donated:
            continue
        reg.counter("donation.regions").inc()
        reg.counter("donation.buffers_donated").inc(len(region.donated))
        reg.counter("donation.bytes_donated").inc(region.donated_bytes)
        total_aliases += len(region.aliases)

        names = [p.name for _, p in region.donated]
        info = {
            "donated": names,
            "aliases": dict(region.aliases),
            "bytes": region.donated_bytes,
        }
        alias_note = "".join(
            f"; {a} reused for {b}" for a, b in region.aliases.items()
        )
        header = f"donated: {', '.join(names)} ({region.donated_bytes} bytes{alias_note})"
        bsym = region.bsym
        new_bsym = bsym.from_bsym(
            header=f"{bsym.header}\n{header}" if bsym.header else header
        )
        new_bsym._donation = info
        annotated[region.index] = new_bsym
        region.bsym = new_bsym

        # arm the compiled region: positions follow the callable's own input
        # order (identical to the bsym arg order by construction, but matched
        # by name so hand-built traces and re-lowered regions stay safe)
        fusion = (bsym._call_ctx or {}).get(bsym.sym.name)
        if fusion is not None and hasattr(fusion, "set_donation"):
            argnums = tuple(
                fusion.input_names.index(n) for n in names if n in fusion.input_names
            )
            fusion.set_donation(argnums, region.aliases)
    if total_aliases:
        reg.counter("donation.aliased_outputs").inc(total_aliases)

    ntrace = from_trace(trace)
    ntrace.bound_symbols = [
        annotated.get(i, b) for i, b in enumerate(trace.bound_symbols)
    ]
    rej = report.rejections()
    rej_note = (
        " rejected " + ", ".join(f"{k}={v}" for k, v in sorted(rej.items()))
        if rej
        else ""
    )
    ntrace._donation_summary = (
        f"{report.donated_buffers} buffer(s) / {report.donated_bytes} bytes donated"
        f" across {sum(1 for r in report.regions if r.donated)} region(s);{rej_note}"
        if report.regions
        else "no fusion regions"
    )
    ntrace.set_provenance(
        TraceProvenance(
            f"Donation analysis ({which}): {report.donated_buffers} buffers / "
            f"{report.donated_bytes} bytes donated"
        )
    )
    return ntrace, report


def donation_summary(report: DonationReport) -> dict:
    """Plain-dict view of a report (what ``tt.donation_stats`` returns)."""
    return {
        "buffers_donated": report.donated_buffers,
        "bytes_donated": report.donated_bytes,
        "regions": [
            {
                "name": r.name,
                "donated": [p.name for _, p in r.donated],
                "aliases": dict(r.aliases),
                "bytes": r.donated_bytes,
                "rejected": {n: reason for n, (reason, _) in r.rejected.items()},
            }
            for r in report.regions
        ],
        "rejections": report.rejections(),
    }
