"""Dataflow-aware fusion partitioning.

Capability analog of the reference's ``thunder/executors/
data_dependent_partition.py`` (``fuse_bound_symbols``: toposort-based group
merging with cycle checks).  The round-1 xlaex pass fused only *adjacent*
fusible bsyms, so a single non-fusible op (an all-reduce, an item(), a
pallas call) split an otherwise-fusible region in two.  This partitioner
groups by dataflow instead: a fusible bsym joins an existing group whenever
doing so cannot create a cycle through a node outside the group, so fusible
islands reorder *around* non-fusible bsyms and XLA sees maximal programs.

Cycle-safety must be judged at the **group** level: a group's dependencies
are the union of its members', so a member added later can make the whole
group depend on something an individual node's ancestry does not show.  The
partitioner therefore maintains the transitive closure of the group DAG as
integer bitsets (``greach``), propagated to dependents on every join —
``n`` may join group ``g`` iff no producer group of ``n`` other than ``g``
transitively depends on ``g``.
"""
from __future__ import annotations

from typing import Callable, Sequence

from thunder_tpu.core.symbol import BoundSymbol

__all__ = ["fuse_bound_symbols", "Group"]


class Group:
    __slots__ = ("gid", "fusible", "bsyms")

    def __init__(self, gid: int, fusible: bool):
        self.gid = gid
        self.fusible = fusible
        self.bsyms: list[BoundSymbol] = []


def fuse_bound_symbols(
    bsyms: Sequence[BoundSymbol], should_fuse: Callable[[BoundSymbol], bool]
) -> list[Group]:
    """Partitions ``bsyms`` (assumed topologically ordered — trace order) into
    groups; members of a fusible group need not be adjacent in the input.
    Returns groups in a valid topological order of the group DAG."""
    n = len(bsyms)
    producer_of: dict[str, int] = {}
    direct_prods: list[list[int]] = [[] for _ in range(n)]
    for i, b in enumerate(bsyms):
        seen_p = set()
        for a in b.flat_proxy_args:
            p = producer_of.get(a.name)
            if p is not None and p not in seen_p:
                seen_p.add(p)
                direct_prods[i].append(p)
        for o in b.flat_proxy_outs:
            producer_of.setdefault(o.name, i)

    groups: list[Group] = []
    group_of: list[int] = [0] * n
    # group-level transitive dependency closure, as bitsets over group ids
    greach: list[int] = []
    rdeps: list[set[int]] = []  # gid -> groups that directly depend on it

    def new_group(fusible: bool) -> Group:
        g = Group(len(groups), fusible)
        groups.append(g)
        greach.append(0)
        rdeps.append(set())
        return g

    def propagate(gid: int):
        """greach[gid] grew: push the new closure to dependents."""
        stack = [gid]
        while stack:
            g = stack.pop()
            add = greach[g] | (1 << g)
            for d in rdeps[g]:
                if add & ~greach[d]:
                    greach[d] |= add
                    stack.append(d)

    def assign(i: int, t: Group):
        group_of[i] = t.gid
        t.bsyms.append(bsyms[i])
        grew = False
        for p in direct_prods[i]:
            h = group_of[p]
            if h == t.gid:
                continue
            add = greach[h] | (1 << h)
            if add & ~greach[t.gid]:
                greach[t.gid] |= add
                grew = True
            rdeps[h].add(t.gid)
        if grew:
            propagate(t.gid)

    for i, b in enumerate(bsyms):
        if not should_fuse(b):
            assign(i, new_group(False))
            continue

        def safe_to_join(pg: Group) -> bool:
            # joining adds edges (producer groups of n) -> pg; a cycle needs a
            # pre-existing path pg ⇝ some producer group h ≠ pg, i.e. h's
            # closure containing pg.  n ⇝ pg paths are impossible (topo order).
            gbit = 1 << pg.gid
            for q in direct_prods[i]:
                h = group_of[q]
                if h != pg.gid and (greach[h] & gbit):
                    return False
            return True

        target: Group | None = None
        seen_cand: set[int] = set()
        # producers' groups first (locality), then any fusible group newest-
        # first so independent islands merge into one region
        for p in direct_prods[i]:
            pg = groups[group_of[p]]
            if pg.fusible and pg.gid not in seen_cand:
                seen_cand.add(pg.gid)
                if safe_to_join(pg):
                    target = pg
                    break
        if target is None:
            for pg in reversed(groups):
                if pg.fusible and pg.gid not in seen_cand:
                    seen_cand.add(pg.gid)
                    if safe_to_join(pg):
                        target = pg
                        break
        if target is None:
            target = new_group(True)
        assign(i, target)

    # topological order over the group DAG (stable by first-member position)
    first_pos: dict[int, int] = {}
    for i in range(n):
        first_pos.setdefault(group_of[i], i)
    gdeps: dict[int, set[int]] = {g.gid: set() for g in groups}
    for i in range(n):
        gi = group_of[i]
        for p in direct_prods[i]:
            if group_of[p] != gi:
                gdeps[gi].add(group_of[p])

    ordered: list[Group] = []
    visited: set[int] = set()
    temp: set[int] = set()

    def visit(gid: int):
        if gid in visited:
            return
        if gid in temp:  # pragma: no cover - partitioner invariant
            raise RuntimeError("fusion partitioner produced a cyclic group graph")
        temp.add(gid)
        for d in sorted(gdeps[gid], key=lambda g: first_pos.get(g, 0)):
            visit(d)
        temp.discard(gid)
        visited.add(gid)
        ordered.append(groups[gid])

    for g in sorted(groups, key=lambda g: first_pos.get(g.gid, 0)):
        visit(g.gid)
    return [g for g in ordered if g.bsyms]
