"""The XLA fusion executor: trace regions → single jax.jit-compiled programs.

Capability analog of the reference's nvFuser executor
(``thunder/executors/nvfuserex_impl.py``): it partitions the trace into
maximal fusible regions and compiles each into one callable.  On TPU the
"fusion backend" is XLA itself — a region becomes a pure-JAX function
(re-evaluating the region's bound symbols over jax values) wrapped in
``jax.jit``, so XLA performs fusion, layout assignment, and latency hiding.
Unlike nvFuser there is no bookending heuristic: XLA handles meta/shape ops
fine inside a program, so regions are as large as possible (ideally the whole
computation), which is exactly the TPU-idiomatic design.
"""
from __future__ import annotations

import time
from typing import Any, Callable, Sequence

import jax

from thunder_tpu.core.compile_data import get_compile_option
from thunder_tpu.core.prims import OpTags, PrimIDs
from thunder_tpu.core.proxies import Proxy, TensorProxy, unvariableify
from thunder_tpu.core.symbol import BoundSymbol, Symbol
from thunder_tpu.core.trace import TraceCtx, TraceProvenance, from_trace
from thunder_tpu.core.utils import consumers, producers
from thunder_tpu.extend import FusionExecutor, add_default_executor, register_executor
from thunder_tpu.executors.utils import Region, eval_bsyms
from thunder_tpu.observability.events import span as _phase_span

__all__ = ["XLAFusionExecutor", "ex", "xla_ex"]

_NONFUSIBLE_IDS = {
    PrimIDs.RETURN,
    PrimIDs.DEL,
    PrimIDs.COMMENT,
    PrimIDs.PRINT,
    PrimIDs.ITEM,
    PrimIDs.DEVICE_PUT,
    PrimIDs.GET_GRAD,
    PrimIDs.PUT_GRAD,
}


class FusionCallable:
    """A compiled region; keeps the sub-trace for inspection and re-lowering."""

    def __init__(self, name: str, bsyms: Sequence[BoundSymbol], inputs: Sequence[Proxy], outputs: Sequence[Proxy]):
        self.name = name
        self.bsyms = list(bsyms)
        self.input_names = [p.name for p in inputs]
        self.output_names = [p.name for p in outputs]
        #: positions donated to XLA (set post-lowering by the donation pass —
        #: executors/donation.py — never at construction, so the donate=False
        #: path compiles the exact program it always did)
        self.donate_argnums: tuple[int, ...] = ()
        #: input name -> output name alias hints (introspection/metrics; the
        #: actual buffer aliasing is XLA's, via donate_argnums)
        self.out_aliases: dict[str, str] = {}
        self._jitted = jax.jit(self._raw)
        self._compiled_once = False

    def set_donation(self, argnums: Sequence[int], aliases: dict | None = None) -> None:
        """Re-arms the region with ``donate_argnums`` (donation pass only).
        The jit is rebuilt — it is lazy, so nothing recompiles until the next
        call — and the compile event re-fires for the donated program."""
        self.donate_argnums = tuple(sorted(argnums))
        self.out_aliases = dict(aliases or {})
        self._jitted = jax.jit(
            self._raw, donate_argnums=self.donate_argnums or None
        )
        self._compiled_once = False

    def _raw(self, *vals):
        env = dict(zip(self.input_names, vals))
        eval_bsyms(self.bsyms, env)
        return tuple(env[n] for n in self.output_names)

    def __call__(self, *vals):
        if self.donate_argnums:
            # a donated input from an EARLIER call may arrive here deleted
            # (donation consumes the caller's array); catch it before XLA
            # does so the error names the proxy and the source lines that
            # built the region, not just an anonymous deleted buffer
            for i in self.donate_argnums:
                v = vals[i] if i < len(vals) else None
                if getattr(v, "is_deleted", None) is not None and v.is_deleted():
                    from thunder_tpu.core.symbol import gather_provenance
                    from thunder_tpu.executors.donation import DonationError

                    prov = ""
                    for b in self.bsyms:
                        entries = gather_provenance(b)
                        if entries:
                            fname, pos = entries[0]
                            lineno = getattr(pos, "lineno", pos)
                            prov = f" (region built from {fname}:{lineno})"
                            break
                    raise DonationError(
                        f"input {self.input_names[i]!r} (position {i}) of fusion "
                        f"region {self.name} was donated by an earlier call and its "
                        f"buffer is gone{prov} — donated inputs are CONSUMED: pass a "
                        f"fresh array (feed the outputs forward) or compile with "
                        f"donate=False"
                    )
            # backends without donation (CPU) and declined donations warn per
            # execute; the shared helper silences exactly that message
            from thunder_tpu.executors.donation import suppress_unusable_donation_warnings

            with suppress_unusable_donation_warnings():
                return self._call_impl(*vals)
        return self._call_impl(*vals)

    def _call_impl(self, *vals):
        if not self._compiled_once:
            # the first call triggers XLA tracing+compilation (jax.jit is
            # lazy); record it as a pipeline event.  Shape-change recompiles
            # are not re-spanned — one flag check per call is the budget here
            self._compiled_once = True
            with _phase_span("xla_compile", fusion=self.name, ops=len(self.bsyms)):
                return self._jitted(*vals)
        return self._jitted(*vals)

    def lower_hlo(self, *abstract_vals) -> str:
        return self._jitted.lower(*abstract_vals).as_text()

    def __repr__(self):
        return f"<FusionCallable {self.name}: {len(self.bsyms)} ops>"


class XLAFusionExecutor(FusionExecutor):
    def __init__(self):
        super().__init__("xla", version=jax.__version__)

    def _is_fusible(self, bsym: BoundSymbol) -> bool:
        sym = bsym.sym
        if sym.id in _NONFUSIBLE_IDS:
            return False
        if getattr(sym, "_xla_fusible", False):
            return True
        from thunder_tpu.executors.jaxex import prim_impls

        if sym.id in prim_impls:
            return True
        if sym.tags and OpTags.UNPACK_OP in sym.tags or (sym.tags and OpTags.CHECK_OP in sym.tags):
            return False
        # composites whose subsymbols are all fusible
        if bsym.subsymbols:
            return all(self._is_fusible(s) for s in bsym.subsymbols)
        return False

    def can_fuse(self, bsym: BoundSymbol) -> bool:
        return self._is_fusible(bsym)

    def fuse(self, region_bsyms: list[BoundSymbol], fusion_counter: int, producers_map, consumers_map, return_proxies) -> BoundSymbol:
        region = Region(producers_map, consumers_map, region_bsyms)
        # tensors have runtime identity; numbers resolve statically UNLESS
        # their value is unknown at trace time (item() results) — those are
        # runtime scalars and must enter the region as inputs
        from thunder_tpu.core.proxies import NumberProxy

        inputs = [
            p
            for p in (unvariableify(v) for v in region.inputs)
            if isinstance(p, TensorProxy) or (isinstance(p, NumberProxy) and p.value is None)
        ]
        outputs = [unvariableify(v) for v in region.outputs]
        # proxies returned from the trace must also escape the fusion
        out_names = {p.name for p in outputs}
        for p in return_proxies:
            produced_here = any(p.name in (o.name for o in b.flat_proxy_outs) for b in region_bsyms)
            if produced_here and p.name not in out_names:
                outputs.append(p)
                out_names.add(p.name)

        name = f"XLA{fusion_counter}"
        fusion = FusionCallable(name, region_bsyms, inputs, outputs)
        sym = Symbol(name=name, meta=None, is_fusion=True, executor=self)
        bsym = sym.bind(
            *inputs,
            output=tuple(outputs),
            subsymbols=tuple(region_bsyms),
            _call_ctx={name: fusion},
        )
        # a fused region keeps the provenance LIST of every op it absorbed
        # (filename stays None: the list rides in source_positions, which
        # gather_provenance and the anomaly reporter understand) so the user
        # file:line survives even if a later pass drops the subsymbols
        from thunder_tpu.core.symbol import gather_provenance

        bsym.source_positions = list(gather_provenance(bsym))
        return bsym

    @_phase_span("lower:xla_fusion")
    def fusion_pass(self, trace: TraceCtx) -> TraceCtx:
        from thunder_tpu.core.trace import _execution_file

        if _execution_file.get() is not None:
            # execution-callback-file debugging: the dumped program must stay
            # hand-editable, and an XLA fusion's constants live inside an
            # opaque compiled callable — keep per-prim eager execution instead
            return trace
        start = time.perf_counter_ns()

        min_size = get_compile_option(
            "xla_min_fusion_size",
            "Minimum number of bound symbols in a region for it to be compiled as one XLA program (default 2).",
            default=2,
        )

        producers_map = producers(trace)
        consumers_map = consumers(trace)

        from thunder_tpu.core.prims import PrimIDs as _P

        return_proxies: list[Proxy] = []
        for bsym in trace.bound_symbols:
            if bsym.sym.id == _P.RETURN:
                return_proxies.extend(bsym.flat_proxy_args)

        # dataflow-aware partitioning (reference data_dependent_partition.py):
        # fusible islands regroup around non-fusible bsyms instead of being
        # split by them
        from thunder_tpu.executors.data_dependent_partition import fuse_bound_symbols

        groups = fuse_bound_symbols(trace.bound_symbols, self._is_fusible)

        def weight(bsym: BoundSymbol) -> int:
            # region size counts FLATTENED prims: one composite call (gelu,
            # softmax) is one top-level bsym but many ops — leaving it
            # unfused would decompose it to per-prim eager jax dispatch,
            # ~10× per-call overhead on small ops
            if not bsym.subsymbols:
                # a leaf prim whose jnp impl is itself a multi-op program
                # (fused sdpa/CE decompositions, matmul-class ops) is worth a
                # compiled region on its own — executing it eagerly pays one
                # dispatch per internal jnp op
                if bsym.sym.tags and OpTags.MATMUL_OP in bsym.sym.tags:
                    return 1_000
                return 1
            return sum(weight(s) for s in bsym.subsymbols)

        new_bsyms: list[BoundSymbol] = []
        fusion_counter = 0
        for g in groups:
            if (
                not g.fusible
                or sum(weight(b) for b in g.bsyms) < int(min_size)
                or not self.get_fuel()
            ):
                new_bsyms.extend(g.bsyms)
            else:
                new_bsyms.append(self.fuse(g.bsyms, fusion_counter, producers_map, consumers_map, return_proxies))
                fusion_counter += 1

        ntrace = from_trace(trace)
        ntrace.bound_symbols = new_bsyms
        elapsed = (time.perf_counter_ns() - start) // 1000000
        ntrace.set_provenance(TraceProvenance(f"XLA Fusion (took {elapsed} milliseconds)"))
        return ntrace


ex = XLAFusionExecutor()
register_executor(ex)
xla_ex = ex
add_default_executor(ex)
