"""examine(): support reporting and debug tooling.

Capability analog of the reference's ``thunder/examine/__init__.py:49`` —
runs a function under a collection mode, reports which torch operations are
(un)supported by the tracer, tries the jit, and prints a repro template.
Plus ``get_fusions`` (``:190``) and a trace memory calculator
(``examine/memory_caculation.py``).
"""
from __future__ import annotations

import collections
from typing import Any, Callable

__all__ = [
    "examine",
    "get_fusions",
    "get_fusion_symbols",
    "memory_estimate",
    "memory_timeline",
    "train_memory_report",
    "cost_analysis",
]


def _collect_torch_functions(fn, args, kwargs):
    """Runs ``fn`` on real torch tensors under TorchFunctionMode, collecting
    every torch callable used (reference CollectFunctionsUsed)."""
    import torch

    calls: dict[str, Any] = {}

    class Collect(torch.overrides.TorchFunctionMode):
        def __torch_function__(self, func, types, f_args=(), f_kwargs=None):
            f_kwargs = f_kwargs or {}
            qn = getattr(func, "__qualname__", None) or str(func)
            mod = getattr(func, "__module__", "") or ""
            calls.setdefault(f"{mod}.{qn}" if mod else qn, func)
            return func(*f_args, **f_kwargs)

    with Collect():
        result = fn(*args, **kwargs)
    return calls, result


def examine(fn: Callable, *args, **kwargs) -> bool:
    """Reports whether ``fn`` can run through thunder_tpu.jit and why not.

    Returns True when everything checked out.  Never raises — the reference's
    contract is "doesn't crash the user program".
    """
    try:
        import torch
    except ImportError:  # pragma: no cover
        print("examine() requires torch for operation collection")
        return False

    from thunder_tpu.torch import _torch_to_thunder_function_map

    if not callable(fn):
        print(f"examine(): expected a callable, got {type(fn)}")
        return False

    # Step 1: run eagerly, collect the torch surface used
    try:
        calls, torch_result = _collect_torch_functions(fn, args, kwargs)
    except Exception as e:
        print(f"examine(): the function failed outside thunder_tpu ({type(e).__name__}: {e}); fix that first")
        return False

    known = set(_torch_to_thunder_function_map)
    unsupported = {name: f for name, f in calls.items() if isinstance(f, Callable) and f not in known and not _is_benign(f)}

    if unsupported:
        print(f"Found {len(unsupported)} distinct operation(s) not supported by the tracer:")
        for name in sorted(unsupported):
            print(f"  {name}")
        print(
            "\nRepro template for an operator request:\n"
            "  import thunder_tpu as tt\n"
            "  import thunder_tpu.torch as ltorch\n"
            "  def repro(...):  # minimal fn using the op above\n"
            "      ...\n"
            "  tt.jit(repro)(...)\n"
        )
    else:
        print(f"All {len(calls)} collected operations are supported by the tracer")

    # Step 2: try the jit and compare
    try:
        import numpy as np

        import thunder_tpu as tt

        jfn = tt.jit(fn)
        jit_result = jfn(*args, **kwargs)
        diverged = False
        try:
            a = np.asarray(jit_result)
            b = torch_result.detach().to(torch.float32).numpy() if isinstance(torch_result, torch.Tensor) else np.asarray(torch_result)
            if a.shape == getattr(b, "shape", None):
                ok = np.allclose(np.asarray(a, dtype=np.float32), np.asarray(b, dtype=np.float32), rtol=1e-3, atol=1e-4)
                diverged = not ok
                print("jit result matches eager torch" if ok else "WARNING: jit result DIVERGES from eager torch")
        except Exception:
            pass
        print("thunder_tpu.jit compiled and ran the function successfully")
        return not unsupported and not diverged
    except Exception as e:
        print(f"thunder_tpu.jit failed: {type(e).__name__}: {e}")
        return False


def _is_benign(func) -> bool:
    """Attribute accesses and dunder plumbing that need no tracer support."""
    qn = getattr(func, "__qualname__", "") or ""
    return qn.startswith(("Tensor.__", "Tensor.shape", "Tensor.dtype", "Tensor.device", "_has_torch_function"))


def get_fusion_symbols(trace) -> list:
    """All fusion bound symbols (XLA regions) in ``trace``
    (reference examine/__init__.py:190 get_fusions)."""
    out = []
    for bsym in trace.bound_symbols:
        if getattr(bsym.sym, "is_fusion", False):
            out.append(bsym)
    return out


def get_fusions(trace) -> list[tuple[str, Callable]]:
    """(name, callable) for each fusion region in ``trace``."""
    out = []
    for bsym in get_fusion_symbols(trace):
        ctx = bsym._call_ctx or {}
        for name, fusion in ctx.items():
            out.append((name, fusion))
    return out


def memory_estimate(trace) -> dict[str, int]:
    """Bytes of inputs / outputs / peak-intermediate estimate for a trace
    (reference examine/memory_caculation.py).  The intermediate estimate
    walks the trace with del-aware liveness (the shared pass in
    ``observability/memory.py``): it is the ceiling XLA's own buffer reuse
    then improves on.  Donation-aware: on a trace compiled with
    ``tt.jit(fn, donate=...)`` the peak reflects donated buffers being
    reclaimed at their consuming region, and ``donated_bytes`` reports the
    total reclaimed that way.  ``memory_timeline(trace)`` returns the
    per-symbol live/peak rows behind this summary."""
    from thunder_tpu.observability.memory import memory_timeline

    t = memory_timeline(trace)
    return {
        "input_bytes": t["input_bytes"],
        "output_bytes": t["output_bytes"],
        "peak_bytes_estimate": t["peak_bytes_estimate"],
        "donated_bytes": t["donated_bytes"],
    }


def memory_timeline(trace) -> dict:
    """Per-symbol live/peak-bytes rows for ``trace`` (del-aware liveness,
    keyed to ``del_last_used`` placement) — see
    ``thunder_tpu.observability.memory.memory_timeline``."""
    from thunder_tpu.observability.memory import memory_timeline as _mt

    return _mt(trace)


def train_memory_report(train_step) -> dict:
    """Memory accounting for a built distributed ``TrainStep``: the
    donation-aware fw/bw peaks, the remat policy + residual-bytes delta it
    bought, the accumulation buffer the scan carries, and the overlap
    bucket layout (``TrainStep.profile_stats()``, surfaced here so the
    examine toolkit covers training-step memory the way
    ``memory_estimate`` covers a single trace).  Requires the step to have
    run (built) at least once."""
    return dict(train_step.profile_stats())


# hardware peaks (bf16 FLOP/s, HBM bytes/s) keyed by jax backend — the ONE
# source of truth for roofline/MFU math (bench.py imports this).  TPU row is
# the v5e chip; the cpu row is nominal so smoke MFU stays well-defined.
HW_PEAKS: dict[str, tuple[float, float]] = {
    "tpu": (197e12, 819e9),
    "cpu": (1e12, 100e9),
}


def cost_analysis(fn: Callable, *args, flops_per_sec: float | None = None,
                  bytes_per_sec: float | None = None) -> dict:
    """XLA's OWN cost model for ``fn`` at ``args``: FLOPs, HBM bytes
    accessed, arithmetic intensity, and a roofline step-time estimate at the
    hardware peaks (defaulted per backend; v5e for TPU).

    ``fn`` must be jax-traceable at ``args`` — a plain jax/numpy callable,
    or a thunder execution trace's ``python_callable()``
    (``tt.last_traces(jfn)[-1].python_callable()``).  This is the
    introspection behind the depth-fit extrapolations: the cost model sees
    the exact compiled program, not an analytic FLOPs formula.

    Roofline keys (``roofline_seconds``/``compute_seconds``/
    ``memory_seconds``/``bound``) are present whenever both peaks resolve —
    explicitly passed, or defaulted from ``HW_PEAKS`` for the backend.
    """
    import jax

    compiled = jax.jit(fn).lower(*args).compile()
    ca = compiled.cost_analysis()
    if isinstance(ca, list):  # older jax returns one entry per device program
        ca = ca[0] if ca else {}
    flops = float(ca.get("flops", 0.0))
    bytes_accessed = float(ca.get("bytes accessed", 0.0))
    out = {
        "flops": flops,
        "bytes_accessed": bytes_accessed,
        "arithmetic_intensity": (flops / bytes_accessed) if bytes_accessed else None,
    }
    peak = HW_PEAKS.get(jax.default_backend())
    if flops_per_sec is None and peak is not None:
        flops_per_sec = peak[0]
    if bytes_per_sec is None and peak is not None:
        bytes_per_sec = peak[1]
    if flops_per_sec is not None and bytes_per_sec is not None:
        t_compute = flops / flops_per_sec
        t_memory = bytes_accessed / bytes_per_sec
        out.update(
            roofline_seconds=max(t_compute, t_memory),
            compute_seconds=t_compute,
            memory_seconds=t_memory,
            bound="compute" if t_compute >= t_memory else "memory",
        )
    return out
