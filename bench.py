"""Headline benchmark: Llama-2-architecture pretraining throughput, single chip.

The reference's headline number is Llama-2-7B single-GPU training throughput,
thunder vs PyTorch eager (+40%, reference README.md:54).  The TPU analog here:
the thunder_tpu compiled train step (trace -> fw/bw split -> XLA executor, one
jitted program) vs the same model hand-written in plain JAX under stock
``jax.jit`` (op-by-op eager dispatch is not a meaningful TPU baseline — and is
impractically slow over a remote-compile tunnel).

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "tokens/s", "vs_baseline": N}
vs_baseline = compiled tokens/s ÷ stock-jax.jit tokens/s (≥1.0 = no framework
overhead; >1.0 = framework kernels/remat beat naive JAX).

Model is the Llama-2 architecture scaled to fit one v5e chip for training
(params + AdamW fp32 state + activations in ~16 GB HBM).
"""
from __future__ import annotations

import json
import os
import sys
import time
import traceback
from functools import partial

import jax
import jax.numpy as jnp
import optax

import thunder_tpu  # noqa: F401  (registers op surface)
from thunder_tpu import distributed as dist
from thunder_tpu.models import llama


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def plain_jax_loss_fn(cfg: llama.Config):
    """Pure-jnp mirror of models/llama.gpt_loss: the baseline model, written
    by hand with no thunder_tpu tracing (compiled with stock jax.jit in
    baseline_run)."""

    def rms_norm(x, w):
        xf = x.astype(jnp.float32)
        ms = jnp.mean(xf * xf, axis=-1, keepdims=True)
        return ((xf * jax.lax.rsqrt(ms + cfg.norm_eps)) * w.astype(jnp.float32)).astype(x.dtype)

    def rope(x, cos, sin):
        half = x.shape[-1] // 2
        rotated = jnp.concatenate([-x[..., half:], x[..., :half]], axis=-1)
        return (x * cos + rotated * sin).astype(x.dtype)

    def attn(ap, x, cos, sin):
        B, T, C = x.shape
        hs, nh, ng = cfg.head_size, cfg.n_head, cfg.n_query_groups
        q = (x @ ap["wq"].T).reshape(B, T, nh, hs).transpose(0, 2, 1, 3)
        k = (x @ ap["wk"].T).reshape(B, T, ng, hs).transpose(0, 2, 1, 3)
        v = (x @ ap["wv"].T).reshape(B, T, ng, hs).transpose(0, 2, 1, 3)
        q, k = rope(q, cos, sin), rope(k, cos, sin)
        if ng != nh:
            rep = nh // ng
            k = jnp.repeat(k, rep, axis=1)
            v = jnp.repeat(v, rep, axis=1)
        scores = (q @ k.transpose(0, 1, 3, 2)).astype(jnp.float32) / (hs**0.5)
        mask = jnp.tril(jnp.ones((T, T), dtype=bool))
        scores = jnp.where(mask, scores, -jnp.inf)
        y = (jax.nn.softmax(scores, axis=-1).astype(q.dtype) @ v)
        y = y.transpose(0, 2, 1, 3).reshape(B, T, nh * hs)
        return y @ ap["wo"].T

    def mlp(mp, x):
        return (jax.nn.silu(x @ mp["fc_1"].T) * (x @ mp["fc_2"].T)) @ mp["proj"].T

    def loss_fn(params, idx, targets, cos, sin):
        x = params["wte"][idx]
        for bp in params["blocks"]:
            h = x + attn(bp["attn"], rms_norm(x, bp["norm_1"]), cos, sin)
            x = h + mlp(bp["mlp"], rms_norm(h, bp["norm_2"]))
        x = rms_norm(x, params["ln_f"])
        logits = (x @ params["lm_head"].T).astype(jnp.float32)
        logp = jax.nn.log_softmax(logits.reshape(-1, logits.shape[-1]), axis=-1)
        return -jnp.take_along_axis(logp, targets.reshape(-1, 1), axis=-1).mean()

    return loss_fn


def time_steps(step, n, *state):
    t0 = time.perf_counter()
    out = None
    for _ in range(n):
        out = step(*state)
        state = out[:2] + state[2:] if isinstance(out, tuple) and len(out) >= 2 else state
    jax.block_until_ready(out)
    return time.perf_counter() - t0


def make_batch(cfg, B, T):
    idx = jax.random.randint(jax.random.PRNGKey(1), (B, T), 0, cfg.vocab_size)
    tgt = jax.random.randint(jax.random.PRNGKey(2), (B, T), 0, cfg.vocab_size)
    cos, sin = llama.build_rope_cache(cfg, T, dtype=jnp.float32)
    return idx, tgt, cos, sin


def compiled_run(cfg, B, T, optimizer, steps):
    """thunder_tpu trace -> fw/bw split -> one XLA program; returns tokens/s."""
    mesh = dist.make_mesh({"dp": 1}, devices=jax.devices()[:1])
    params = llama.init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.bfloat16)
    idx, tgt, cos, sin = make_batch(cfg, B, T)

    def loss_fn(params, idx, targets, cos, sin):
        return llama.gpt_loss(params, idx, targets, cos, sin, cfg)

    step = dist.make_train_step(loss_fn, optimizer, mesh, batch_specs=None, donate=True)
    opt_state = step.init_optimizer_state(params)
    t0 = time.perf_counter()
    params2, opt2, loss = step(params, opt_state, idx, tgt, cos, sin)
    jax.block_until_ready(loss)
    log(f"compiled[B={B}] compile+first step: {time.perf_counter()-t0:.1f}s loss={float(loss):.4f}")
    dt = time_steps(lambda p, o: step(p, o, idx, tgt, cos, sin), steps, params2, opt2)
    tps = B * T * steps / dt
    log(f"compiled[B={B}]: {tps:,.0f} tokens/s ({dt/steps*1e3:.1f} ms/step)")
    return tps


def baseline_run(cfg, B, T, optimizer, steps):
    """Baseline: the same model hand-written in plain JAX, compiled with stock
    ``jax.jit``.  (The reference baselines against torch *eager*; on a TPU
    everything is compiled, so the honest comparison for a compiler framework
    is stock jax.jit — vs_baseline ≥ 1.0 means the framework's pipeline adds
    no overhead over hand-written JAX and its kernels/remat win beyond it.)"""
    idx, tgt, cos, sin = make_batch(cfg, B, T)
    vg = jax.value_and_grad(plain_jax_loss_fn(cfg))
    p = llama.init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.bfloat16)
    o = optimizer.init(p)

    @partial(jax.jit, donate_argnums=(0, 1))
    def jstep(p, o):
        l, g = vg(p, idx, tgt, cos, sin)
        upd, o = optimizer.update(g, o, p)
        return optax.apply_updates(p, upd), o, l

    t0 = time.perf_counter()
    p, o, l = jstep(p, o)  # compile + warmup
    jax.block_until_ready(l)
    log(f"jax.jit[B={B}] compile+first step: {time.perf_counter()-t0:.1f}s loss={float(l):.4f}")
    t0 = time.perf_counter()
    for _ in range(steps):
        p, o, l = jstep(p, o)
    jax.block_until_ready(l)
    dt = time.perf_counter() - t0
    tps = B * T * steps / dt
    log(f"jax.jit[B={B}]: {tps:,.0f} tokens/s ({dt/steps*1e3:.1f} ms/step)")
    return tps


def _resolve_backend() -> str:
    """Return the JAX backend name, surviving flaky TPU init.

    Round 1's bench died at backend init ("UNAVAILABLE: TPU backend
    setup/compile error", BENCH_r01.json rc=1).  JAX caches a failed backend
    for the process lifetime, so in-process retry is useless — instead
    re-exec this script: twice to give the TPU another chance, then once
    more with the platform forced to CPU so a (smoke-mode) number is still
    produced.  Runs inside main()'s fail-soft wrapper, so even a forced-CPU
    failure still emits the diagnostic JSON line.
    """
    if os.environ.get("THUNDER_TPU_BENCH_FORCE_CPU"):
        from thunder_tpu._platform import force_cpu

        force_cpu()  # raises on failure → caught by the __main__ wrapper
        return jax.default_backend()
    # Probe backend init in a SUBPROCESS with a hard timeout first: a flaky
    # tunnel can make jax.default_backend() hang for tens of minutes in-process
    # (observed ~25 min), which would eat the whole bench budget before the
    # CPU fallback ever ran.
    import subprocess

    for attempt in range(2):
        try:
            probe = subprocess.run(
                [sys.executable, "-c", "import jax; print(jax.default_backend())"],
                timeout=240,
                capture_output=True,
                text=True,
            )
        except subprocess.TimeoutExpired:
            log(f"backend probe timed out (attempt {attempt})")
            continue
        if probe.returncode == 0 and probe.stdout.strip():
            backend = probe.stdout.strip()
            log(f"backend probe: {backend}")
            try:
                return jax.default_backend()  # init is known-good; do it for real
            except Exception as e:  # tunnel flaked between probe and init
                log(f"backend init failed after successful probe: {e}")
                break
        log(f"backend probe failed (attempt {attempt}): {probe.stderr.strip()[-200:]}")
        time.sleep(15)
    # TPU unusable: force CPU so a (smoke-mode) number is still produced
    env = dict(os.environ)
    env["THUNDER_TPU_BENCH_FORCE_CPU"] = "1"
    os.execve(sys.executable, [sys.executable, os.path.abspath(__file__), *sys.argv[1:]], env)


#
# MFU: model FLOPs per token (PaLM-appendix accounting: 6N for the dense
# params + 12·L·T·d_attn for attention scores/values) against peak chip FLOPs
#

_PEAK_BF16_FLOPS = {
    "tpu": 197e12,  # v5e chip, bf16
    "cpu": 1e12,    # nominal; CPU smoke MFU is meaningless but well-defined
}


def model_flops_per_token(cfg: llama.Config, T: int) -> float:
    n_params = (
        cfg.padded_vocab_size * cfg.n_embd * 2  # wte + lm_head
        + cfg.n_layer
        * (
            cfg.n_embd * (cfg.n_head + 2 * cfg.n_query_groups) * cfg.head_size  # qkv
            + cfg.n_head * cfg.head_size * cfg.n_embd  # wo
            + 3 * cfg.n_embd * cfg.intermediate_size  # swiglu
        )
    )
    attn = 12 * cfg.n_layer * T * cfg.n_head * cfg.head_size / 2  # causal halves the scores
    return 6 * n_params + attn


def mfu(tokens_per_sec: float, cfg: llama.Config, T: int, backend: str) -> float:
    peak = _PEAK_BF16_FLOPS.get(backend, 1e12)
    return tokens_per_sec * model_flops_per_token(cfg, T) / peak


#
# Microbenchmarks (reference benchmarks/targets.py:402-700 — GELU→block ops).
# Run with `python bench.py micro`; results go to stderr (the driver's stdout
# contract stays one JSON line from the headline run).
#


def _time_fn(fn, *args, iters=20):
    out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / iters


def micro_benchmarks(on_tpu: bool):
    import numpy as np

    import thunder_tpu as tt
    import thunder_tpu.torch as ltorch

    B, H, T, hs = (4, 16, 2048, 128) if on_tpu else (2, 2, 256, 64)
    V, C = (32000, 2048) if on_tpu else (1024, 256)
    key = jax.random.PRNGKey(0)
    dt = jnp.bfloat16 if on_tpu else jnp.float32

    results = {}

    # SDPA: kernels on vs off (flash Pallas vs jnp decomposition)
    q = jax.random.normal(key, (B, H, T, hs), dtype=dt)
    k = jax.random.normal(jax.random.fold_in(key, 1), (B, H, T, hs), dtype=dt)
    v = jax.random.normal(jax.random.fold_in(key, 2), (B, H, T, hs), dtype=dt)

    def sdpa(q, k, v):
        return ltorch.scaled_dot_product_attention(q, k, v, is_causal=True)

    results["sdpa_ms"] = _time_fn(tt.jit(sdpa), q, k, v) * 1e3
    os.environ["THUNDER_TPU_DISABLE_PALLAS"] = "1"
    try:
        results["sdpa_nokernel_ms"] = _time_fn(tt.jit(sdpa), q, k, v) * 1e3
    finally:
        del os.environ["THUNDER_TPU_DISABLE_PALLAS"]

    # fused cross entropy
    logits = jax.random.normal(key, (B * T, V), dtype=jnp.float32)
    tgt = jax.random.randint(jax.random.fold_in(key, 3), (B * T,), 0, V)
    results["cross_entropy_ms"] = _time_fn(tt.jit(lambda l, t: ltorch.cross_entropy(l, t)), logits, tgt) * 1e3

    # rmsnorm
    x = jax.random.normal(key, (B, T, C), dtype=dt)
    w = jnp.ones((C,), dtype=dt)
    results["rms_norm_ms"] = _time_fn(tt.jit(lambda a, ww: ltorch.rms_norm(a, (C,), ww)), x, w) * 1e3

    # one transformer block fwd
    cfg = llama.Config.from_name("tiny-llama-debug") if not on_tpu else llama.Config.from_name(
        "Llama-2-7b-hf", n_layer=1, n_embd=2048, n_head=16, intermediate_size=5504
    )
    params = llama.init_params(cfg, key, dtype=dt)
    Tb = min(T, cfg.block_size)
    idx, _, cos, sin = make_batch(cfg, B, Tb)
    results["block_fwd_ms"] = _time_fn(
        tt.jit(lambda p, i, c, s: llama.gpt_forward(p, i, c, s, cfg)), params, idx, cos, sin
    ) * 1e3

    for name, ms in results.items():
        log(f"micro {name}: {ms:.3f} ms")
    if "sdpa_nokernel_ms" in results and results["sdpa_ms"] > 0:
        log(f"micro sdpa kernel speedup: {results['sdpa_nokernel_ms']/results['sdpa_ms']:.2f}x")
    return results


def decode_benchmark(on_tpu: bool):
    """KV-cache autoregressive decode throughput (milestone E inference),
    fp vs int8-quantized weights."""
    from thunder_tpu.models import generate as gen

    if on_tpu:
        cfg = llama.Config.from_name(
            "Llama-2-7b-hf", n_layer=8, n_embd=2048, n_head=16, intermediate_size=5504
        )
        B, T_prompt, N = 8, 128, 256
    else:
        cfg = llama.Config.from_name("tiny-moe-debug")
        B, T_prompt, N = 4, 16, 32
    params = llama.init_params(cfg, jax.random.PRNGKey(0),
                               dtype=jnp.bfloat16 if on_tpu else jnp.float32)
    prompt = jax.random.randint(jax.random.PRNGKey(1), (B, T_prompt), 0, cfg.vocab_size)

    results = {}
    for name, q in (("fp", False), ("int8", True)):
        t0 = time.perf_counter()
        out = gen.generate(params, prompt, cfg, N, quantized=q)
        jax.block_until_ready(out)
        compile_and_first = time.perf_counter() - t0
        t0 = time.perf_counter()
        out = gen.generate(params, prompt, cfg, N, quantized=q)
        jax.block_until_ready(out)
        dt = time.perf_counter() - t0
        tps = B * N / dt
        results[name] = tps
        log(f"decode[{name}] B={B} N={N}: {tps:,.0f} tokens/s "
            f"({dt/N*1e3:.2f} ms/token-batch; first call {compile_and_first:.1f}s)")
    return results


def main():
    on_tpu = _resolve_backend() == "tpu"
    if len(sys.argv) > 1 and sys.argv[1] == "micro":
        micro_benchmarks(on_tpu)
        print(json.dumps({"metric": "micro", "value": 1.0, "unit": "ok", "vs_baseline": 1.0}))
        return
    if len(sys.argv) > 1 and sys.argv[1] == "decode":
        r = decode_benchmark(on_tpu)
        print(json.dumps({
            "metric": "kvcache_decode_tokens_per_sec" if on_tpu else "kvcache_decode_cpu_smoke",
            "value": round(r["fp"], 1),
            "unit": "tokens/s",
            "vs_baseline": round(r["int8"] / r["fp"], 3),
        }))
        return
    if on_tpu:
        # Llama-2 architecture, ~540M params: training state fits one v5e chip
        cfg = llama.Config.from_name(
            "Llama-2-7b-hf", n_layer=8, n_embd=2048, n_head=16, intermediate_size=5504
        )
        B, T = 4, 2048
        steps, baseline_steps = 20, 20
    else:  # CPU smoke mode (dev only; driver runs on TPU)
        cfg = llama.Config.from_name("tiny-llama-debug")
        B, T = 4, 64
        steps, baseline_steps = 5, 5
    log(f"bench: backend={jax.default_backend()} cfg={cfg.name} n_layer={cfg.n_layer} "
        f"n_embd={cfg.n_embd} B={B} T={T}")
    optimizer = optax.adamw(1e-4)

    compiled_tps = compiled_run(cfg, B, T, optimizer, steps)
    jax.clear_caches()  # free the compiled program + donated buffers before the next phase
    baseline_tps = baseline_run(cfg, B, T, optimizer, baseline_steps)

    backend = jax.default_backend()
    print(json.dumps({
        "metric": "llama2_arch_540m_pretrain_tokens_per_sec_single_chip" if on_tpu
                  else "llama_tiny_pretrain_tokens_per_sec_cpu_smoke",
        "value": round(compiled_tps, 1),
        "unit": "tokens/s",
        "vs_baseline": round(compiled_tps / baseline_tps, 3),
        "mfu_pct": round(100 * mfu(compiled_tps, cfg, T, backend), 2),
        "baseline_mfu_pct": round(100 * mfu(baseline_tps, cfg, T, backend), 2),
    }))


if __name__ == "__main__":
    try:
        main()
    except Exception:
        # Fail-soft: always emit one valid JSON line so the driver records a
        # diagnostic artifact instead of an empty one (round-1 BENCH was rc=1
        # with no output at all).
        traceback.print_exc(file=sys.stderr)
        print(json.dumps({
            "metric": "bench_error",
            "value": 0.0,
            "unit": "tokens/s",
            "vs_baseline": 0.0,
        }))
        sys.exit(1)
