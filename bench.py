"""Headline benchmark: Llama-2-architecture pretraining throughput, single chip.

The reference's headline number is Llama-2-7B single-GPU training throughput,
thunder vs PyTorch eager (+40%, reference README.md:54).  The TPU analog here:
the thunder_tpu compiled train step (trace -> fw/bw split -> XLA executor, one
jitted program) vs the same model hand-written in plain JAX under stock
``jax.jit`` (op-by-op eager dispatch is not a meaningful TPU baseline — and is
impractically slow over a remote-compile tunnel).

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "tokens/s", "vs_baseline": N}
vs_baseline = compiled tokens/s ÷ stock-jax.jit tokens/s (≥1.0 = no framework
overhead; >1.0 = framework kernels/remat beat naive JAX).

Model is the Llama-2 architecture scaled to fit one v5e chip for training
(params + AdamW fp32 state + activations in ~16 GB HBM).
"""
from __future__ import annotations

import json
import os
import sys
import time
import traceback
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
import optax

import thunder_tpu  # noqa: F401  (registers op surface)
from thunder_tpu import distributed as dist
from thunder_tpu.models import llama


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def plain_jax_loss_fn(cfg: llama.Config):
    """Pure-jnp mirror of models/llama.gpt_loss: the baseline model, written
    by hand with no thunder_tpu tracing (compiled with stock jax.jit in
    baseline_run)."""

    def rms_norm(x, w):
        xf = x.astype(jnp.float32)
        ms = jnp.mean(xf * xf, axis=-1, keepdims=True)
        return ((xf * jax.lax.rsqrt(ms + cfg.norm_eps)) * w.astype(jnp.float32)).astype(x.dtype)

    def rope(x, cos, sin):
        half = x.shape[-1] // 2
        rotated = jnp.concatenate([-x[..., half:], x[..., :half]], axis=-1)
        return (x * cos + rotated * sin).astype(x.dtype)

    def attn(ap, x, cos, sin):
        B, T, C = x.shape
        hs, nh, ng = cfg.head_size, cfg.n_head, cfg.n_query_groups
        q = (x @ ap["wq"].T).reshape(B, T, nh, hs).transpose(0, 2, 1, 3)
        k = (x @ ap["wk"].T).reshape(B, T, ng, hs).transpose(0, 2, 1, 3)
        v = (x @ ap["wv"].T).reshape(B, T, ng, hs).transpose(0, 2, 1, 3)
        q, k = rope(q, cos, sin), rope(k, cos, sin)
        if ng != nh:
            rep = nh // ng
            k = jnp.repeat(k, rep, axis=1)
            v = jnp.repeat(v, rep, axis=1)
        scores = (q @ k.transpose(0, 1, 3, 2)).astype(jnp.float32) / (hs**0.5)
        mask = jnp.tril(jnp.ones((T, T), dtype=bool))
        scores = jnp.where(mask, scores, -jnp.inf)
        y = (jax.nn.softmax(scores, axis=-1).astype(q.dtype) @ v)
        y = y.transpose(0, 2, 1, 3).reshape(B, T, nh * hs)
        return y @ ap["wo"].T

    def mlp(mp, x):
        return (jax.nn.silu(x @ mp["fc_1"].T) * (x @ mp["fc_2"].T)) @ mp["proj"].T

    def loss_fn(params, idx, targets, cos, sin):
        x = params["wte"][idx]
        for bp in params["blocks"]:
            h = x + attn(bp["attn"], rms_norm(x, bp["norm_1"]), cos, sin)
            x = h + mlp(bp["mlp"], rms_norm(h, bp["norm_2"]))
        x = rms_norm(x, params["ln_f"])
        logits = (x @ params["lm_head"].T).astype(jnp.float32)
        logp = jax.nn.log_softmax(logits.reshape(-1, logits.shape[-1]), axis=-1)
        return -jnp.take_along_axis(logp, targets.reshape(-1, 1), axis=-1).mean()

    return loss_fn


def time_steps(step, n, *state):
    """Time n chained steps, fenced by a real host fetch.

    ``jax.block_until_ready`` does not wait on the tunneled axon backend, so
    the loop ends with ``_sync`` (fetch one element) and the measured fetch
    round-trip floor is subtracted.  The steps chain through ``state`` so
    in-order execution makes the final fetch fence the whole loop.
    """
    floor = _fetch_floor()
    t0 = time.perf_counter()
    out = None
    for _ in range(n):
        out = step(*state)
        state = out[:2] + state[2:] if isinstance(out, tuple) and len(out) >= 2 else state
    _sync(out)
    return max(time.perf_counter() - t0 - floor, 1e-9), state


def make_batch(cfg, B, T):
    idx = jax.random.randint(jax.random.PRNGKey(1), (B, T), 0, cfg.vocab_size)
    tgt = jax.random.randint(jax.random.PRNGKey(2), (B, T), 0, cfg.vocab_size)
    cos, sin = llama.build_rope_cache(cfg, T, dtype=jnp.float32)
    return idx, tgt, cos, sin


def compiled_run(cfg, B, T, optimizer, steps):
    """thunder_tpu trace -> fw/bw split -> one XLA program; returns tokens/s."""
    mesh = dist.make_mesh({"dp": 1}, devices=jax.devices()[:1])
    params = llama.init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.bfloat16)
    idx, tgt, cos, sin = make_batch(cfg, B, T)

    def loss_fn(params, idx, targets, cos, sin):
        return llama.gpt_loss(params, idx, targets, cos, sin, cfg)

    step = dist.make_train_step(loss_fn, optimizer, mesh, batch_specs=None, donate=True)
    opt_state = step.init_optimizer_state(params)
    t0 = time.perf_counter()
    params2, opt2, loss = step(params, opt_state, idx, tgt, cos, sin)
    loss_v = float(loss)  # real fetch: block_until_ready does not wait on axon
    log(f"compiled[B={B}] compile+first step: {time.perf_counter()-t0:.1f}s loss={loss_v:.4f}")
    # best of two timing loops: the tunnel drifts by whole percents between
    # loops, and the first loop after compilation is occasionally cold.  State
    # threads through because each loop donates its input buffers.
    dt1, st = time_steps(lambda p, o: step(p, o, idx, tgt, cos, sin), steps, params2, opt2)
    dt2, _ = time_steps(lambda p, o: step(p, o, idx, tgt, cos, sin), steps, *st)
    dt = min(dt1, dt2)
    tps = B * T * steps / dt
    log(f"compiled[B={B}]: {tps:,.0f} tokens/s ({dt/steps*1e3:.1f} ms/step)")
    return tps


def baseline_run(cfg, B, T, optimizer, steps):
    """Baseline: the same model hand-written in plain JAX, compiled with stock
    ``jax.jit``.  (The reference baselines against torch *eager*; on a TPU
    everything is compiled, so the honest comparison for a compiler framework
    is stock jax.jit — vs_baseline ≥ 1.0 means the framework's pipeline adds
    no overhead over hand-written JAX and its kernels/remat win beyond it.)"""
    idx, tgt, cos, sin = make_batch(cfg, B, T)
    vg = jax.value_and_grad(plain_jax_loss_fn(cfg))
    p = llama.init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.bfloat16)
    o = optimizer.init(p)

    @partial(jax.jit, donate_argnums=(0, 1))
    def jstep(p, o):
        l, g = vg(p, idx, tgt, cos, sin)
        upd, o = optimizer.update(g, o, p)
        return optax.apply_updates(p, upd), o, l

    t0 = time.perf_counter()
    p, o, l = jstep(p, o)  # compile + warmup
    loss_v = float(l)  # real fetch: block_until_ready does not wait on axon
    log(f"jax.jit[B={B}] compile+first step: {time.perf_counter()-t0:.1f}s loss={loss_v:.4f}")
    dt1, st = time_steps(lambda pp, oo: jstep(pp, oo), steps, p, o)
    dt2, _ = time_steps(lambda pp, oo: jstep(pp, oo), steps, *st)
    dt = min(dt1, dt2)
    tps = B * T * steps / dt
    log(f"jax.jit[B={B}]: {tps:,.0f} tokens/s ({dt/steps*1e3:.1f} ms/step)")
    return tps


# every backend-acquisition attempt, persisted into the output JSON so the
# artifact records how hard the TPU was tried (VERDICT r2: the r02 bench gave
# the flaky tunnel 8 minutes; this gives it ~40 by default)
tpu_attempts: list[dict] = []


def _resolve_backend() -> str:
    """Return the JAX backend name, surviving flaky TPU init.

    Round 1's bench died at backend init; round 2's two 240 s probes gave up
    too early and fell back to a CPU smoke; round 3's 2400 s default outlived
    the DRIVER's ~20 min window entirely (BENCH_r03.json: rc=124, no output).
    Now: probe in a SUBPROCESS with a hard timeout (in-process init can hang
    ~25 min and JAX caches a failed backend for the process lifetime),
    retrying with backoff until ``THUNDER_TPU_BENCH_MAX_WAIT_S`` (default
    600 s — the probe must leave the driver window room for the CPU-fallback
    run; set the env higher for patient builder-side runs) is spent; every
    attempt is recorded in ``tpu_attempts`` (merged into the JSON artifact).
    Only then force CPU (smoke mode) so a diagnostic number is still
    produced, with the latest committed TPU result embedded as ``last_tpu``.
    """
    if os.environ.get("THUNDER_TPU_BENCH_FORCE_CPU"):
        from thunder_tpu._platform import force_cpu

        force_cpu()  # raises on failure → caught by the __main__ wrapper
        return jax.default_backend()
    import subprocess

    budget = float(os.environ.get("THUNDER_TPU_BENCH_MAX_WAIT_S", "600"))
    t_start = time.monotonic()
    attempt = 0
    sleep_s = 30.0
    while time.monotonic() - t_start < budget:
        attempt += 1
        t0 = time.monotonic()
        rec = {"attempt": attempt, "t_offset_s": round(t0 - t_start, 1)}
        try:
            probe = subprocess.run(
                [sys.executable, "-c", "import jax; print(jax.default_backend())"],
                timeout=min(300, max(60, budget - (time.monotonic() - t_start))),
                capture_output=True,
                text=True,
            )
            rec["rc"] = probe.returncode
            rec["out"] = probe.stdout.strip()[-40:]
            if probe.returncode != 0:
                rec["err"] = probe.stderr.strip()[-160:]
        except subprocess.TimeoutExpired:
            rec["rc"] = "timeout"
        rec["dur_s"] = round(time.monotonic() - t0, 1)
        tpu_attempts.append(rec)
        log(f"backend probe attempt {attempt}: {rec}")
        if rec.get("rc") == 0 and rec.get("out"):
            try:
                backend = jax.default_backend()  # init is known-good; do it for real
                rec["resolved"] = backend
                return backend
            except Exception as e:  # tunnel flaked between probe and init
                rec["init_error"] = str(e)[-160:]
                log(f"backend init failed after successful probe: {e}")
        time.sleep(min(sleep_s, max(0.0, budget - (time.monotonic() - t_start))))
        sleep_s = min(sleep_s * 1.7, 300.0)
    # TPU unusable within budget: force CPU so a (smoke) number still emerges
    env = dict(os.environ)
    env["THUNDER_TPU_BENCH_FORCE_CPU"] = "1"
    env["THUNDER_TPU_BENCH_ATTEMPTS"] = json.dumps(tpu_attempts)
    os.execve(sys.executable, [sys.executable, os.path.abspath(__file__), *sys.argv[1:]], env)


#
# MFU: model FLOPs per token (PaLM-appendix accounting: 6N for the dense
# params + 12·L·T·d_attn for attention scores/values) against peak chip FLOPs
#

# single source of truth for hardware peaks: thunder_tpu.examine.HW_PEAKS
# (v5e bf16 MXU + HBM stream; cpu nominal so smoke MFU stays well-defined)
from thunder_tpu.examine import HW_PEAKS as _HW_PEAKS

_PEAK_BF16_FLOPS = {k: v[0] for k, v in _HW_PEAKS.items()}

# the measured-headline geometry, shared by the TPU headline branch and the
# analytic `cost` mode so the roofline always bounds the number we report:
# (config name, Config overrides, B, T)
_HEADLINE_GEOMETRY = ("Llama-2-7b-hf", {"n_layer": 4}, 2, 2048)


def model_flops_per_token(cfg: llama.Config, T: int) -> float:
    n_params = (
        cfg.padded_vocab_size * cfg.n_embd * 2  # wte + lm_head
        + cfg.n_layer
        * (
            cfg.n_embd * (cfg.n_head + 2 * cfg.n_query_groups) * cfg.head_size  # qkv
            + cfg.n_head * cfg.head_size * cfg.n_embd  # wo
            + 3 * cfg.n_embd * cfg.intermediate_size  # swiglu
        )
    )
    attn = 12 * cfg.n_layer * T * cfg.n_head * cfg.head_size / 2  # causal halves the scores
    return 6 * n_params + attn


def mfu(tokens_per_sec: float, cfg: llama.Config, T: int, backend: str) -> float:
    peak = _PEAK_BF16_FLOPS.get(backend, 1e12)
    return tokens_per_sec * model_flops_per_token(cfg, T) / peak


#
# Microbenchmarks (reference benchmarks/targets.py:402-700 — GELU→block ops).
# Run with `python bench.py micro`; results go to stderr (the driver's stdout
# contract stays one JSON line from the headline run).
#


# tunnel-proof timing primitives live in the benchmark library (shared with
# the per-op/per-block/per-model benchmark classes); aliased here for the
# harness tests and historical call sites
from thunder_tpu.benchmarks import timing as _timing

_sync = _timing.sync
_fetch_floor = _timing.fetch_floor


def _time_fn(fn, *args, iters=20):
    return _timing.time_fn(fn, *args, iters=iters)


def _best_ms(fn, *args, reps=3):
    # goes through the module-level _time_fn (not _timing.best_ms) so tests
    # can monkeypatch the per-rep measurement
    vals = [v for v in (_time_fn(fn, *args) for _ in range(reps)) if v == v]
    return min(vals) * 1e3 if vals else float("nan")


def micro_benchmarks(on_tpu: bool):
    import numpy as np

    import thunder_tpu as tt
    import thunder_tpu.torch as ltorch

    B, H, T, hs = (4, 16, 2048, 128) if on_tpu else (2, 2, 256, 64)
    V, C = (32000, 2048) if on_tpu else (1024, 256)
    key = jax.random.PRNGKey(0)
    dt = jnp.bfloat16 if on_tpu else jnp.float32

    results = {}

    # SDPA: kernels on vs off (flash Pallas vs jnp decomposition)
    q = jax.random.normal(key, (B, H, T, hs), dtype=dt)
    k = jax.random.normal(jax.random.fold_in(key, 1), (B, H, T, hs), dtype=dt)
    v = jax.random.normal(jax.random.fold_in(key, 2), (B, H, T, hs), dtype=dt)

    def sdpa(q, k, v):
        return ltorch.scaled_dot_product_attention(q, k, v, is_causal=True)

    best = _best_ms  # best-of-3: rides out tunnel cold-start drift

    results["sdpa_ms"] = best(tt.jit(sdpa), q, k, v)
    os.environ["THUNDER_TPU_DISABLE_PALLAS"] = "1"
    try:
        results["sdpa_nokernel_ms"] = best(tt.jit(sdpa), q, k, v)
    finally:
        del os.environ["THUNDER_TPU_DISABLE_PALLAS"]

    # fused cross entropy
    logits = jax.random.normal(key, (B * T, V), dtype=jnp.float32)
    tgt = jax.random.randint(jax.random.fold_in(key, 3), (B * T,), 0, V)
    results["cross_entropy_ms"] = best(tt.jit(lambda l, t: ltorch.cross_entropy(l, t)), logits, tgt)

    # rmsnorm
    x = jax.random.normal(key, (B, T, C), dtype=dt)
    w = jnp.ones((C,), dtype=dt)
    results["rms_norm_ms"] = best(tt.jit(lambda a, ww: ltorch.rms_norm(a, (C,), ww)), x, w)

    # one transformer block fwd
    cfg = llama.Config.from_name("tiny-llama-debug") if not on_tpu else llama.Config.from_name(
        "Llama-2-7b-hf", n_layer=1, n_embd=2048, n_head=16, intermediate_size=5504
    )
    params = llama.init_params(cfg, key, dtype=dt)
    Tb = min(T, cfg.block_size)
    idx, _, cos, sin = make_batch(cfg, B, Tb)
    results["block_fwd_ms"] = best(
        tt.jit(lambda p, i, c, s: llama.gpt_forward(p, i, c, s, cfg)), params, idx, cos, sin
    )

    for name, ms in results.items():
        log(f"micro {name}: {ms:.3f} ms")
    if "sdpa_nokernel_ms" in results and results["sdpa_ms"] > 0:
        log(f"micro sdpa kernel speedup: {results['sdpa_nokernel_ms']/results['sdpa_ms']:.2f}x")
    return results


#
# Per-op sweep: thunder_tpu jit vs stock jax.jit on the reference's
# microbenchmark op set (benchmarks/targets.py:402-700: GELU → CE → norm →
# SDPA → MLP → block), written to a committed JSON artifact.
#


def sweep_benchmarks(on_tpu: bool, out_path: str = "BENCH_MICRO.json"):
    import thunder_tpu as tt
    import thunder_tpu.torch as ltorch

    if on_tpu:
        B, H, T, hs, C, V, I = 8, 32, 2048, 128, 4096, 32000, 11008
        dt = jnp.bfloat16
    else:
        B, H, T, hs, C, V, I = 2, 2, 256, 64, 256, 1024, 688
        dt = jnp.float32
    key = jax.random.PRNGKey(0)
    k2 = lambda i: jax.random.fold_in(key, i)
    N = B * T

    x_rows = jax.random.normal(k2(0), (N, C), dtype=dt)
    logits = jax.random.normal(k2(1), (N, V), dtype=jnp.float32)
    tgt = jax.random.randint(k2(2), (N,), 0, V)
    w_norm = jnp.ones((C,), dtype=dt)
    q = jax.random.normal(k2(3), (B, H, T, hs), dtype=dt)
    kk = jax.random.normal(k2(4), (B, H, T, hs), dtype=dt)
    v = jax.random.normal(k2(5), (B, H, T, hs), dtype=dt)
    w1 = jax.random.normal(k2(6), (I, C), dtype=dt) * 0.02
    w2 = jax.random.normal(k2(7), (I, C), dtype=dt) * 0.02
    w3 = jax.random.normal(k2(8), (C, I), dtype=dt) * 0.02

    def plain_ce(l, t):
        lse = jax.nn.logsumexp(l, axis=-1)
        return (lse - jnp.take_along_axis(l, t[:, None], axis=1)[:, 0]).mean()

    def plain_rms(a, w):
        af = a.astype(jnp.float32)
        ms = jnp.mean(af * af, axis=-1, keepdims=True)
        return ((af * jax.lax.rsqrt(ms + 1e-5)) * w.astype(jnp.float32)).astype(a.dtype)

    def plain_sdpa(q, k, v):
        s = jnp.einsum("bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32) / (hs ** 0.5)
        s = jnp.where(jnp.tril(jnp.ones((T, T), dtype=bool)), s, -jnp.inf)
        return jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(s, -1).astype(v.dtype), v)

    def plain_mlp(x, w1, w2, w3):
        return (jax.nn.silu(x @ w1.T) * (x @ w2.T)) @ w3.T

    cases = {
        # approximate=False on the jax side: torch's gelu default is the exact
        # erf form, jax.nn.gelu's default is the cheaper tanh approximation —
        # comparing those would measure op semantics, not framework overhead.
        "gelu": (tt.jit(lambda a: ltorch.gelu(a)),
                 jax.jit(partial(jax.nn.gelu, approximate=False)), (x_rows,)),
        "cross_entropy": (
            tt.jit(lambda l, t: ltorch.cross_entropy(l, t)), jax.jit(plain_ce), (logits, tgt)),
        "rms_norm": (
            tt.jit(lambda a, w: ltorch.rms_norm(a, (C,), w)), jax.jit(plain_rms), (x_rows, w_norm)),
        "sdpa_causal": (
            tt.jit(lambda q, k, v: ltorch.scaled_dot_product_attention(q, k, v, is_causal=True)),
            jax.jit(plain_sdpa), (q, kk, v)),
        "swiglu_mlp": (
            tt.jit(lambda x, a, b, c: ltorch.linear(ltorch.silu(ltorch.linear(x, a)) * ltorch.linear(x, b), c)),
            jax.jit(plain_mlp), (x_rows, w1, w2, w3)),
        "sdpa_grad": (
            tt.grad(lambda q, k, v: ltorch.scaled_dot_product_attention(q, k, v, is_causal=True).sum(),
                    argnums=(0, 1, 2)),
            jax.jit(jax.grad(lambda q, k, v: plain_sdpa(q, k, v).sum(), argnums=(0, 1, 2))), (q, kk, v)),
        "ce_grad": (
            tt.grad(lambda l, t: ltorch.cross_entropy(l, t), argnums=0),
            jax.jit(jax.grad(plain_ce, argnums=0)), (logits, tgt)),
    }

    # decode-shaped entries (small B, one query against a full KV history —
    # the serving shape where fused kernels earn differently than at
    # training shapes; VERDICT r3 #2 asked for this axis)
    q1 = jax.random.normal(k2(9), (B, H, 1, hs), dtype=dt)
    logits1 = jax.random.normal(k2(10), (B, V), dtype=jnp.float32)
    tgt1 = jax.random.randint(k2(11), (B,), 0, V)

    def plain_sdpa_decode(q, k, v):
        s = jnp.einsum("bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32) / (hs ** 0.5)
        return jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(s, -1).astype(v.dtype), v)

    cases["sdpa_decode"] = (
        tt.jit(lambda q, k, v: ltorch.scaled_dot_product_attention(q, k, v)),
        jax.jit(plain_sdpa_decode), (q1, kk, v))
    cases["ce_decode"] = (
        tt.jit(lambda l, t: ltorch.cross_entropy(l, t)), jax.jit(plain_ce), (logits1, tgt1))
    # the production CE shape: half-precision logits with the f32 cast in
    # the program — the absorb pass feeds the kernel bf16 directly, XLA
    # fuses its own cast, so both sides move half the bytes
    logits_h = jax.random.normal(k2(12), (N, V), dtype=dt)
    cases["cross_entropy_halfp"] = (
        tt.jit(lambda l, t: ltorch.cross_entropy(l.to(ltorch.float32), t)),
        jax.jit(lambda l, t: plain_ce(l.astype(jnp.float32), t)), (logits_h, tgt))

    results = {}
    for name, (tfn, jfn, args) in cases.items():
        try:
            # Pairwise-interleaved reps, per-side min: the tunneled backend
            # drifts by several ms on timescales of one rep, so each rep times
            # both sides back-to-back and min() rides out the drift (measured:
            # swiglu_mlp read 0.75x once, 1.00x on every re-measurement).
            pairs = [(_time_fn(tfn, *args), _time_fn(jfn, *args)) for _ in range(3)]
            tt_vals = [p[0] for p in pairs if p[0] == p[0]]
            jx_vals = [p[1] for p in pairs if p[1] == p[1]]
            if not tt_vals or not jx_vals:
                results[name] = {"error": "measurement unreliable (fetch-floor jitter)"}
                log(f"sweep {name}: UNRELIABLE (jitter swamped signal)")
                continue
            tt_ms = min(tt_vals) * 1e3
            jx_ms = min(jx_vals) * 1e3
            results[name] = {
                "thunder_ms": round(tt_ms, 4),
                "jax_ms": round(jx_ms, 4),
                "speedup": round(jx_ms / tt_ms, 3) if tt_ms > 0 else None,
            }
            log(f"sweep {name}: thunder {tt_ms:.3f} ms vs jax {jx_ms:.3f} ms "
                f"({results[name]['speedup']}x)")
        except Exception as e:
            results[name] = {"error": str(e)[-200:]}
            log(f"sweep {name}: ERROR {e}")
    artifact = {
        "backend": jax.default_backend(),
        "shapes": {"B": B, "H": H, "T": T, "hs": hs, "C": C, "V": V, "I": I, "dtype": str(dt.__name__ if hasattr(dt, '__name__') else dt)},
        "results": results,
    }
    with open(out_path, "w") as f:
        json.dump(artifact, f, indent=1)
    log(f"sweep artifact written to {out_path}")
    return results


def blocks_benchmarks(on_tpu: bool, out_path: str = "BENCH_BLOCKS.json"):
    """Per-op + per-block + per-model benchmark classes (the reference's
    reusable benchmark library tier, benchmarks/__init__.py:50-460), written
    to a committed JSON artifact."""
    from thunder_tpu.benchmarks import all_benchmarks, run_benchmark

    rows = []
    artifact = {"backend": jax.default_backend(), "rows": rows}
    if artifact["backend"] != "tpu":
        artifact["note"] = ("CPU smoke: validates the harness only — CPU op timings "
                            "say nothing about TPU kernels (pallas runs in interpret "
                            "mode); the committed TPU run overwrites this file")

    def flush():
        # written after EVERY row: a tunnel window dying (or the queue's
        # timeout firing) mid-grid must keep the rows already measured
        with open(out_path, "w") as f:
            json.dump(artifact, f, indent=1)

    for b in all_benchmarks(on_tpu):
        try:
            r = run_benchmark(b)
            rows.append(r.row())
            log(f"blocks {b.tier}/{b.name}: thunder {r.thunder_ms:.3f} ms"
                + (f" vs jax {r.baseline_ms:.3f} ms ({r.speedup}x)" if r.baseline_ms else ""))
        except Exception as e:
            rows.append({"name": b.name, "tier": b.tier, "error": str(e)[-200:]})
            log(f"blocks {b.tier}/{b.name}: ERROR {e}")
        flush()
    flush()
    log(f"blocks artifact written to {out_path}")
    return rows


def scaling_table(out_path: str = "BENCH_SCALING.json", smoke: bool = False):
    """Distributed scaling + production-training knob table on the virtual
    CPU mesh.

    Two halves:

    - ``modes``: tokens/s at 1/2/4/8 devices × ddp/fsdp/tp (the reference's
      multiprocess distributed benchmark runner analog,
      benchmarks/__init__.py:584-698 — torchrun spawns there; one process +
      virtual mesh here).  CPU tokens/s say nothing about ICI — the value is
      the TREND and CI-policing the sharded step at every size.
    - the training-knob sweeps (PR 20): remat policy peak-bytes curve at
      equal loss, accumulation peak curve over k, overlap bucket/fraction
      curve, overlap grad parity vs plain SPMD, and a mid-run-kill elastic
      restart whose loss curve must be bit-identical to the undisturbed run.
      These are DETERMINISTIC (byte/bool facts, not timings), so
      tools/bench_targets.check_scaling_targets gates them even on CPU.
    """
    import tempfile

    import numpy as np
    from jax.sharding import PartitionSpec as P

    from thunder_tpu._platform import force_cpu

    force_cpu(8)
    from thunder_tpu import distributed as dist
    from thunder_tpu.serving.faults import FP_TRAIN_STEP, FaultPlan, FaultSpec, RetryPolicy
    from thunder_tpu.train import AsyncCheckpointer, train_loop

    cfg = llama.Config.from_name("tiny-llama-debug")
    B, T, steps = 16, 64, (2 if smoke else 4)
    sizes = (1, 2) if smoke else (1, 2, 4, 8)
    idx = jax.random.randint(jax.random.PRNGKey(1), (B, T), 0, cfg.vocab_size)
    tgt = jax.random.randint(jax.random.PRNGKey(2), (B, T), 0, cfg.vocab_size)
    cos, sin = llama.build_rope_cache(cfg, T)

    def loss_fn(p, i, t, c, s):
        return llama.gpt_loss(p, i, t, c, s, cfg)

    table: dict[str, dict[str, float]] = {}
    for mode in ("ddp", "fsdp", "tp"):
        table[mode] = {}
        for n in sizes:
            axes = {"tp": {"tp": n}, "fsdp": {"fsdp": n}, "ddp": {"dp": n}}[mode]
            bspec = P() if mode == "tp" else P(next(iter(axes)))
            mesh = dist.make_mesh(axes, devices=jax.devices()[:n])
            place = {"ddp": dist.ddp, "fsdp": dist.fsdp, "tp": dist.tp_fsdp}[mode]
            params = place(llama.init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32), mesh)
            step = dist.make_train_step(
                loss_fn, optax.adamw(1e-3), mesh, batch_specs=(bspec, bspec, P(), P()),
            )
            opt = step.init_optimizer_state(params)
            params, opt, loss = step(params, opt, idx, tgt, cos, sin)  # compile
            _sync(loss)
            dt_s, _ = time_steps(lambda p, o: step(p, o, idx, tgt, cos, sin), steps, params, opt)
            table[mode][str(n)] = round(B * T * steps / dt_s, 1)
            log(f"scaling {mode} x{n}: {table[mode][str(n)]:,.0f} tokens/s (cpu smoke)")

    mesh1 = dist.make_mesh({"dp": 1}, devices=jax.devices()[:1])

    def one_step(**kw):
        params = dist.ddp(llama.init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32), mesh1)
        ts = dist.make_train_step(loss_fn, optax.adamw(1e-3), mesh1, **kw)
        opt = ts.init_optimizer_state(params)
        new_p, _, loss = ts(params, opt, idx, tgt, cos, sin)
        return new_p, float(loss), ts.profile_stats()

    # remat policy sweep: peak bytes must fall as the policy gets more
    # aggressive while the loss stays bit-identical (recompute changes
    # memory, never math)
    remat = {}
    for pol in ("none", "attention", "full_block"):
        _, loss, st = one_step(remat=pol)
        remat[pol] = {
            "peak_bytes": int(st["peak_bytes_estimate"]),
            "residual_bytes": int(st["residual_bytes"]),
            "loss": loss,
        }
        log(f"scaling remat={pol}: peak {remat[pol]['peak_bytes']:,} B loss {loss:.6f}")
    remat_reduction = 1.0 - remat["full_block"]["peak_bytes"] / remat["none"]["peak_bytes"]
    remat_loss_delta = max(abs(remat[p]["loss"] - remat["none"]["loss"])
                           for p in ("attention", "full_block"))

    # accumulation sweep: microbatch activations shrink with B/k, the f32
    # accumulator adds param-sized bytes — the peak curve must not grow
    accum = {}
    for k in (1, 2, 4):
        p_k, loss, st = one_step(accum_steps=k)
        accum[str(k)] = {
            "peak_bytes": int(st["peak_bytes_estimate"]),
            "accum_buffer_bytes": int(st["accum_buffer_bytes"]),
            "loss": loss,
        }
        if k == 1:
            p_1 = p_k
        log(f"scaling accum k={k}: peak {accum[str(k)]['peak_bytes']:,} B loss {loss:.6f}")
    accum_loss_delta = max(abs(accum[k]["loss"] - accum["1"]["loss"]) for k in accum)

    # overlap sweep: dp=2 mesh, shrinking bucket caps — more buckets, more
    # of the gradient bytes overlap the backward; grads must match SPMD
    mesh2 = dist.make_mesh({"dp": 2}, devices=jax.devices()[:2])

    def dp2_step(**kw):
        params = dist.ddp(llama.init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32), mesh2)
        ts = dist.make_train_step(loss_fn, optax.adamw(1e-3), mesh2, **kw)
        opt = ts.init_optimizer_state(params)
        new_p, _, loss = ts(params, opt, idx, tgt, cos, sin)
        return new_p, float(loss), ts

    p_plain, loss_plain, _ = dp2_step(overlap=False)
    overlap = {}
    p_ov = None
    for mb in (1.0, 0.25, 0.05):
        p_o, loss_o, ts_o = dp2_step(overlap=True, overlap_bucket_mb=mb)
        rep = ts_o.profile_stats()["overlap"]
        overlap[str(mb)] = {"n_buckets": rep["n_buckets"],
                            "overlap_frac": round(rep["overlap_frac"], 6)}
        p_ov = p_o
        log(f"scaling overlap bucket={mb}MiB: {rep['n_buckets']} buckets "
            f"frac {rep['overlap_frac']:.3f}")
    ov_delta = max(float(jnp.max(jnp.abs(a - b))) for a, b in zip(
        jax.tree_util.tree_leaves(p_plain), jax.tree_util.tree_leaves(p_ov)))

    # elastic restart episode: kill step call #4 with an engine-class fault,
    # restore the newest committed checkpoint, and require the final loss
    # curve bit-identical to the undisturbed run
    loop_steps = 4 if smoke else 6
    Br, Tr = 4, 32
    cos_r, sin_r = llama.build_rope_cache(cfg, Tr)

    def batch_for_step(s):
        k1, k2 = jax.random.split(jax.random.PRNGKey(7000 + s))
        return (jax.random.randint(k1, (Br, Tr), 0, cfg.vocab_size),
                jax.random.randint(k2, (Br, Tr), 0, cfg.vocab_size), cos_r, sin_r)

    def fresh_loop():
        params = dist.ddp(llama.init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32), mesh1)
        ts = dist.make_train_step(loss_fn, optax.adamw(1e-3), mesh1)
        return ts, params, ts.init_optimizer_state(params)

    ts_a, p_a, o_a = fresh_loop()
    base = train_loop(ts_a, p_a, o_a, batch_for_step, steps=loop_steps)
    base_losses = [float(x) for x in base.losses]
    with tempfile.TemporaryDirectory() as ckdir:
        ts_b, p_b, o_b = fresh_loop()
        plan = FaultPlan([FaultSpec(point=FP_TRAIN_STEP, kind="oom", at=loop_steps - 2)])
        with AsyncCheckpointer(ckdir, config={"bench": "scaling"}) as ck:
            faulted = train_loop(
                ts_b, p_b, o_b, batch_for_step, steps=loop_steps,
                checkpointer=ck, checkpoint_every=2, fault_plan=plan,
                retry=RetryPolicy(max_retries=2, sleep=lambda s: None),
            )
    faulted_losses = [float(x) for x in faulted.losses]
    bitident = all(
        np.float32(a).tobytes() == np.float32(b).tobytes()
        for a, b in zip(base_losses, faulted_losses)
    )
    log(f"scaling restart: {faulted.restarts} restart(s), resumed from "
        f"{faulted.resumed_from}, loss curve bit-identical: {bitident}")

    results = {
        "modes": table,
        "remat": remat,
        "remat_peak_reduction_frac": round(remat_reduction, 6),
        "remat_loss_max_delta": float(remat_loss_delta),
        "accum": accum,
        "accum_loss_max_delta": float(accum_loss_delta),
        "overlap": overlap,
        "overlap_grad_parity": bool(ov_delta <= 1e-5),
        "overlap_max_param_delta": float(ov_delta),
        "restart_loss_bitident": bool(bitident),
        "restart_restarts": int(faulted.restarts),
        "restart_resumed_from": faulted.resumed_from,
    }
    artifact = {"backend": jax.default_backend(),
                "note": "virtual-mesh CPU smoke; tokens/s = trend only, the "
                        "knob sweeps (remat/accum/overlap/restart) are "
                        "deterministic facts gated by tools/bench_targets",
                "shapes": {"B": B, "T": T, "cfg": cfg.name},
                "results": results}
    with open(out_path, "w") as f:
        json.dump(artifact, f, indent=1)
    log(f"scaling artifact written to {out_path}")
    return artifact


def dist_throughput_smoke():
    """Virtual-mesh distributed throughput (8 CPU devices): a correctness-
    speed SMOKE (clearly labeled — CPU tokens/s say nothing about ICI), the
    reference's distributed-benchmark-runner analog (benchmarks/__init__.py:
    584-698 spawns torchrun; here one process + virtual mesh)."""
    from thunder_tpu._platform import force_cpu

    force_cpu(8)
    import optax
    from jax.sharding import PartitionSpec as P

    from thunder_tpu import distributed as dist

    cfg = llama.Config.from_name("tiny-llama-debug")
    B, T, steps = 16, 64, 5
    idx = jax.random.randint(jax.random.PRNGKey(1), (B, T), 0, cfg.vocab_size)
    tgt = jax.random.randint(jax.random.PRNGKey(2), (B, T), 0, cfg.vocab_size)
    cos, sin = llama.build_rope_cache(cfg, T)
    results = {}
    for name, axes, place, specs in (
        ("ddp8", {"dp": 8}, dist.ddp, (P("dp"), P("dp"), P(), P())),
        ("fsdp8", {"fsdp": 8}, dist.fsdp, (P("fsdp"), P("fsdp"), P(), P())),
        ("dp2_fsdp2_tp2", {"dp": 2, "fsdp": 2, "tp": 2}, dist.tp_fsdp,
         (P(("dp", "fsdp")), P(("dp", "fsdp")), P(), P())),
    ):
        mesh = dist.make_mesh(axes)
        params = place(llama.init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32), mesh)
        step = dist.make_train_step(
            lambda p, i, t, c, s: llama.gpt_loss(p, i, t, c, s, cfg),
            optax.adamw(1e-3), mesh, batch_specs=specs,
        )
        opt = step.init_optimizer_state(params)
        params, opt, loss = step(params, opt, idx, tgt, cos, sin)  # compile
        _sync(loss)
        dt_s, _ = time_steps(lambda p, o: step(p, o, idx, tgt, cos, sin), steps, params, opt)
        results[name] = round(B * T * steps / dt_s, 1)
        log(f"dist {name}: {results[name]:,.0f} tokens/s (cpu smoke) loss={float(loss):.4f}")
    return results


def decode_benchmark(on_tpu: bool):
    """KV-cache autoregressive decode throughput (milestone E inference),
    fp vs int8-quantized weights."""
    from thunder_tpu.models import generate as gen

    if on_tpu:
        cfg = llama.Config.from_name(
            "Llama-2-7b-hf", n_layer=8, n_embd=2048, n_head=16, intermediate_size=5504
        )
        B, T_prompt, N = 8, 128, 256
    else:
        cfg = llama.Config.from_name("tiny-moe-debug")
        B, T_prompt, N = 4, 16, 32
    params = llama.init_params(cfg, jax.random.PRNGKey(0),
                               dtype=jnp.bfloat16 if on_tpu else jnp.float32)
    prompt = jax.random.randint(jax.random.PRNGKey(1), (B, T_prompt), 0, cfg.vocab_size)

    results = {}
    # speculative: the draft is the target's own first layers (true depth
    # truncation — weight-correlated, so acceptance is meaningful; a random
    # draft would agree with the target ~1/vocab of the time and measure
    # nothing but overhead)
    from thunder_tpu.models.speculative import speculative_generate

    draft_cfg = llama.Config.from_name(cfg.name, **{**{k: getattr(cfg, k) for k in (
        "n_embd", "n_head", "intermediate_size", "vocab_size", "block_size")},
        "n_layer": max(cfg.n_layer // 4, 1)})
    draft_params = {**params, "blocks": params["blocks"][: draft_cfg.n_layer]}
    sp_prompt = prompt[:1]
    t0 = time.perf_counter()
    out = speculative_generate(params, draft_params, sp_prompt, cfg, draft_cfg, N, K=4)
    _sync(out)
    log(f"decode[speculative] compile+first: {time.perf_counter()-t0:.1f}s")
    floor = _fetch_floor()
    t0 = time.perf_counter()
    out = speculative_generate(params, draft_params, sp_prompt, cfg, draft_cfg, N, K=4)
    _sync(out)
    dt = max(time.perf_counter() - t0 - floor, 1e-9)
    results["speculative"] = N / dt
    log(f"decode[speculative B=1 K=4 draft={draft_cfg.n_layer}L] N={N}: "
        f"{results['speculative']:,.0f} tokens/s "
        f"({speculative_generate.last_tokens_per_round:.2f} tokens/round)")

    for name, q in (("fp", False), ("int8", True)):
        t0 = time.perf_counter()
        out = gen.generate(params, prompt, cfg, N, quantized=q)
        _sync(out)
        compile_and_first = time.perf_counter() - t0
        floor = _fetch_floor()
        t0 = time.perf_counter()
        out = gen.generate(params, prompt, cfg, N, quantized=q)
        _sync(out)
        dt = max(time.perf_counter() - t0 - floor, 1e-9)
        tps = B * N / dt
        results[name] = tps
        log(f"decode[{name}] B={B} N={N}: {tps:,.0f} tokens/s "
            f"({dt/N*1e3:.2f} ms/token-batch; first call {compile_and_first:.1f}s)")
    return results


def main():
    if len(sys.argv) > 1 and sys.argv[1] == "dist":
        # virtual-mesh smoke: forces 8 CPU devices itself, no TPU probe
        r = dist_throughput_smoke()
        print(json.dumps({
            "metric": "dist_throughput_cpu_smoke", "value": max(r.values()),
            "unit": "tokens/s", "vs_baseline": 1.0, "modes": r,
        }))
        return
    if len(sys.argv) > 1 and sys.argv[1] == "scaling":
        # virtual-mesh scaling + training-knob table: forces 8 CPU devices
        # itself, no TPU probe
        art = scaling_table()
        r = art["results"]
        best = max(v for row in r["modes"].values() for v in row.values())
        print(json.dumps({
            "metric": "dist_scaling_table_cpu_smoke", "value": best,
            "unit": "tokens/s", "vs_baseline": 1.0, "table": r["modes"],
            "remat_peak_reduction_frac": r["remat_peak_reduction_frac"],
            "overlap_grad_parity": r["overlap_grad_parity"],
            "restart_loss_bitident": r["restart_loss_bitident"],
        }))
        return
    if len(sys.argv) > 1 and sys.argv[1] == "dispatch":
        # dispatch-overhead microbench: host-side cost of re-entering a
        # compiled function at 1/8/64 cached specializations — the framework
        # overhead the keyed cache keeps O(1).  Host work only, no TPU probe.
        from thunder_tpu._platform import force_cpu

        force_cpu()
        from thunder_tpu.benchmarks.dispatch import dispatch_overhead_bench

        r = dispatch_overhead_bench()
        us = {k: v["us_per_call"] for k, v in r.items()}
        for k, v in us.items():
            log(f"dispatch overhead @{k} specializations: {v:.2f} us/call")
        print(json.dumps({
            "metric": "dispatch_overhead_us_per_call_64_specializations",
            "value": us["64"],
            "unit": "us/call",
            # flatness ratio: ~1.0 = O(1) dispatch; the linear scan this
            # replaced scaled this with the specialization count
            "vs_baseline": round(us["64"] / us["1"], 3) if us.get("1") else None,
            "per_specializations": r,
        }))
        return
    if len(sys.argv) > 1 and sys.argv[1] == "profile":
        # profiling-transform overhead: instrumented vs uninstrumented
        # dispatch on the llama block target (observability subsystem).
        # Host work only, no TPU probe; artifact uses the BENCH_MICRO schema.
        from thunder_tpu._platform import force_cpu

        force_cpu()
        from thunder_tpu.benchmarks.profile_overhead import profile_overhead_bench

        out = profile_overhead_bench(on_tpu=False)
        artifact = {"backend": jax.default_backend(), **out}
        with open("BENCH_PROFILE.json", "w") as f:
            json.dump(artifact, f, indent=1)
        for k, v in out["results"].items():
            log(f"profile {k}: {v}")
        print(json.dumps({
            "metric": "profiling_transform_overhead_x",
            "value": out["results"]["overhead_x"],
            "unit": "x",
            # plain-vs-plain is definitionally 1.0: profiling off takes the
            # unmodified code path (byte-identical program)
            "vs_baseline": 1.0,
            "results": out["results"],
        }))
        return
    if len(sys.argv) > 1 and sys.argv[1] == "anomaly":
        # anomaly-detection overhead: plain vs detect_anomalies=True dispatch
        # on the llama block target (numerics observability).  Host work
        # only, no TPU probe; artifact uses the BENCH_MICRO schema.
        from thunder_tpu._platform import force_cpu

        force_cpu()
        from thunder_tpu.benchmarks.anomaly_overhead import anomaly_overhead_bench

        out = anomaly_overhead_bench(on_tpu=False)
        artifact = {"backend": jax.default_backend(), **out}
        with open("BENCH_ANOMALY.json", "w") as f:
            json.dump(artifact, f, indent=1)
        for k, v in out["results"].items():
            log(f"anomaly {k}: {v}")
        print(json.dumps({
            "metric": "anomaly_detection_overhead_x",
            "value": out["results"]["overhead_x"],
            "unit": "x",
            # plain-vs-plain is definitionally 1.0: anomaly mode off takes
            # the unmodified code path (byte-identical program)
            "vs_baseline": 1.0,
            "results": out["results"],
        }))
        return
    if len(sys.argv) > 1 and sys.argv[1] == "donation":
        # buffer-donation microbench: transformer-block train step with the
        # del-aware donation pass on/off — steps/sec, peak-bytes estimate
        # delta (examine.memory_timeline, donation-aware), and the
        # donate=False-vs-plain dispatch ratio CI gates on.  Host work only,
        # no TPU probe; artifact uses the BENCH_MICRO schema.
        from thunder_tpu._platform import force_cpu

        force_cpu()
        from thunder_tpu.benchmarks.donation import donation_bench

        out = donation_bench(on_tpu=False)
        artifact = {"backend": jax.default_backend(), **out}
        with open("BENCH_DONATION.json", "w") as f:
            json.dump(artifact, f, indent=1)
        for k, v in out["results"].items():
            log(f"donation {k}: {v}")
        print(json.dumps({
            "metric": "donation_peak_bytes_reduction_pct",
            "value": out["results"]["peak_reduction_pct"],
            "unit": "%",
            # the donated peak vs the undonated peak of the same program
            "vs_baseline": round(
                out["results"]["update_peak_bytes_on"]
                / out["results"]["update_peak_bytes_off"], 3)
            if out["results"]["update_peak_bytes_off"] else None,
            "results": out["results"],
        }))
        return
    if len(sys.argv) > 1 and sys.argv[1] == "serving":
        # continuous-batching serving bench: N concurrent requests through
        # the paged-pool engine vs N sequential generate() calls — tokens/s,
        # mean batch occupancy, and the bucket-bounded compile count.  Host
        # work only, no TPU probe; artifact uses the BENCH_MICRO schema.
        from thunder_tpu._platform import force_cpu

        force_cpu()
        from thunder_tpu.benchmarks.serving import serving_bench

        out = serving_bench(on_tpu=False)
        artifact = {"backend": jax.default_backend(), **out}
        with open("BENCH_SERVING.json", "w") as f:
            json.dump(artifact, f, indent=1)
        for k, v in out["results"].items():
            log(f"serving {k}: {v}")
        print(json.dumps({
            "metric": "serving_vs_sequential_throughput_x",
            "value": out["results"]["throughput_ratio"],
            "unit": "x",
            # the sequential path IS the baseline of this ratio
            "vs_baseline": out["results"]["throughput_ratio"],
            "results": out["results"],
        }))
        return
    if len(sys.argv) > 1 and sys.argv[1] == "serving_async":
        # async-engine serving bench: short-cohort TTFT p95 under
        # long-prompt contention, the async event-loop engine (chunked
        # prefill + deferred materialization) vs the synchronous engine,
        # exact token parity asserted.  Host work only, no TPU probe;
        # artifact uses the BENCH_MICRO schema.
        from thunder_tpu._platform import force_cpu

        force_cpu()
        from thunder_tpu.benchmarks.serving_async import serving_async_bench

        out = serving_async_bench(on_tpu=False)
        artifact = {"backend": jax.default_backend(), **out}
        with open("BENCH_SERVING_ASYNC.json", "w") as f:
            json.dump(artifact, f, indent=1)
        for k, v in out["results"].items():
            log(f"serving_async {k}: {v}")
        print(json.dumps({
            "metric": "async_short_ttft_p95_improvement_x",
            "value": out["results"]["ttft_p95_improvement_x"],
            "unit": "x",
            # the synchronous engine IS the baseline of this ratio
            "vs_baseline": out["results"]["ttft_p95_improvement_x"],
            "results": out["results"],
        }))
        return
    if len(sys.argv) > 1 and sys.argv[1] == "serving_dp":
        # data-parallel serving bench: 2 replicated engine lanes behind
        # the prefix-affinity router vs one engine at equal total
        # occupancy — the router co-locates the shared-prefix family so
        # each lane decodes at its own block-table bucket (shape
        # segregation), exact token parity asserted.  Host work only, no
        # TPU probe; artifact uses the BENCH_MICRO schema.
        from thunder_tpu._platform import force_cpu

        force_cpu()
        from thunder_tpu.benchmarks.serving_dp import serving_dp_bench

        out = serving_dp_bench(on_tpu=False)
        artifact = {"backend": jax.default_backend(), **out}
        with open("BENCH_SERVING_DP.json", "w") as f:
            json.dump(artifact, f, indent=1)
        for k, v in out["results"].items():
            log(f"serving_dp {k}: {v}")
        print(json.dumps({
            "metric": "serving_dp_vs_solo_throughput_x",
            "value": out["results"]["throughput_ratio"],
            "unit": "x",
            # the solo engine IS the baseline of this ratio
            "vs_baseline": out["results"]["throughput_ratio"],
            "results": out["results"],
        }))
        return
    if len(sys.argv) > 1 and sys.argv[1] == "serving_mesh":
        # mesh-parallel serving bench: the SPMD engine (TP-sharded params,
        # heads-over-tp block arena, pjit bucket programs) vs the
        # single-device engine at equal total batch, token parity asserted
        # against solo sharded generate().  Runs on the virtual 8-device
        # CPU mesh; artifact uses the BENCH_MICRO schema.
        from thunder_tpu._platform import force_cpu

        force_cpu(8)
        from thunder_tpu.benchmarks.serving_mesh import serving_mesh_bench

        out = serving_mesh_bench(on_tpu=False)
        artifact = {"backend": jax.default_backend(), **out}
        with open("BENCH_SERVING_MESH.json", "w") as f:
            json.dump(artifact, f, indent=1)
        for k, v in out["results"].items():
            log(f"serving_mesh {k}: {v}")
        print(json.dumps({
            "metric": "serving_mesh_vs_single_device_throughput_x",
            "value": out["results"]["throughput_ratio"],
            "unit": "x",
            # the single-device engine IS the baseline of this ratio
            "vs_baseline": out["results"]["throughput_ratio"],
            "results": out["results"],
        }))
        return
    if len(sys.argv) > 1 and sys.argv[1] == "capacity":
        # multi-tenant capacity bench: admitted concurrency at fixed arena
        # bytes (int8 KV pool vs the f32 baseline, exact token parity
        # asserted) plus the adapter-mix tokens/sec overhead and the
        # zero-recompile-per-adapter contract.  Host work only, no TPU
        # probe; artifact uses the BENCH_MICRO schema.
        from thunder_tpu._platform import force_cpu

        force_cpu()
        from thunder_tpu.benchmarks.capacity import capacity_bench

        out = capacity_bench(on_tpu=False)
        artifact = {"backend": jax.default_backend(), **out}
        with open("BENCH_CAPACITY.json", "w") as f:
            json.dump(artifact, f, indent=1)
        for k, v in out["results"].items():
            log(f"capacity {k}: {v}")
        print(json.dumps({
            "metric": "int8_admitted_concurrency_x",
            "value": out["results"]["admitted_ratio"],
            "unit": "x",
            # the f32 pool at the same arena bytes IS the baseline
            "vs_baseline": out["results"]["admitted_ratio"],
            "results": out["results"],
        }))
        return
    if len(sys.argv) > 1 and sys.argv[1] == "tracing":
        # serving-plane tracing overhead: default engine vs observability
        # explicitly off (the gated ≈1.0x claim — off must be the identical
        # code path) vs spans+SLO+flight armed.  Host work only, no TPU
        # probe; artifact uses the BENCH_MICRO schema.
        from thunder_tpu._platform import force_cpu

        force_cpu()
        from thunder_tpu.benchmarks.tracing_overhead import tracing_overhead_bench

        out = tracing_overhead_bench(on_tpu=False)
        artifact = {"backend": jax.default_backend(), **out}
        with open("BENCH_TRACING.json", "w") as f:
            json.dump(artifact, f, indent=1)
        for k, v in out["results"].items():
            log(f"tracing {k}: {v}")
        print(json.dumps({
            "metric": "serving_tracing_off_overhead_x",
            "value": out["results"]["off_overhead_x"],
            "unit": "x",
            # off-vs-default is definitionally 1.0: tracing off takes the
            # unmodified drive loop (token-identical, program-identical)
            "vs_baseline": 1.0,
            "results": out["results"],
        }))
        return
    if len(sys.argv) > 1 and sys.argv[1] == "recovery":
        # fault-tolerance bench: re-prefill recovery vs a cold engine
        # restart at the same resume point, injected-fault token parity
        # (retry + arena-rebuild paths both fire), and the armed-but-silent
        # FaultPlan overhead.  Host work only, no TPU probe; artifact uses
        # the BENCH_MICRO schema.
        from thunder_tpu._platform import force_cpu

        force_cpu()
        from thunder_tpu.benchmarks.recovery import recovery_bench

        out = recovery_bench(on_tpu=False)
        artifact = {"backend": jax.default_backend(), **out}
        with open("BENCH_RECOVERY.json", "w") as f:
            json.dump(artifact, f, indent=1)
        for k, v in out["results"].items():
            log(f"recovery {k}: {v}")
        print(json.dumps({
            "metric": "recovery_vs_cold_restart_speedup_x",
            "value": out["results"]["speedup_x"],
            "unit": "x",
            # the cold restart IS the baseline of this ratio
            "vs_baseline": out["results"]["speedup_x"],
            "results": out["results"],
        }))
        return
    if len(sys.argv) > 1 and sys.argv[1] == "paged_attn":
        # paged-attention decode bench: attn="paged" (Pallas flash-decoding
        # off the block arena, interpret mode on CPU) vs attn="gather" —
        # token parity + program purity gated, analytic arena-traffic
        # ratio gated >1; wall-clock informational until a real TPU window.
        from thunder_tpu._platform import force_cpu

        force_cpu()
        from thunder_tpu.benchmarks.paged_attention import paged_attention_bench

        out = paged_attention_bench(on_tpu=False)
        artifact = {"backend": jax.default_backend(), **out}
        with open("BENCH_PAGED_ATTN.json", "w") as f:
            json.dump(artifact, f, indent=1)
        for k, v in out["results"].items():
            log(f"paged_attn {k}: {v}")
        print(json.dumps({
            "metric": "paged_attn_arena_traffic_ratio_x",
            "value": out["results"]["arena_traffic_ratio_x"],
            "unit": "x",
            # the gather path's per-step arena bytes ARE the baseline
            "vs_baseline": out["results"]["arena_traffic_ratio_x"],
            "results": out["results"],
        }))
        return
    if len(sys.argv) > 1 and sys.argv[1] == "ragged":
        # ragged paged decode + paged chunk-prefill bench: blocks walked vs
        # real on a mixed 64/1024-token occupancy-8 cohort (the bucket tax
        # the ragged clamp stops paying, gated >= 2x), exact token parity
        # vs the gather twins, analytic chunk arena-traffic ratio, and the
        # zero-new-programs warm-engine contract.
        from thunder_tpu._platform import force_cpu

        force_cpu()
        from thunder_tpu.benchmarks.ragged import ragged_bench

        out = ragged_bench(on_tpu=False)
        artifact = {"backend": jax.default_backend(), **out}
        with open("BENCH_RAGGED.json", "w") as f:
            json.dump(artifact, f, indent=1)
        for k, v in out["results"].items():
            log(f"ragged {k}: {v}")
        print(json.dumps({
            "metric": "ragged_blocks_walked_over_real_x",
            "value": out["results"]["blocks_ratio_x"],
            "unit": "x",
            # the bucketed walk (what every step paid pre-ragged) IS the
            # baseline of this ratio
            "vs_baseline": out["results"]["blocks_ratio_x"],
            "results": out["results"],
        }))
        return
    if len(sys.argv) > 1 and sys.argv[1] == "serving_spec":
        # speculative-serving bench: draft/verify lane vs the plain decode
        # engine at occupancy 8 with a high-acceptance draft (the 1-layer
        # prefix of a residual-no-op'd 4-layer target), exact token parity
        # asserted request-by-request.  Host work only, no TPU probe;
        # artifact uses the BENCH_MICRO schema.
        from thunder_tpu._platform import force_cpu

        force_cpu()
        from thunder_tpu.benchmarks.serving_spec import serving_spec_bench

        out = serving_spec_bench(on_tpu=False)
        artifact = {"backend": jax.default_backend(), **out}
        with open("BENCH_SERVING_SPEC.json", "w") as f:
            json.dump(artifact, f, indent=1)
        for k, v in out["results"].items():
            log(f"serving_spec {k}: {v}")
        print(json.dumps({
            "metric": "serving_spec_vs_plain_throughput_x",
            "value": out["results"]["speedup_x"],
            "unit": "x",
            # the plain continuous-batching engine IS the baseline
            "vs_baseline": out["results"]["speedup_x"],
            "results": out["results"],
        }))
        return
    if len(sys.argv) > 1 and sys.argv[1] == "multistep":
        # multi-step decode bench: host visits per served token at
        # decode_steps N in {1, 4, 8}, occupancy 8, exact token parity
        # asserted request-by-request and zero cold compiles in the
        # measured windows.  Host work only, no TPU probe; artifact uses
        # the BENCH_MICRO schema.
        from thunder_tpu._platform import force_cpu

        force_cpu()
        from thunder_tpu.benchmarks.multistep import multistep_bench

        out = multistep_bench(on_tpu=False)
        artifact = {"backend": jax.default_backend(), **out}
        with open("BENCH_MULTISTEP.json", "w") as f:
            json.dump(artifact, f, indent=1)
        for k, v in out["results"].items():
            log(f"multistep {k}: {v}")
        ph = out["results"]["per_horizon"]
        h1 = ph["1"]["host_visits_per_token"]
        hN = ph[str(out["results"]["horizons"][-1])]["host_visits_per_token"]
        print(json.dumps({
            "metric": "multistep_host_visit_amortization_x",
            "value": round(h1 / hN, 2),
            "unit": "x",
            # the 1-step engine's host-visits-per-token IS the baseline
            "vs_baseline": round(h1 / hN, 2),
            "results": out["results"],
        }))
        return
    if len(sys.argv) > 1 and sys.argv[1] == "sessions":
        # stateful-serving bench: turn-2 TTFT with resident session KV vs a
        # cold full-history re-prefill (tokens bit-identical), high-class
        # TTFT p95 with evict-and-resume preemption vs FIFO starvation, and
        # zero compiled programs for brand-new constraint schemas.  Host
        # work only, no TPU probe; artifact uses the BENCH_MICRO schema.
        from thunder_tpu._platform import force_cpu

        force_cpu()
        from thunder_tpu.benchmarks.sessions import sessions_bench

        out = sessions_bench(on_tpu=False)
        artifact = {"backend": jax.default_backend(), **out}
        with open("BENCH_SESSIONS.json", "w") as f:
            json.dump(artifact, f, indent=1)
        for k, v in out["results"].items():
            log(f"sessions {k}: {v}")
        print(json.dumps({
            "metric": "sessions_turn2_ttft_speedup_x",
            "value": out["results"]["ttft_speedup_x"],
            "unit": "x",
            # the cold full-history re-prefill IS the baseline
            "vs_baseline": out["results"]["ttft_speedup_x"],
            "results": out["results"],
        }))
        return
    if len(sys.argv) > 1 and sys.argv[1] == "goodput":
        # goodput-ledger bench: observation overhead vs an identical
        # goodput=False engine, the exact conservation identity in-bench,
        # ledger/engine speculative-acceptance integer agreement, and zero
        # programs compiled for observation.  Host work only, no TPU probe;
        # artifact uses the BENCH_MICRO schema.
        from thunder_tpu._platform import force_cpu

        force_cpu()
        from thunder_tpu.benchmarks.goodput import goodput_bench

        out = goodput_bench(on_tpu=False)
        artifact = {"backend": jax.default_backend(), **out}
        with open("BENCH_GOODPUT.json", "w") as f:
            json.dump(artifact, f, indent=1)
        for k, v in out["results"].items():
            log(f"goodput {k}: {v}")
        print(json.dumps({
            "metric": "goodput_observation_overhead_x",
            "value": out["results"]["overhead_ratio_x"],
            "unit": "x",
            # the goodput=False engine IS the baseline
            "vs_baseline": out["results"]["overhead_ratio_x"],
            "results": out["results"],
        }))
        return
    if len(sys.argv) > 1 and sys.argv[1] == "cost":
        # analytic companion to the measured headline (no TPU needed): XLA's
        # own cost model on the compiled loss+grad at headline geometry, and
        # the v5e roofline upper bound in tokens/s.  Shapes only — params are
        # ShapeDtypeStructs, so this runs in seconds on CPU.
        from thunder_tpu._platform import force_cpu

        force_cpu()
        from thunder_tpu.benchmarks import jax_gpt_loss
        from thunder_tpu.examine import HW_PEAKS, cost_analysis

        name, overrides, B, T = _HEADLINE_GEOMETRY
        cfg = llama.Config.from_name(name, **overrides)
        structs = jax.eval_shape(
            lambda: llama.init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.bfloat16))
        idx_s = jax.ShapeDtypeStruct((B, T), jnp.int32)
        cos_s = jax.ShapeDtypeStruct((T, cfg.rope_n_elem), jnp.float32)
        loss = jax_gpt_loss(cfg)
        fl, bw = HW_PEAKS["tpu"]
        fwd = cost_analysis(loss, structs, idx_s, idx_s, cos_s, cos_s,
                            flops_per_sec=fl, bytes_per_sec=bw)
        bwd = cost_analysis(jax.grad(loss), structs, idx_s, idx_s, cos_s, cos_s,
                            flops_per_sec=fl, bytes_per_sec=bw)
        # the FLOPs count is backend-robust; bytes-accessed comes from THIS
        # backend's fusion decisions (a CPU compile overestimates TPU HBM
        # traffic), so the headline limit is the compute roofline
        if not bwd["compute_seconds"]:
            print(json.dumps({"metric": "compute_roofline_tokens_per_sec", "value": 0.0,
                              "unit": "tokens/s", "vs_baseline": 0.0,
                              "error": "cost model unavailable on this backend"}))
            return
        ub = B * T / bwd["compute_seconds"]
        print(json.dumps({
            "metric": "compute_roofline_tokens_per_sec", "value": round(ub, 1),
            "unit": "tokens/s", "vs_baseline": 1.0,
            "config": f"{cfg.name} n_layer={cfg.n_layer} B={B} T={T} (v5e bf16 peak)",
            "fwd": {k: fwd[k] for k in ("flops", "bytes_accessed", "arithmetic_intensity", "bound")},
            "fwd_bwd": {k: bwd[k] for k in ("flops", "bytes_accessed", "arithmetic_intensity", "bound")},
            "backend_compiled": jax.default_backend(),
            "note": "XLA cost model of the compiled fwd+bwd at headline shapes; "
                    "value = FLOPs-limited tokens/s at v5e bf16 peak (bytes/"
                    "memory-bound figures reflect THIS backend's fusion and "
                    "overestimate TPU HBM traffic when compiled on cpu)",
        }))
        return
    on_tpu = _resolve_backend() == "tpu"
    if len(sys.argv) > 1 and sys.argv[1] == "blocks":
        rows = blocks_benchmarks(on_tpu)
        ok = [r["speedup"] for r in rows if isinstance(r.get("speedup"), (int, float))]
        print(json.dumps({
            "metric": "blocks_geomean_speedup_vs_jax",
            "value": round(float(np.prod(ok) ** (1 / len(ok))), 3) if ok else 0.0,
            "unit": "x", "vs_baseline": 1.0, "n": len(rows),
        }))
        return
    if len(sys.argv) > 1 and sys.argv[1] == "micro":
        micro_benchmarks(on_tpu)
        print(json.dumps({"metric": "micro", "value": 1.0, "unit": "ok", "vs_baseline": 1.0}))
        return
    if len(sys.argv) > 1 and sys.argv[1] == "sweep":
        r = sweep_benchmarks(on_tpu)
        ok = [v["speedup"] for v in r.values() if isinstance(v, dict) and v.get("speedup")]
        print(json.dumps({
            "metric": "sweep_geomean_speedup_vs_jax",
            "value": round(float(np.prod(ok) ** (1 / len(ok))), 3) if ok else 0.0,
            "unit": "x", "vs_baseline": 1.0,
        }))
        return
    if len(sys.argv) > 1 and sys.argv[1] == "decode":
        r = decode_benchmark(on_tpu)
        print(json.dumps({
            "metric": "kvcache_decode_tokens_per_sec" if on_tpu else "kvcache_decode_cpu_smoke",
            "value": round(r["fp"], 1),
            "unit": "tokens/s",
            "vs_baseline": round(r["int8"] / r["fp"], 3),
        }))
        return
    exercise_tpu_path = on_tpu or os.environ.get("THUNDER_TPU_BENCH_EXERCISE_TPU_PATH", "") in ("1", "true")
    if exercise_tpu_path:
        # Llama-2-7B depth-truncated to 4 REAL layers (n_embd=4096, n_head=32,
        # intermediate 11008 — the true 7B layer program): params+AdamW fp32
        # state ≈ 13 GB, fits one v5e chip with remat at T=2048/bf16.  The
        # per-layer program is identical to the 32-layer flagship, so the
        # extrapolated full-7B throughput below is a layer-time scale-up.
        # THUNDER_TPU_BENCH_EXERCISE_TPU_PATH runs this exact code path on
        # CPU at toy dims — a pre-flight so the flaky-TPU window is never
        # spent discovering a bench bug
        # THUNDER_TPU_BENCH_FUSED_CE=1 flips the head to the fused
        # linear+CE prim (no materialized logits) — an A/B lever for tunnel
        # sessions; tools/config_sweep.py measures the same toggle
        fused = {"fused_head_ce": True} if os.environ.get("THUNDER_TPU_BENCH_FUSED_CE") else {}
        if on_tpu:
            _name, _overrides, B, T = _HEADLINE_GEOMETRY
            cfg = llama.Config.from_name(_name, **_overrides, **fused)
            steps, baseline_steps = 10, 10
        else:
            cfg = llama.Config.from_name(
                "Llama-2-7b-hf", n_layer=2, n_embd=256, n_head=4, intermediate_size=688,
                vocab_size=512, **fused,
            )
            B, T = 2, 256
            steps, baseline_steps = 3, 3
    else:  # CPU smoke mode (dev only; driver runs on TPU)
        cfg = llama.Config.from_name("tiny-llama-debug")
        B, T = 4, 64
        steps, baseline_steps = 5, 5
    log(f"bench: backend={jax.default_backend()} cfg={cfg.name} n_layer={cfg.n_layer} "
        f"n_embd={cfg.n_embd} B={B} T={T}")
    optimizer = optax.adamw(1e-4)

    compiled_tps = compiled_run(cfg, B, T, optimizer, steps)
    jax.clear_caches()  # free the compiled program + donated buffers before the next phase
    baseline_tps = baseline_run(cfg, B, T, optimizer, baseline_steps)

    backend = jax.default_backend()
    report = {
        "metric": "llama2_7b_4layer_pretrain_tokens_per_sec_single_chip" if on_tpu
                  else ("tpu_path_preflight_cpu" if exercise_tpu_path
                        else "llama_tiny_pretrain_tokens_per_sec_cpu_smoke"),
        "value": round(compiled_tps, 1),
        "unit": "tokens/s",
        "vs_baseline": round(compiled_tps / baseline_tps, 3),
        "mfu_pct": round(100 * mfu(compiled_tps, cfg, T, backend), 2),
        "baseline_mfu_pct": round(100 * mfu(baseline_tps, cfg, T, backend), 2),
        "backend": backend,
        "tpu_attempts": _all_attempts(),
    }
    if backend != "tpu":
        report["last_tpu"] = _last_tpu_result()
    if exercise_tpu_path:
        # extrapolate to the 32-layer 7B: per-token FLOPs scale with the layer
        # count (embedding/head amortize), so tokens/s_7B ≈ tokens/s_4L ×
        # flops_4L / flops_32L at equal MFU — report both honestly
        full = llama.Config.from_name("Llama-2-7b-hf")
        scale = model_flops_per_token(cfg, T) / model_flops_per_token(full, T)
        report["extrapolated_7b_tokens_per_sec"] = round(compiled_tps * scale, 1)
    print(json.dumps(report))


def _last_tpu_result():
    """Latest committed real-TPU headline (BENCH_TPU.json), embedded into any
    non-TPU artifact so a tunnel-down driver run is never information-free
    (VERDICT r3 #1: BENCH_r03.json parsed to null while the real numbers sat
    in a separately committed file)."""
    try:
        path = os.path.join(os.path.dirname(os.path.abspath(__file__)), "BENCH_TPU.json")
        with open(path) as f:
            return json.load(f)
    except Exception:
        return None


def _all_attempts() -> list:
    """Attempts from this process plus any recorded before a forced-CPU
    re-exec (handed over via env)."""
    prior = os.environ.get("THUNDER_TPU_BENCH_ATTEMPTS")
    out = json.loads(prior) if prior else []
    return out + tpu_attempts


if __name__ == "__main__":
    try:
        main()
    except Exception:
        # Fail-soft: always emit one valid JSON line so the driver records a
        # diagnostic artifact instead of an empty one (round-1 BENCH was rc=1
        # with no output at all).
        traceback.print_exc(file=sys.stderr)
        print(json.dumps({
            "metric": "bench_error",
            "value": 0.0,
            "unit": "tokens/s",
            "vs_baseline": 0.0,
            "last_tpu": _last_tpu_result(),
        }))
        sys.exit(1)
