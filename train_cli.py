#!/usr/bin/env python
"""End-to-end pretraining CLI (reference ``benchmarks/benchmark_litgpt.py``:
config × parallelism × precision sweeps with tokens/s + memory reporting).

Examples::

    # single chip (or CPU smoke), flagship config scaled down
    python train_cli.py --config tiny-llama-debug --steps 20

    # 8 virtual CPU devices, FSDP, bf16 params
    python train_cli.py --config tiny-llama-debug --mode fsdp --devices 8 \
        --virtual-cpu --steps 10

    # TP x FSDP with gradient accumulation
    python train_cli.py --mode tp_fsdp --devices 8 --virtual-cpu --accum 2

Modes map to the distributed API: ``none`` (single device), ``ddp``,
``fsdp`` (ZeRO-2), ``zero3`` (regather-in-backward), ``tp_fsdp``
(megatron rules x dim-0 shards), ``sp`` (ring-attention sequence
parallelism), ``pp`` (GPipe pipeline), ``ep`` (expert-parallel MoE
all_to_all; MoE configs only).  ``--quant int8`` runs forward GEMMs
dynamically int8-quantized with bf16/f32 grads (the TE-executor training
contract, reference transformer_engineex.py:183).  Prints per-step timings
and a final JSON summary line.
"""
from __future__ import annotations

import argparse
import json
import sys
import time


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__, formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--config", default="tiny-llama-debug", help="model config name (models/llama.py zoo)")
    ap.add_argument("--mode", default="none",
                    choices=["none", "ddp", "fsdp", "zero3", "tp_fsdp", "sp", "pp", "ep"])
    ap.add_argument("--fused-ce", action="store_true",
                    help="fuse the lm-head matmul into a chunked-vocab cross-entropy "
                         "(no materialized logits; Config.fused_head_ce)")
    ap.add_argument("--quant", default=None, choices=["int8", "fp8"],
                    help="quantized training: int8/fp8(e4m3) forward GEMMs, full-precision grads")
    ap.add_argument("--comm-combine-mb", type=float, default=None,
                    help="XLA collective-combining threshold in MiB (the bucket_size_in_mb analog)")
    ap.add_argument("--sp-impl", default="ring", choices=["ring", "ulysses"],
                    help="sequence-parallel attention: ring (ppermute K/V rotation) or "
                         "ulysses (all_to_all seq<->head re-shard)")
    ap.add_argument("--bucket", action="store_true",
                    help="pad batches to power-of-two (B, T) buckets so one compiled "
                         "program serves every shape inside a bucket")
    ap.add_argument("--devices", type=int, default=1)
    ap.add_argument("--virtual-cpu", action="store_true", help="force N virtual CPU devices (no hardware needed)")
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--seq", type=int, default=None, help="sequence length (default: min(block_size, 128))")
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--accum", type=int, default=1, help="gradient-accumulation micro steps")
    ap.add_argument("--dtype", default="float32", choices=["float32", "bfloat16"])
    ap.add_argument("--lr", type=float, default=1e-3)
    ap.add_argument("--no-remat", action="store_true")
    ap.add_argument("--remat", default=None, choices=["on", "off", "auto"],
                    help="activation rematerialization; 'auto' pays recompute only "
                         "when residuals would not fit device memory (overrides --no-remat)")
    ap.add_argument("--checkpoint-dir", default=None, help="save a checkpoint at the end (orbax)")
    ap.add_argument("--telemetry", default=None,
                    help="per-step JSONL telemetry path (StepLogger: loss, step time, "
                         "tokens/sec, peak-bytes estimate; mirrored into the metrics registry)")
    ap.add_argument("--telemetry-grad-norm", action="store_true",
                    help="also log the global grad norm each step (runs one extra "
                         "grads-only step per logged step; TrainStep modes, accum=1)")
    args = ap.parse_args(argv)

    if args.virtual_cpu:
        from thunder_tpu._platform import force_cpu

        force_cpu(args.devices)

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from thunder_tpu import distributed as dist
    from thunder_tpu.models import llama

    devices = jax.devices()[: args.devices]
    assert len(devices) >= args.devices, f"need {args.devices} devices, have {len(jax.devices())}"

    cfg = llama.Config.from_name(
        args.config, **({"fused_head_ce": True} if args.fused_ce else {})
    )
    T = args.seq or min(cfg.block_size, 128)
    dtype = jnp.bfloat16 if args.dtype == "bfloat16" else jnp.float32
    params = llama.init_params(cfg, jax.random.PRNGKey(0), dtype=dtype)
    log(f"config={cfg.name} n_layer={cfg.n_layer} n_embd={cfg.n_embd} "
        f"params={llama.param_count(params)/1e6:.1f}M B={args.batch} T={T} "
        f"mode={args.mode} devices={args.devices} dtype={args.dtype}")

    idx = jax.random.randint(jax.random.PRNGKey(1), (args.batch, T), 0, cfg.vocab_size)
    tgt = jax.random.randint(jax.random.PRNGKey(2), (args.batch, T), 0, cfg.vocab_size)
    cos, sin = llama.build_rope_cache(cfg, T)
    optimizer = optax.adamw(args.lr)

    if args.mode in ("sp", "pp", "ep"):
        assert args.quant is None, "--quant needs a TrainStep mode (not sp/pp/ep)"
        assert not args.fused_ce, "--fused-ce needs a TrainStep mode (not sp/pp/ep)"
        assert args.comm_combine_mb is None, "--comm-combine-mb needs a TrainStep mode (not sp/pp/ep)"
        assert not args.bucket, "--bucket needs a TrainStep mode (not sp/pp/ep)"
        # sequence / pipeline / expert parallelism drive the shard_map-based
        # training losses directly: jax.value_and_grad through the shard_map
        # (grad sync comes out of the broadcast transpose), optax update jitted
        # alongside — one compiled program per step, like TrainStep
        if args.mode == "sp":
            assert T % args.devices == 0, f"--seq {T} must divide over sp={args.devices}"
            mesh = dist.make_mesh({"sp": args.devices}, devices=devices)
            train_params = params
            sp_loss = dist.ulysses_gpt_loss if args.sp_impl == "ulysses" else dist.sp_gpt_loss

            def loss_fn(p, i, t):
                return sp_loss(p, i, t, cos, sin, cfg, mesh=mesh)
        elif args.mode == "pp":
            pp = args.devices
            assert cfg.n_layer % pp == 0, f"n_layer {cfg.n_layer} must divide over pp={pp}"
            n_micro = 2 if args.batch % 2 == 0 else 1
            mesh = dist.make_mesh({"pp": pp}, devices=devices)
            train_params = dist.place_pipeline_params(dist.stack_blocks(params), mesh)

            def loss_fn(p, i, t):
                return dist.pp_gpt_loss(p, i, t, cos, sin, cfg, mesh=mesh, n_micro=n_micro)
        else:  # ep
            assert cfg.mlp_class == "LLaMAMoE", (
                f"--mode ep needs a MoE config (e.g. tiny-moe-debug, mixtral-like); got {cfg.name}"
            )
            assert args.batch % args.devices == 0, (
                f"--batch {args.batch} must divide over ep={args.devices}"
            )
            mesh = dist.make_mesh({"ep": args.devices}, devices=devices)
            train_params = params

            def loss_fn(p, i, t):
                return dist.ep_gpt_loss(p, i, t, cos, sin, cfg, mesh=mesh)

        opt_state = optimizer.init(train_params)

        @jax.jit
        def sharded_step(p, o, i, t):
            loss, grads = jax.value_and_grad(loss_fn)(p, i, t)
            updates, o = optimizer.update(grads, o, p)
            return optax.apply_updates(p, updates), o, loss

        step = lambda p, o, i, t, c, s: sharded_step(p, o, i, t)
        accumulate = None
        train_step_obj = None
        params = train_params
    else:
        if args.mode == "none":
            mesh = dist.make_mesh({"dp": 1}, devices=devices[:1])
            params = dist.ddp(params, mesh)
        elif args.mode == "ddp":
            mesh = dist.make_mesh({"dp": args.devices}, devices=devices)
            params = dist.ddp(params, mesh)
        elif args.mode in ("fsdp", "zero3"):
            mesh = dist.make_mesh({"fsdp": args.devices}, devices=devices)
            params = dist.fsdp(params, mesh)
        else:  # tp_fsdp
            tp = 2 if args.devices % 2 == 0 else 1
            mesh = dist.make_mesh({"fsdp": args.devices // tp, "tp": tp}, devices=devices)
            params = dist.tp_fsdp(params, mesh)

        def loss_fn(p, i, t, c, s):
            return llama.gpt_loss(p, i, t, c, s, cfg)

        train_step = dist.make_train_step(
            loss_fn, optimizer, mesh,
            remat=({"on": True, "off": False, "auto": "auto"}[args.remat]
                   if args.remat else not args.no_remat),
            zero3=(args.mode == "zero3"),
            quant=args.quant, comm_combine_threshold_mb=args.comm_combine_mb,
            bucketer=llama.batch_bucketer(cfg) if args.bucket else None,
        )
        opt_state = train_step.init_optimizer_state(params)
        step = train_step
        accumulate = train_step.accumulate
        train_step_obj = train_step

    t0 = time.perf_counter()
    if args.accum > 1:
        assert accumulate is not None, "--accum needs a TrainStep mode (not sp/pp/ep)"
        mb = args.batch // args.accum
        micro = [(idx[k * mb:(k + 1) * mb], tgt[k * mb:(k + 1) * mb], cos, sin) for k in range(args.accum)]
        params, opt_state, loss = accumulate(params, opt_state, micro)
    else:
        params, opt_state, loss = step(params, opt_state, idx, tgt, cos, sin)
    jax.block_until_ready(loss)
    log(f"compile+first step: {time.perf_counter()-t0:.1f}s loss={float(loss):.4f}")

    # per-step telemetry (observability.telemetry.StepLogger): one JSONL
    # record per optimizer step, mirrored into the metrics registry.  The
    # peak-bytes estimate is static (del-aware liveness over the lowered
    # fw/bw traces), computed once — TrainStep modes only (sp/pp/ep drive
    # shard_map losses directly, no thunder trace to account)
    telemetry = None
    peak_bytes = None
    if args.telemetry:
        from thunder_tpu.observability.telemetry import StepLogger, trace_peak_bytes

        telemetry = StepLogger(args.telemetry, meta={
            "config": cfg.name, "mode": args.mode, "devices": args.devices,
            "batch": args.batch, "seq": T, "dtype": args.dtype,
            "accum": args.accum, "quant": args.quant,
        })
        if getattr(train_step_obj, "fw_trace", None) is not None:
            peak_bytes = max(
                trace_peak_bytes(train_step_obj.fw_trace),
                trace_peak_bytes(train_step_obj.bw_trace),
            )
        log(f"telemetry -> {args.telemetry}"
            + (f" (peak_bytes_estimate={peak_bytes})" if peak_bytes else ""))

    t0 = time.perf_counter()
    last = loss
    for k in range(args.steps):
        t_step = time.perf_counter()
        if args.accum > 1:
            params, opt_state, last = accumulate(params, opt_state, micro)
        else:
            params, opt_state, last = step(params, opt_state, idx, tgt, cos, sin)
        if telemetry is not None:
            jax.block_until_ready(last)
            gn = None
            if args.telemetry_grad_norm and train_step_obj is not None and args.accum == 1:
                import optax as _optax

                _, g = train_step_obj.grads(params, opt_state, idx, tgt, cos, sin)
                gn = float(_optax.global_norm(g))
            telemetry.log_step(
                k,
                loss=float(last),
                grad_norm=gn,
                step_time_s=time.perf_counter() - t_step,
                tokens=args.batch * T,
                peak_bytes=peak_bytes,
            )
    jax.block_until_ready(last)
    dt = time.perf_counter() - t0
    if telemetry is not None:
        telemetry.close()
    tps = args.batch * T * args.steps / dt

    if args.checkpoint_dir:
        from thunder_tpu.distributed import save_checkpoint

        save_checkpoint(args.checkpoint_dir, {"params": params, "opt_state": opt_state}, step=args.steps)
        log(f"checkpoint saved to {args.checkpoint_dir}")

    print(json.dumps({
        "config": cfg.name, "mode": args.mode, "devices": args.devices,
        "quant": args.quant,
        "fused_ce": bool(args.fused_ce),
        "tokens_per_sec": round(tps, 1), "ms_per_step": round(dt / args.steps * 1e3, 2),
        "final_loss": round(float(last), 4),
    }))


if __name__ == "__main__":
    main()
