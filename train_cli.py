#!/usr/bin/env python
"""End-to-end pretraining CLI (reference ``benchmarks/benchmark_litgpt.py``:
config × parallelism × precision sweeps with tokens/s + memory reporting).

Examples::

    # single chip (or CPU smoke), flagship config scaled down
    python train_cli.py --config tiny-llama-debug --steps 20

    # 8 virtual CPU devices, FSDP, bf16 params
    python train_cli.py --config tiny-llama-debug --mode fsdp --devices 8 \
        --virtual-cpu --steps 10

    # TP x FSDP with gradient accumulation
    python train_cli.py --mode tp_fsdp --devices 8 --virtual-cpu --accum 2

Modes map to the distributed API: ``none`` (single device), ``ddp``,
``fsdp`` (ZeRO-2), ``zero3`` (regather-in-backward), ``tp_fsdp``
(megatron rules x dim-0 shards), ``sp`` (ring-attention sequence
parallelism), ``pp`` (GPipe pipeline), ``ep`` (expert-parallel MoE
all_to_all; MoE configs only).  ``--quant int8`` runs forward GEMMs
dynamically int8-quantized with bf16/f32 grads (the TE-executor training
contract, reference transformer_engineex.py:183).  Prints per-step timings
and a final JSON summary line.
"""
from __future__ import annotations

import argparse
import json
import sys
import time


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__, formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--config", default="tiny-llama-debug", help="model config name (models/llama.py zoo)")
    ap.add_argument("--mode", default="none",
                    choices=["none", "ddp", "fsdp", "zero3", "tp_fsdp", "sp", "pp", "ep"])
    ap.add_argument("--fused-ce", action="store_true",
                    help="fuse the lm-head matmul into a chunked-vocab cross-entropy "
                         "(no materialized logits; Config.fused_head_ce)")
    ap.add_argument("--quant", default=None, choices=["int8", "fp8"],
                    help="quantized training: int8/fp8(e4m3) forward GEMMs, full-precision grads")
    ap.add_argument("--comm-combine-mb", type=float, default=None,
                    help="XLA collective-combining threshold in MiB (the bucket_size_in_mb analog)")
    ap.add_argument("--sp-impl", default="ring", choices=["ring", "ulysses"],
                    help="sequence-parallel attention: ring (ppermute K/V rotation) or "
                         "ulysses (all_to_all seq<->head re-shard)")
    ap.add_argument("--bucket", action="store_true",
                    help="pad batches to power-of-two (B, T) buckets so one compiled "
                         "program serves every shape inside a bucket")
    ap.add_argument("--devices", type=int, default=1)
    ap.add_argument("--virtual-cpu", action="store_true", help="force N virtual CPU devices (no hardware needed)")
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--seq", type=int, default=None, help="sequence length (default: min(block_size, 128))")
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--accum", type=int, default=1,
                    help="host-loop gradient accumulation (k calls to the grads/apply "
                         "entries per optimizer step)")
    ap.add_argument("--accum-steps", type=int, default=1,
                    help="IN-PROGRAM gradient accumulation: one donated program scans k "
                         "microbatches with a float32 accumulator (TrainStep modes; in pp "
                         "mode k rides the GPipe microbatch schedule instead)")
    ap.add_argument("--dtype", default="float32", choices=["float32", "bfloat16"])
    ap.add_argument("--lr", type=float, default=1e-3)
    ap.add_argument("--no-remat", action="store_true")
    ap.add_argument("--remat", default=None,
                    choices=["on", "off", "auto", "none", "attention", "full_block"],
                    help="activation rematerialization: on/off/auto (legacy) or a policy — "
                         "none, attention (recompute attention internals), full_block "
                         "(aggressive, residuals shrink toward the inputs; what zero3 forces)")
    ap.add_argument("--overlap", action="store_true",
                    help="bucketed-psum gradient collectives overlapping the backward "
                         "(pure-dp meshes; the torch-DDP bucket_cap_mb design)")
    ap.add_argument("--overlap-bucket-mb", type=float, default=4.0,
                    help="gradient bucket cap in MiB for --overlap")
    ap.add_argument("--checkpoint-dir", default=None,
                    help="checkpoint directory: with --checkpoint-every the async atomic "
                         "checkpointer writes here during the run; otherwise one final "
                         "save (orbax) lands here")
    ap.add_argument("--checkpoint-every", type=int, default=0,
                    help="dispatch an async atomic checkpoint every N optimizer steps "
                         "(train.checkpoint; 0 = off)")
    ap.add_argument("--resume", action="store_true",
                    help="resume from the newest committed checkpoint in --checkpoint-dir "
                         "(torn checkpoints are skipped with a structured warning); the "
                         "replayed loss curve is bit-identical to an undisturbed run")
    ap.add_argument("--telemetry", default=None,
                    help="per-step JSONL telemetry path (StepLogger: loss, step time, "
                         "tokens/sec, peak-bytes estimate; mirrored into the metrics registry)")
    ap.add_argument("--telemetry-grad-norm", action="store_true",
                    help="also log the global grad norm each step (runs one extra "
                         "grads-only step per logged step; TrainStep modes, accum=1)")
    args = ap.parse_args(argv)

    if args.virtual_cpu:
        from thunder_tpu._platform import force_cpu

        force_cpu(args.devices)

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from thunder_tpu import distributed as dist
    from thunder_tpu.models import llama

    devices = jax.devices()[: args.devices]
    assert len(devices) >= args.devices, f"need {args.devices} devices, have {len(jax.devices())}"

    cfg = llama.Config.from_name(
        args.config, **({"fused_head_ce": True} if args.fused_ce else {})
    )
    T = args.seq or min(cfg.block_size, 128)
    dtype = jnp.bfloat16 if args.dtype == "bfloat16" else jnp.float32
    params = llama.init_params(cfg, jax.random.PRNGKey(0), dtype=dtype)
    log(f"config={cfg.name} n_layer={cfg.n_layer} n_embd={cfg.n_embd} "
        f"params={llama.param_count(params)/1e6:.1f}M B={args.batch} T={T} "
        f"mode={args.mode} devices={args.devices} dtype={args.dtype}")

    idx = jax.random.randint(jax.random.PRNGKey(1), (args.batch, T), 0, cfg.vocab_size)
    tgt = jax.random.randint(jax.random.PRNGKey(2), (args.batch, T), 0, cfg.vocab_size)
    cos, sin = llama.build_rope_cache(cfg, T)
    optimizer = optax.adamw(args.lr)

    if args.mode in ("sp", "pp", "ep"):
        assert args.quant is None, "--quant needs a TrainStep mode (not sp/pp/ep)"
        assert not args.fused_ce, "--fused-ce needs a TrainStep mode (not sp/pp/ep)"
        assert args.comm_combine_mb is None, "--comm-combine-mb needs a TrainStep mode (not sp/pp/ep)"
        assert not args.bucket, "--bucket needs a TrainStep mode (not sp/pp/ep)"
        # sequence / pipeline / expert parallelism drive the shard_map-based
        # training losses directly: jax.value_and_grad through the shard_map
        # (grad sync comes out of the broadcast transpose), optax update jitted
        # alongside — one compiled program per step, like TrainStep
        if args.mode == "sp":
            assert T % args.devices == 0, f"--seq {T} must divide over sp={args.devices}"
            mesh = dist.make_mesh({"sp": args.devices}, devices=devices)
            train_params = params
            sp_loss = dist.ulysses_gpt_loss if args.sp_impl == "ulysses" else dist.sp_gpt_loss

            def loss_fn(p, i, t):
                return sp_loss(p, i, t, cos, sin, cfg, mesh=mesh)
        elif args.mode == "pp":
            pp = args.devices
            assert cfg.n_layer % pp == 0, f"n_layer {cfg.n_layer} must divide over pp={pp}"
            # --accum-steps rides the GPipe schedule: more microbatches
            # per step IS pipeline-parallel gradient accumulation (the
            # bubble shrinks as k grows); clamped to a divisor of the batch
            from thunder_tpu.train import pp_microbatches

            n_micro = pp_microbatches(
                args.accum_steps if args.accum_steps > 1 else 2, args.batch
            )
            mesh = dist.make_mesh({"pp": pp}, devices=devices)
            train_params = dist.place_pipeline_params(dist.stack_blocks(params), mesh)

            def loss_fn(p, i, t):
                return dist.pp_gpt_loss(p, i, t, cos, sin, cfg, mesh=mesh, n_micro=n_micro)
        else:  # ep
            assert cfg.mlp_class == "LLaMAMoE", (
                f"--mode ep needs a MoE config (e.g. tiny-moe-debug, mixtral-like); got {cfg.name}"
            )
            assert args.batch % args.devices == 0, (
                f"--batch {args.batch} must divide over ep={args.devices}"
            )
            mesh = dist.make_mesh({"ep": args.devices}, devices=devices)
            train_params = params

            def loss_fn(p, i, t):
                return dist.ep_gpt_loss(p, i, t, cos, sin, cfg, mesh=mesh)

        opt_state = optimizer.init(train_params)

        @jax.jit
        def sharded_step(p, o, i, t):
            loss, grads = jax.value_and_grad(loss_fn)(p, i, t)
            updates, o = optimizer.update(grads, o, p)
            return optax.apply_updates(p, updates), o, loss

        step = lambda p, o, i, t, c, s: sharded_step(p, o, i, t)
        accumulate = None
        train_step_obj = None
        params = train_params
    else:
        if args.mode == "none":
            mesh = dist.make_mesh({"dp": 1}, devices=devices[:1])
            params = dist.ddp(params, mesh)
        elif args.mode == "ddp":
            mesh = dist.make_mesh({"dp": args.devices}, devices=devices)
            params = dist.ddp(params, mesh)
        elif args.mode in ("fsdp", "zero3"):
            mesh = dist.make_mesh({"fsdp": args.devices}, devices=devices)
            params = dist.fsdp(params, mesh)
        else:  # tp_fsdp
            tp = 2 if args.devices % 2 == 0 else 1
            mesh = dist.make_mesh({"fsdp": args.devices // tp, "tp": tp}, devices=devices)
            params = dist.tp_fsdp(params, mesh)

        def loss_fn(p, i, t, c, s):
            return llama.gpt_loss(p, i, t, c, s, cfg)

        remat_arg = (
            {"on": True, "off": False, "auto": "auto"}.get(args.remat, args.remat)
            if args.remat else not args.no_remat
        )
        train_step = dist.make_train_step(
            loss_fn, optimizer, mesh,
            remat=remat_arg,
            zero3=(args.mode == "zero3"),
            quant=args.quant, comm_combine_threshold_mb=args.comm_combine_mb,
            bucketer=llama.batch_bucketer(cfg) if args.bucket else None,
            accum_steps=args.accum_steps,
            overlap=args.overlap, overlap_bucket_mb=args.overlap_bucket_mb,
        )
        opt_state = train_step.init_optimizer_state(params)
        step = train_step
        accumulate = train_step.accumulate
        train_step_obj = train_step

    elastic = args.checkpoint_every > 0 or args.resume
    if elastic:
        assert args.checkpoint_dir, "--checkpoint-every/--resume need --checkpoint-dir"
        assert train_step_obj is not None, (
            "--checkpoint-every/--resume need a TrainStep mode (not sp/pp/ep)")
        assert args.accum == 1, "--checkpoint-every composes with --accum-steps, not --accum"

    t0 = time.perf_counter()
    if elastic:
        # the elastic loop is step-indexed: every step (including the first)
        # runs inside train_loop so a resumed run replays the exact same
        # step sequence — no out-of-band warmup step to desync the curve
        loss = None
    elif args.accum > 1:
        assert accumulate is not None, "--accum needs a TrainStep mode (not sp/pp/ep)"
        mb = args.batch // args.accum
        micro = [(idx[k * mb:(k + 1) * mb], tgt[k * mb:(k + 1) * mb], cos, sin) for k in range(args.accum)]
        params, opt_state, loss = accumulate(params, opt_state, micro)
    else:
        params, opt_state, loss = step(params, opt_state, idx, tgt, cos, sin)
    if loss is not None:
        jax.block_until_ready(loss)
        log(f"compile+first step: {time.perf_counter()-t0:.1f}s loss={float(loss):.4f}")

    # per-step telemetry (observability.telemetry.StepLogger): one JSONL
    # record per optimizer step, mirrored into the metrics registry.  The
    # peak-bytes estimate is static (del-aware liveness over the lowered
    # fw/bw traces), computed once — TrainStep modes only (sp/pp/ep drive
    # shard_map losses directly, no thunder trace to account)
    telemetry = None
    peak_bytes = None
    if args.telemetry:
        from thunder_tpu.observability.telemetry import StepLogger, trace_peak_bytes

        # run_start carries the FULL training config: a resumed run (or a
        # postmortem) must be able to reconstruct every knob from record 0
        telemetry = StepLogger(args.telemetry, meta={
            "config": cfg.name, "mode": args.mode, "devices": args.devices,
            "batch": args.batch, "seq": T, "dtype": args.dtype,
            "accum": args.accum, "quant": args.quant,
            "accum_steps": args.accum_steps,
            "remat": (args.remat or ("off" if args.no_remat else "on")),
            "overlap": bool(args.overlap),
            "overlap_bucket_mb": args.overlap_bucket_mb,
            "checkpoint_dir": args.checkpoint_dir,
            "checkpoint_every": args.checkpoint_every,
            "resume": bool(args.resume),
            "mesh_axes": dict(mesh.shape),
            "lr": args.lr,
        })
        if getattr(train_step_obj, "fw_trace", None) is not None:
            peak_bytes = max(
                trace_peak_bytes(train_step_obj.fw_trace),
                trace_peak_bytes(train_step_obj.bw_trace),
            )
        log(f"telemetry -> {args.telemetry}"
            + (f" (peak_bytes_estimate={peak_bytes})" if peak_bytes else ""))

    t0 = time.perf_counter()
    restarts = resumed_from = None
    if elastic:
        from thunder_tpu.observability.telemetry import trace_peak_bytes as _tpb
        from thunder_tpu.train import AsyncCheckpointer, restore_latest, train_loop

        # the config fingerprint in each manifest: resuming under silently
        # different knobs is a divergence, not a resume
        train_config = {"config": cfg.name, "mode": args.mode,
                        "devices": args.devices, "batch": args.batch, "seq": T,
                        "dtype": args.dtype, "accum_steps": args.accum_steps,
                        "lr": args.lr}
        start_step = 0
        if args.resume:
            got = restore_latest(args.checkpoint_dir,
                                 {"params": params, "opt_state": opt_state},
                                 config=train_config)
            if got is not None:
                start_step, state = got
                params, opt_state = state["params"], state["opt_state"]
                log(f"resumed from committed checkpoint step {start_step}")
            else:
                log("no committed checkpoint found; starting from scratch")
        resumed_from = start_step if args.resume else None

        t_prev = [time.perf_counter()]
        peak_holder = [peak_bytes]

        def on_step(s, loss_s):
            now = time.perf_counter()
            if telemetry is not None:
                if peak_holder[0] is None and getattr(train_step_obj, "fw_trace", None) is not None:
                    peak_holder[0] = max(_tpb(train_step_obj.fw_trace),
                                         _tpb(train_step_obj.bw_trace))
                telemetry.log_step(
                    s, loss=float(loss_s), step_time_s=now - t_prev[0],
                    tokens=args.batch * T, peak_bytes=peak_holder[0],
                )
            t_prev[0] = now

        with AsyncCheckpointer(args.checkpoint_dir, config=train_config) as ck:
            res = train_loop(
                step, params, opt_state, lambda s: (idx, tgt, cos, sin),
                steps=args.steps, start_step=start_step,
                checkpointer=ck, checkpoint_every=args.checkpoint_every,
                on_step=on_step,
            )
        params, opt_state = res.params, res.opt_state
        last = res.losses[-1] if res.losses and res.losses[-1] is not None else float("nan")
        restarts = res.restarts
        steps_done = max(args.steps - start_step, 1)
        jax.block_until_ready(last)
        dt = time.perf_counter() - t0
    else:
        last = loss
        for k in range(args.steps):
            t_step = time.perf_counter()
            if args.accum > 1:
                params, opt_state, last = accumulate(params, opt_state, micro)
            else:
                params, opt_state, last = step(params, opt_state, idx, tgt, cos, sin)
            if telemetry is not None:
                jax.block_until_ready(last)
                gn = None
                if args.telemetry_grad_norm and train_step_obj is not None and args.accum == 1:
                    import optax as _optax

                    _, g = train_step_obj.grads(params, opt_state, idx, tgt, cos, sin)
                    gn = float(_optax.global_norm(g))
                telemetry.log_step(
                    k,
                    loss=float(last),
                    grad_norm=gn,
                    step_time_s=time.perf_counter() - t_step,
                    tokens=args.batch * T,
                    peak_bytes=peak_bytes,
                )
        jax.block_until_ready(last)
        dt = time.perf_counter() - t0
        steps_done = args.steps
    if telemetry is not None:
        telemetry.close()
    tps = args.batch * T * steps_done / dt

    if args.checkpoint_dir and not elastic:
        from thunder_tpu.distributed import save_checkpoint

        save_checkpoint(args.checkpoint_dir, {"params": params, "opt_state": opt_state}, step=args.steps)
        log(f"checkpoint saved to {args.checkpoint_dir}")

    print(json.dumps({
        "config": cfg.name, "mode": args.mode, "devices": args.devices,
        "quant": args.quant,
        "fused_ce": bool(args.fused_ce),
        "accum_steps": args.accum_steps,
        "remat": (args.remat or ("off" if args.no_remat else "on")),
        "overlap": bool(args.overlap),
        "checkpoint_every": args.checkpoint_every,
        "resumed_from": resumed_from, "restarts": restarts,
        "tokens_per_sec": round(tps, 1), "ms_per_step": round(dt / steps_done * 1e3, 2),
        "final_loss": round(float(last), 4),
    }))


if __name__ == "__main__":
    main()
